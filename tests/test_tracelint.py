"""tracelint test suite (ISSUE 5): per-rule fixtures — true positive,
true negative, suppressed — plus the tier-1 CI gate: a self-run over
``mxnet_tpu/`` must be clean, and a synthetic ``float(loss)`` seeded
into a fused-step body must fail it.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.tracelint import run_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def lint(tmp_path, source, name="snippet.py", **kw):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_paths([str(p)], **kw)


def rules_of(findings):
    return [f.rule for f in findings]


def cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.tracelint"] + args,
        capture_output=True, text=True, cwd=cwd, env=_ENV)


# ------------------------------------------------------------------ #
# TL001 — host sync inside traced code
# ------------------------------------------------------------------ #

class TestTL001HostSync:
    def test_float_in_jitted_fn(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "float" in fs[0].message and "step" in fs[0].message

    def test_item_via_callgraph_helper(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def helper(x):
                return x.item()

            def step(x):
                return helper(x)

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "helper" in fs[0].message

    def test_branch_on_traced_array(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            def step(x):
                s = jnp.sum(x)
                if s > 0:
                    return x
                return -x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "branches on a traced array" in fs[0].message

    def test_numpy_materialization_in_trace_scope(self, tmp_path):
        fs = lint(tmp_path, """
            import numpy as onp
            from mxnet_tpu.gluon.block import trace_scope

            def run(key, vals):
                with trace_scope(key, True) as aux:
                    host = onp.asarray(vals[0])
                return host
        """)
        assert rules_of(fs) == ["TL001"]
        assert "onp.asarray" in fs[0].message

    def test_true_negatives(self, tmp_path):
        # host work outside the traced region, trace-time python on
        # hyperparameters/shapes, identity tests: all fine
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            def host_metric(x):
                return float(x)  # never traced

            class Rule:
                momentum = 0.0

                def step(self, w, g, state):
                    n = float(w.shape[0])
                    if self.momentum == 0.0:
                        return w - g / n
                    if state is None:
                        state = jnp.zeros_like(w)
                    return w + self.momentum * state - g / n

            def outer(w, g, s):
                return Rule().step(w, g, s)

            fn = jax.jit(outer)
        """)
        assert fs == []

    def test_suppressed_with_reason(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)  # tracelint: disable=TL001 -- test fixture
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert fs == []

    def test_suppression_without_reason_is_tl000_and_keeps_finding(
            self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)  # tracelint: disable=TL001
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert sorted(rules_of(fs)) == ["TL000", "TL001"]


# ------------------------------------------------------------------ #
# TL002 — donated buffer read after dispatch
# ------------------------------------------------------------------ #

class TestTL002Donation:
    def test_read_after_donating_dispatch(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                out = fn(w, g)
                return w + out
        """)
        assert rules_of(fs) == ["TL002"]
        assert "`w`" in fs[0].message

    def test_producer_method_indirection(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            class Step:
                def _make(self):
                    return jax.jit(add, donate_argnums=(1,))

                def run(self, w, g):
                    fn = self._make()
                    out = fn(w, g)
                    return g + out
        """)
        assert rules_of(fs) == ["TL002"]
        assert "`g`" in fs[0].message

    def test_rebind_from_result_is_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                w = fn(w, g)
                return w + 1
        """)
        assert fs == []

    def test_phase_polymorphic_producer_intersects(self, tmp_path):
        # the FusedStep._compile regression: a compiler returning
        # different jits per phase must not union donated positions
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            class Step:
                def _make(self, phase):
                    if phase == "micro":
                        return jax.jit(add, donate_argnums=(0,))
                    return jax.jit(add, donate_argnums=(1,))

                def run(self, w, g):
                    fn = self._make("micro")
                    out = fn(w, g)
                    return w + g + out
        """)
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                out = fn(w, g)
                return w + out  # tracelint: disable=TL002 -- fixture
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL003 — retrace hazards
# ------------------------------------------------------------------ #

class TestTL003Retrace:
    def test_list_in_cache_key(self, tmp_path):
        fs = lint(tmp_path, """
            def lookup(cache, shape):
                opts = [shape]
                key = (shape, opts)
                return cache.get(key)
        """)
        assert rules_of(fs) == ["TL003"]
        assert "a list" in fs[0].message

    def test_lambda_and_id_keys(self, tmp_path):
        fs = lint(tmp_path, """
            def store(cache, f, shape):
                cache[(shape, lambda x: x)] = 1
                cache[(id(f), shape)] = 2
        """)
        assert sorted(rules_of(fs)) == ["TL003", "TL003"]
        msgs = " ".join(f.message for f in fs)
        assert "lambda" in msgs and "identity key" in msgs

    def test_jit_inside_loop(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def build(fns):
                outs = []
                for f in fns:
                    outs.append(jax.jit(f))
                return outs
        """)
        assert "TL003" in rules_of(fs)
        assert "inside a loop" in fs[0].message

    def test_hashable_key_and_hoisted_jit_are_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def get(cache, arr, training, hyper_key):
                key = (tuple(arr.shape), str(arr.dtype), training,
                       hyper_key)
                fn = cache.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x + 1)
                    cache[key] = fn
                return fn
        """)
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            def store(cache, f, shape):
                # bounded registry, evicted on pickle:
                # tracelint: disable=TL003 -- fixture justification
                cache[(id(f), shape)] = 2
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL004 — lock discipline
# ------------------------------------------------------------------ #

class TestTL004Locks:
    def test_unlocked_mutation_of_protected_field(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    self._items.clear()
        """)
        assert rules_of(fs) == ["TL004"]
        assert "_items" in fs[0].message and "drop" in fs[0].message

    def test_lock_order_inversion(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class AB:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._x = 0

                def one(self):
                    with self._a:
                        with self._b:
                            self._x = 1

                def two(self):
                    with self._b:
                        with self._a:
                            self._x = 2
        """)
        assert rules_of(fs) == ["TL004"]
        assert "inversion" in fs[0].message

    def test_consistent_locking_and_init_are_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []      # pre-sharing: exempt

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    with self._lock:
                        self._items.clear()

                def peek(self):
                    return len(self._items)  # read, not mutation
        """)
        assert fs == []

    def test_module_level_lock(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            _lock = threading.Lock()
            _registry = {}

            def put(k, v):
                with _lock:
                    _registry[k] = v

            def drop(k):
                _registry.pop(k)
        """)
        assert rules_of(fs) == ["TL004"]
        assert "_registry" in fs[0].message

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    self._items.clear()  # tracelint: disable=TL004 -- fixture
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL005 — env-hatch registry
# ------------------------------------------------------------------ #

class TestTL005EnvRegistry:
    def _docs(self, tmp_path):
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        f = d / "ENV_VARS.md"
        f.write_text("| Variable | Default | Effect |\n|---|---|---|\n"
                     "| `MXNET_DOCUMENTED` | 1 | real |\n"
                     "| `MXNET_STALE` | 1 | nobody reads me |\n")
        return str(f)

    def test_undocumented_read_and_stale_row(self, tmp_path):
        docs = self._docs(tmp_path)
        fs = lint(tmp_path, """
            import os

            a = os.environ.get("MXNET_DOCUMENTED", "1")
            b = os.environ.get("MXNET_SECRET", "0")
        """, env_docs=docs)
        assert sorted(rules_of(fs)) == ["TL005", "TL005"]
        msgs = " ".join(f.message for f in fs)
        assert "MXNET_SECRET" in msgs and "MXNET_STALE" in msgs
        assert "MXNET_DOCUMENTED" not in msgs

    def test_registered_and_documented_is_clean(self, tmp_path):
        d = tmp_path / "docs"
        d.mkdir()
        (d / "ENV_VARS.md").write_text("| `MXNET_IGNORED_COMPAT` | 1 | "
                                       "accepted, no-op |\n")
        fs = lint(tmp_path, """
            from mxnet_tpu.base import register_env

            register_env("MXNET_IGNORED_COMPAT", 1, "no-op")
        """, env_docs=str(d / "ENV_VARS.md"))
        assert fs == []

    def test_prose_mentions_are_not_documentation(self, tmp_path):
        # a var named in a row's PROSE cell (not the first cell) is a
        # reference, not a doc row — it must not mask a stale/missing row
        d = tmp_path / "docs"
        d.mkdir()
        (d / "ENV_VARS.md").write_text(
            "| `MXNET_REAL` | 1 | replaces `MXNET_LEGACY_PROSE` |\n")
        fs = lint(tmp_path, """
            import os

            a = os.environ.get("MXNET_REAL")
        """, env_docs=str(d / "ENV_VARS.md"))
        assert fs == []


# ------------------------------------------------------------------ #
# the tier-1 gate: self-run, seeded violation, baseline
# ------------------------------------------------------------------ #

class TestGate:
    def test_self_run_is_clean(self):
        """THE CI gate: tracelint over the library AND the tooling and
        benchmark layers — and the runnable example fixtures — must
        stay clean at merge: a regression in trace/sharding discipline
        fails tier-1.  Runs with --jobs to exercise the parallel path
        in CI."""
        r = cli(["mxnet_tpu/", "tools/", "benchmark/",
                 "tests/fixtures/", "--jobs", "2", "--format=json"])
        assert r.returncode == 0, f"tracelint found:\n{r.stdout}\n{r.stderr}"
        payload = json.loads(r.stdout)
        assert payload["findings"] == []

    def test_no_reasonless_suppressions_repo_wide(self):
        """Every `# tracelint: disable=` in the repo — library, tools,
        benchmarks, tests, examples — carries a justification (zero
        TL000s), so nothing is suppressed silently."""
        r = cli(["mxnet_tpu/", "tools/", "benchmark/", "tests/",
                 "example/", "bench.py", "--select", "TL000",
                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_seeded_float_loss_fails_gate(self, tmp_path):
        """Acceptance check: a synthetic host sync in a fused-step body
        is caught (the analyzer sees through jax.jit(apply, ...))."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "gluon", "fused_step.py")).read()
        needle = ("            outs, grads, new_frozen = "
                  "pure(key, train_vals, frozen_vals,\n")
        assert needle in src
        seeded = src.replace(
            needle, needle.rstrip("\n") + "\n                loss_val = "
            "float(outs[0])  # seeded violation\n", 1)
        bad = tmp_path / "fused_step_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--format=json"])
        assert r.returncode == 1
        payload = json.loads(r.stdout)
        assert any(f["rule"] == "TL001" and "float" in f["message"]
                   for f in payload["findings"])

    def test_seeded_axis_mismatch_fails_gate(self, tmp_path):
        """Acceptance check: an axis-name literal drifted away from the
        collectives' axis vocabulary is caught (TL006)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "parallel", "collectives.py")).read()
        needle = "        return jax.lax.psum(contrib, axis)"
        assert needle in src
        seeded = src.replace(
            needle, '        return jax.lax.psum(contrib, "dcn")', 1)
        bad = tmp_path / "collectives_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--format=json"])
        assert r.returncode == 1
        hits = [f for f in json.loads(r.stdout)["findings"]
                if f["rule"] == "TL006"]
        assert hits and "'dcn'" in hits[0]["message"]
        assert hits[0]["severity"] == "error"

    def test_seeded_conditional_collective_fails_gate(self, tmp_path):
        """Acceptance check: a collective gated on jax.process_index()
        inside the pipeline's traced shard body is caught (TL008)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "parallel", "pipeline.py")).read()
        needle = "        my = lax.axis_index(axis)\n"
        assert needle in src
        seeded = src.replace(
            needle, needle +
            "        if jax.process_index() == 0:\n"
            "            xs_local = lax.psum(xs_local, axis)\n", 1)
        bad = tmp_path / "pipeline_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--select", "TL008", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any("psum" in f["message"] and
                   "host-dependent" in f["message"] for f in hits)

    def test_baseline_lands_rule_warn_only(self, tmp_path):
        """--baseline lets a future rule land without failing the gate:
        recorded fingerprints are ignored, fresh findings are not."""
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            def step(w, g):
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """))
        base = tmp_path / "baseline.json"
        r = cli([str(bad), "--write-baseline", str(base)])
        assert r.returncode == 0 and base.exists()
        r = cli([str(bad), "--baseline", str(base)])
        assert r.returncode == 0, r.stdout
        # a NEW violation is still caught through the same baseline
        bad.write_text(bad.read_text().replace(
            "return w - lr * g", "return w - lr * g.item()"))
        r = cli([str(bad), "--baseline", str(base), "--format=json"])
        assert r.returncode == 1
        assert any(f["rule"] == "TL001" and "item" in f["message"]
                   for f in json.loads(r.stdout)["findings"])

    def test_select_restricts_rules(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            def step(w, g):
                return w - float(g) * g

            fn = jax.jit(step)
        """))
        assert cli([str(bad), "--select", "TL004"]).returncode == 0
        assert cli([str(bad), "--select", "TL001"]).returncode == 1
        assert cli([str(bad), "--select", "TL999"]).returncode == 2


class TestReviewRegressions:
    """Post-review regression net: partial-tree TL005, nested-class
    TL004 attribution, suppression markers inside string literals."""

    def test_single_file_lint_has_no_stale_doc_false_positives(self):
        # the natural lint-the-file-I-edited workflow: env vars read
        # elsewhere in the repo must not be reported as stale doc rows
        r = cli(["mxnet_tpu/gluon/data/dataloader.py", "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_nested_class_owns_its_own_lock_discipline(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Outer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                class Inner:  # unrelated single-threaded helper
                    def __init__(self):
                        self._items = []

                    def drop(self):
                        self._items.clear()
        """)
        assert fs == []

    def test_suppression_marker_inside_string_is_not_a_suppression(
            self, tmp_path):
        # core.py's own TL000 help text quotes the syntax; a string
        # must neither raise TL000 nor suppress the next line
        fs = lint(tmp_path, """
            import jax

            HELP = "write '# tracelint: disable=TLxxx -- reason'"

            def step(w, g):
                msg = "see '# tracelint: disable=TL001 -- like this'"
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]

    def test_self_lint_of_tracelint_itself(self):
        # the analyzer's own sources (which quote the suppression
        # syntax in strings/docstrings) must lint clean
        r = cli(["tools/tracelint/", "--format=json"])
        assert r.returncode == 0, r.stdout


# ------------------------------------------------------------------ #
# cross-module call-graph resolution (ISSUE 11 engine upgrade)
# ------------------------------------------------------------------ #

def lint_tree(tmp_path, files, **kw):
    for name, source in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(source))
    return run_paths([str(tmp_path)], **kw)


class TestCrossModuleEngine:
    def test_tl001_reaches_host_sync_two_modules_away(self, tmp_path):
        """THE regression pin for the repo-wide engine: the jit seed in
        a.py propagates through b.py into c.py's host sync."""
        fs = lint_tree(tmp_path, {
            "a.py": """
                import jax
                from b import step

                fn = jax.jit(step)
            """,
            "b.py": """
                from c import helper

                def step(x):
                    return helper(x)
            """,
            "c.py": """
                def helper(x):
                    return x.item()
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("c.py")
        assert "helper" in fs[0].message

    def test_from_import_aliasing(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "a.py": """
                import jax
                from b import step as entry

                fn = jax.jit(entry)
            """,
            "b.py": """
                def step(x):
                    return float(x)
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("b.py")

    def test_module_dotted_seed(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "a.py": """
                import jax
                import b

                fn = jax.jit(b.step)
            """,
            "b.py": """
                def step(x):
                    return x.asnumpy()
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("b.py")

    def test_relative_import_chain_in_package(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/a.py": """
                import jax
                from .b import step

                fn = jax.jit(step)
            """,
            "pkg/b.py": """
                from .c import helper

                def step(x):
                    return helper(x)
            """,
            "pkg/c.py": """
                def helper(x):
                    return x.tolist()
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith(os.path.join("pkg", "c.py"))

    def test_reexport_through_package_init(self, tmp_path):
        # `from pkg import helper` where pkg/__init__ re-exports it
        fs = lint_tree(tmp_path, {
            "pkg/__init__.py": "from .impl import helper\n",
            "pkg/impl.py": """
                def helper(x):
                    return x.item()
            """,
            "main.py": """
                import jax
                from pkg import helper

                def step(x):
                    return helper(x)

                fn = jax.jit(step)
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("impl.py")

    def test_diamond_imports_flag_once(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "base.py": """
                def helper(x):
                    return x.item()
            """,
            "left.py": """
                from base import helper

                def via_left(x):
                    return helper(x)
            """,
            "right.py": """
                from base import helper

                def via_right(x):
                    return helper(x)
            """,
            "top.py": """
                import jax
                from left import via_left
                from right import via_right

                def step(x):
                    return via_left(x) + via_right(x)

                fn = jax.jit(step)
            """})
        assert rules_of(fs) == ["TL001"]  # one finding, not two
        assert fs[0].path.endswith("base.py")

    def test_unresolvable_import_falls_back_to_module_local(
            self, tmp_path):
        # an import the project can't see contributes no edges; the
        # module-local walk still catches the local violation
        fs = lint_tree(tmp_path, {
            "a.py": """
                import jax
                from some_external_dep import helper

                def step(x):
                    y = helper(x)
                    return float(y)

                fn = jax.jit(step)
            """})
        assert rules_of(fs) == ["TL001"]
        assert "float" in fs[0].message

    def test_class_method_resolution_across_modules(self, tmp_path):
        # ancestor direction: traced Sub.step calls self.helper defined
        # on a base class imported from another module
        fs = lint_tree(tmp_path, {
            "base_mod.py": """
                class Base:
                    def helper(self, x):
                        return x.item()
            """,
            "sub_mod.py": """
                import jax
                from base_mod import Base

                class Sub(Base):
                    @jax.jit
                    def step(self, x):
                        return self.helper(x)
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("base_mod.py")

    def test_subclass_override_across_modules(self, tmp_path):
        # descendant direction: traced Base.run calls self.rule, which
        # a subclass in ANOTHER module overrides with a host sync (the
        # optimizer-registry pattern, now cross-file)
        fs = lint_tree(tmp_path, {
            "base_mod.py": """
                import jax

                class Base:
                    @jax.jit
                    def run(self, x):
                        return self.rule(x)

                    def rule(self, x):
                        return x
            """,
            "sub_mod.py": """
                from base_mod import Base

                class Sub(Base):
                    def rule(self, x):
                        return float(x)
            """})
        assert rules_of(fs) == ["TL001"]
        assert fs[0].path.endswith("sub_mod.py")

    def test_partial_wrapped_seed(self, tmp_path):
        # shard_map(partial(fn, ...)) traces fn
        fs = lint_tree(tmp_path, {
            "a.py": """
                import jax
                from functools import partial

                def body(v, flag):
                    return v.item()

                fn = jax.shard_map(partial(body, flag=True), mesh=None,
                                   in_specs=None, out_specs=None)
            """})
        assert rules_of(fs) == ["TL001"]

    def test_local_variable_sharing_a_module_name_stays_unresolved(
            self, tmp_path):
        # review regression: `bench = Bench(); bench.run(x)` must NOT
        # resolve into a lint module named bench.py — a plain variable
        # receiver is not an import binding
        fs = lint_tree(tmp_path, {
            "bench.py": """
                def run(x):
                    return float(x)
            """,
            "a.py": """
                import jax
                from somewhere import Bench

                def step(x):
                    bench = Bench()
                    return bench.run(x)

                fn = jax.jit(step)
            """})
        assert fs == []

    def test_symbol_abstract_eval_does_not_trace_invoke(self):
        """Regression for the cross-module finding fixed in this PR:
        symbol's eval_shape bodies route through _node_outputs_abstract
        (raw opref.fn), NOT _registry.invoke, so the imperative
        machinery (profiler clocks, NaiveEngine block_until_ready, env
        hatches via is_naive_engine) is no longer trace-reachable."""
        r = cli(["mxnet_tpu/symbol/symbol.py", "mxnet_tpu/ops/registry.py",
                 "mxnet_tpu/base.py", "--select", "TL001,TL007",
                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []


# ------------------------------------------------------------------ #
# TL006 — axis/mesh discipline
# ------------------------------------------------------------------ #

class TestTL006AxisDiscipline:
    def test_unknown_axis_cross_module_is_error(self, tmp_path):
        # the binding mesh lives in one module, the drifted literal in
        # another — the exact seam the module-local engine missed
        fs = lint_tree(tmp_path, {
            "mesh_mod.py": """
                import numpy as onp
                from jax.sharding import Mesh

                MESH = Mesh(onp.arange(4), ("dp",))
            """,
            "use_mod.py": """
                from jax import lax

                def reduce_grads(g):
                    return lax.psum(g, "pd")
            """})
        assert rules_of(fs) == ["TL006"]
        assert fs[0].severity == "error"
        assert "'pd'" in fs[0].message and fs[0].path.endswith("use_mod.py")

    def test_bound_axis_is_clean(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "mesh_mod.py": """
                import numpy as onp
                from jax.sharding import Mesh

                MESH = Mesh(onp.arange(8).reshape(4, 2), ("dp", "tp"))
            """,
            "use_mod.py": """
                from jax import lax
                from jax.sharding import PartitionSpec

                def reduce_grads(g):
                    return lax.psum(g, "tp")

                SPEC = PartitionSpec("dp", None)
            """})
        assert fs == []

    def test_param_default_only_axis_literal_is_warn(self, tmp_path):
        # 'sp' exists only as a default-axis parameter: a literal use is
        # conditionally bound (depends on the caller's mesh) — warn
        fs = lint_tree(tmp_path, {
            "api.py": """
                from jax import lax

                def ring_pass(x, axis="sp"):
                    return lax.ppermute(x, axis_name=axis, perm=[])
            """,
            "use.py": """
                from jax import lax

                def fold(x):
                    return lax.psum(x, "sp")
            """})
        assert rules_of(fs) == ["TL006"]
        assert fs[0].severity == "warn"
        assert "conditionally bound" in fs[0].message

    def test_make_mesh_dict_binds_axes(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "a.py": """
                from jax import lax
                from mylib import make_mesh

                MESH = make_mesh({"dp": 4, "sp": 2})

                def fold(x):
                    return lax.psum(x, ("dp", "sp"))
            """})
        assert fs == []

    def test_partition_spec_unknown_axis(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "a.py": """
                import numpy as onp
                from jax.sharding import Mesh, PartitionSpec as P

                MESH = Mesh(onp.arange(4), ("dp",))
                SPEC = P("model", None)
            """})
        assert rules_of(fs) == ["TL006"]
        assert "PartitionSpec" in fs[0].message
        assert "'model'" in fs[0].message

    def test_gather_axis_kwarg_does_not_shadow_axis_name(self, tmp_path):
        # review regression: all_gather's axis= kwarg is the INTEGER
        # array dim; the positional axis NAME must still be checked
        fs = lint_tree(tmp_path, {
            "mesh_mod.py": """
                import numpy as onp
                from jax.sharding import Mesh

                MESH = Mesh(onp.arange(4), ("dp",))
            """,
            "use_mod.py": """
                from jax import lax

                def gather(x):
                    return lax.all_gather(x, "dcn", axis=0, tiled=True)
            """})
        assert rules_of(fs) == ["TL006"]
        assert "'dcn'" in fs[0].message

    def test_suppressed(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "a.py": """
                from jax import lax

                def fold(x):
                    # tracelint: disable=TL006 -- fixture: axis bound by caller's test mesh
                    return lax.psum(x, "zz")
            """})
        assert fs == []


# ------------------------------------------------------------------ #
# TL007 — cross-host trace divergence
# ------------------------------------------------------------------ #

class TestTL007HostDivergence:
    def test_process_index_feeding_return(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(x):
                r = jax.process_index()
                return x + r

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL007"]
        assert "process_index" in fs[0].message

    def test_environ_branching_the_trace(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import os

            def step(x):
                if os.environ.get("MXNET_DEBUG_SCALE"):
                    return x * 2
                return x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL007"]
        assert "environ" in fs[0].message

    def test_host_rng_feeding_jax_call(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import numpy as onp

            def step(x):
                key = jax.random.PRNGKey(onp.random.randint(0, 100))
                return x + jax.random.uniform(key, x.shape)

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL007"]
        assert "host RNG" in fs[0].message

    def test_from_imported_host_reads_are_caught(self, tmp_path):
        # review regression: `from os import getenv` / `from time
        # import perf_counter` classify the same as the dotted forms
        fs = lint(tmp_path, """
            import jax
            from os import getenv

            def step(x):
                if getenv("MXNET_DEBUG_SCALE"):
                    return x * 2
                return x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL007"]

    def test_project_module_named_random_is_not_stdlib(self, tmp_path):
        # `from pkg import random` binds a PROJECT module; its draws are
        # jax-keyed, not host RNG — must not classify as stdlib random
        fs = lint_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/random.py": """
                def uniform(key, shape):
                    return shape
            """,
            "pkg/use.py": """
                import jax
                from . import random

                def step(x):
                    return x + random.uniform(None, x.shape)

                fn = jax.jit(step)
            """})
        assert [f for f in fs if f.rule == "TL007"] == []

    def test_host_side_timer_is_not_divergence(self, tmp_path):
        # a profiler clock whose value never feeds the trace (the
        # registry.invoke pattern): no finding
        fs = lint(tmp_path, """
            import jax
            import time

            def log_ms(dt):
                pass

            def step(x):
                t0 = time.perf_counter()
                y = x + 1
                if t0 is not None:
                    log_ms(time.perf_counter() - t0)
                return y

            fn = jax.jit(step)
        """)
        assert fs == []

    def test_process_index_outside_trace_is_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def rank():
                return jax.process_index()
        """)
        assert fs == []

    def test_donate_argnums_from_set_iteration(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def f(a, b):
                return a + b

            fn = jax.jit(f, donate_argnums=tuple({0, 1}))
        """)
        assert rules_of(fs) == ["TL007"]
        assert "donate_argnums" in fs[0].message

    def test_sorted_set_is_stable(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def f(a, b):
                return a + b

            fn = jax.jit(f, donate_argnums=tuple(sorted({0, 1})))
        """)
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import os

            def step(x):
                # tracelint: disable=TL007 -- fixture: launcher propagates env
                if os.environ.get("MXNET_DEBUG_SCALE"):
                    return x * 2
                return x

            fn = jax.jit(step)
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL008 — conditional collectives
# ------------------------------------------------------------------ #

class TestTL008ConditionalCollective:
    def test_collective_under_data_dependent_branch(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp
            from jax import lax

            def body(v):
                s = jnp.sum(v)
                if s > 0:
                    v = lax.psum(v, "dp")
                return v

            fn = jax.shard_map(body, mesh=None, in_specs=None,
                               out_specs=None)
        """, select=["TL008"])
        assert rules_of(fs) == ["TL008"]
        assert "data-dependent" in fs[0].message
        assert "psum" in fs[0].message

    def test_collective_under_host_dependent_branch(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from jax import lax

            def body(v):
                if jax.process_index() == 0:
                    v = lax.psum(v, "dp")
                return v

            fn = jax.shard_map(body, mesh=None, in_specs=None,
                               out_specs=None)
        """, select=["TL008"])
        assert rules_of(fs) == ["TL008"]
        assert "host-dependent" in fs[0].message

    def test_collective_under_static_config_branch_is_fine(
            self, tmp_path):
        # a trace-time hyperparameter branch is uniform across shards
        fs = lint(tmp_path, """
            import jax
            from jax import lax

            def make(reduce_grads):
                def body(v):
                    if reduce_grads:
                        v = lax.psum(v, "dp")
                    return v
                return jax.shard_map(body, mesh=None, in_specs=None,
                                     out_specs=None)
        """, select=["TL008"])
        assert fs == []

    def test_collective_in_loop_is_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from jax import lax

            def body(v):
                for i in range(4):
                    v = lax.ppermute(v, "sp", [(0, 1), (1, 0)])
                return v

            fn = jax.shard_map(body, mesh=None, in_specs=None,
                               out_specs=None)
        """, select=["TL008"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp
            from jax import lax

            def body(v):
                s = jnp.sum(v)
                if s > 0:
                    # tracelint: disable=TL008 -- fixture justification
                    v = lax.psum(v, "dp")
                return v

            fn = jax.shard_map(body, mesh=None, in_specs=None,
                               out_specs=None)
        """, select=["TL008"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL009 — accountant discipline
# ------------------------------------------------------------------ #

class TestTL009AccountantDiscipline:
    def test_set_without_drop(self, tmp_path):
        fs = lint(tmp_path, """
            from mxnet_tpu.telemetry.memory import ACCOUNTANT

            def hold(key, tree):
                ACCOUNTANT.set("serve.scratch", key, tree)
        """, select=["TL009"])
        assert rules_of(fs) == ["TL009"]
        assert "serve.scratch" in fs[0].message

    def test_drop_in_another_module_pairs(self, tmp_path):
        # the release path may live across the repo (Trainer sets,
        # FusedStep drops) — project-wide pairing, no finding
        fs = lint_tree(tmp_path, {
            "a.py": """
                from mxnet_tpu.telemetry.memory import ACCOUNTANT

                def hold(key, tree):
                    ACCOUNTANT.set("serve.scratch", key, tree)
            """,
            "b.py": """
                from mxnet_tpu.telemetry.memory import ACCOUNTANT

                def release(key):
                    ACCOUNTANT.drop_deferred("serve.scratch", key)
            """}, select=["TL009"])
        assert fs == []

    def test_dynamic_subsystem_is_skipped(self, tmp_path):
        fs = lint(tmp_path, """
            from mxnet_tpu.telemetry.memory import ACCOUNTANT

            def hold(subsystem, key, tree):
                ACCOUNTANT.set(subsystem, key, tree)
        """, select=["TL009"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            from mxnet_tpu.telemetry.memory import ACCOUNTANT

            def hold(key, tree):
                # tracelint: disable=TL009 -- fixture: process-lifetime entry
                ACCOUNTANT.set("proc.forever", key, tree)
        """, select=["TL009"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL010 — stale suppressions (opt-in)
# ------------------------------------------------------------------ #

class TestTL010StaleSuppressions:
    SRC = """
        import jax

        def step(w, g):
            lr = float(g)  # tracelint: disable=TL001 -- epoch sync fixture
            return w - lr * g

        def host_only(x):
            return x + 1  # tracelint: disable=TL002 -- stale: nothing fires here

        fn = jax.jit(step)
    """

    def test_stale_suppression_reported_on_select(self, tmp_path):
        fs = lint(tmp_path, self.SRC, select=["TL010"])
        assert rules_of(fs) == ["TL010"]
        assert "TL002" in fs[0].message
        assert fs[0].severity == "warn"

    def test_live_suppression_not_reported(self, tmp_path):
        fs = lint(tmp_path, self.SRC, select=["TL010"])
        assert all("TL001" not in f.message for f in fs)

    def test_not_reported_by_default(self, tmp_path):
        fs = lint(tmp_path, self.SRC)
        assert fs == []

    def test_repo_has_no_stale_suppressions(self):
        r = cli(["mxnet_tpu/", "tools/", "benchmark/", "--select",
                 "TL010", "--format=json"])
        assert json.loads(r.stdout)["findings"] == []


# ------------------------------------------------------------------ #
# TL011 — clock discipline
# ------------------------------------------------------------------ #

class TestTL011ClockDiscipline:
    def test_wall_clock_deadline_math(self, tmp_path):
        fs = lint(tmp_path, """
            import time

            def close(timeout=60.0):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    pass
        """, select=["TL011"])
        assert set(rules_of(fs)) == {"TL011"}
        # one finding per defect: the assignment's BinOp hit subsumes
        # the stored-into hit, the while-compare is the second defect
        assert len(fs) == 2
        msgs = " ".join(f.message for f in fs)
        assert "monotonic" in msgs and "timeout" in msgs

    def test_wall_clock_into_timeout_kwarg(self, tmp_path):
        fs = lint(tmp_path, """
            import time

            def wait_for(ev):
                ev.wait(timeout=time.time())
        """, select=["TL011"])
        assert rules_of(fs) == ["TL011"]
        assert "timeout=" in fs[0].message

    def test_from_imported_time_classifies(self, tmp_path):
        fs = lint(tmp_path, """
            from time import time

            def budget(timeout):
                return time() + timeout
        """, select=["TL011"])
        assert rules_of(fs) == ["TL011"]

    def test_elapsed_logging_is_exempt(self, tmp_path):
        # the event_handler.py / callback.py / telemetry-timestamp
        # exemption: wall-clock elapsed that only feeds logging
        fs = lint(tmp_path, """
            import time

            def log(x):
                pass

            class Speedometer:
                def __init__(self, batch_size):
                    self.batch_size = batch_size
                    self.tic = time.time()

                def __call__(self, count):
                    speed = count * self.batch_size / (
                        time.time() - self.tic)
                    log(speed)
                    self.tic = time.time()

            def stamp(fields):
                return {"ts": round(time.time(), 6), **fields}
        """, select=["TL011"])
        assert fs == []

    def test_monotonic_deadlines_are_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import time

            def close(timeout=60.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    pass
        """, select=["TL011"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import time

            def lease(timeout):
                # tracelint: disable=TL011 -- fixture: protocol wants wall-clock epoch
                return time.time() + timeout
        """, select=["TL011"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL012 — finalizer lock safety
# ------------------------------------------------------------------ #

class TestTL012FinalizerLocks:
    def test_del_reaches_lock_through_helper(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def close(self):
                    with self._lock:
                        self._items.clear()

                def __del__(self):
                    self.close()
        """, select=["TL012"])
        assert rules_of(fs) == ["TL012"]
        assert "__del__" in fs[0].message and "Lock" in fs[0].message

    def test_weakref_finalize_callback(self, tmp_path):
        fs = lint(tmp_path, """
            import threading
            import weakref

            _lock = threading.Lock()
            _reg = {}

            def _cleanup(key):
                with _lock:
                    _reg.pop(key, None)

            class Owner:
                def __init__(self, key):
                    weakref.finalize(self, _cleanup, key)
        """, select=["TL012"])
        assert rules_of(fs) == ["TL012"]
        assert "finalize" in fs[0].message

    def test_aliased_weakref_finalize_is_seen(self, tmp_path):
        # review regression: `import weakref as wr` must classify the
        # same as the plain import; a project-local function named
        # finalize must NOT seed the walk
        fs = lint(tmp_path, """
            import threading
            import weakref as wr

            _lock = threading.Lock()
            _reg = {}

            def _cleanup(key):
                with _lock:
                    _reg.pop(key, None)

            def finalize(obj, fn):   # unrelated local helper
                pass

            class Owner:
                def __init__(self, key):
                    wr.finalize(self, _cleanup, key)

            def harmless(x):
                finalize(x, _cleanup)
        """, select=["TL012"])
        assert rules_of(fs) == ["TL012"]

    def test_singleton_instance_method_resolves(self, tmp_path):
        # the ACCOUNTANT shape: the lock lives behind a module-level
        # singleton in another module
        fs = lint_tree(tmp_path, {
            "ledger.py": """
                import threading

                class Ledger:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._entries = {}

                    def drop(self, key):
                        with self._lock:
                            self._entries.pop(key, None)

                LEDGER = Ledger()
            """,
            "owner.py": """
                from ledger import LEDGER

                class Owner:
                    def __del__(self):
                        LEDGER.drop("x")
            """}, select=["TL012"])
        assert rules_of(fs) == ["TL012"]
        assert fs[0].path.endswith("ledger.py")

    def test_lock_free_deferral_is_clean(self, tmp_path):
        # the drop_deferred pattern: finalizers append to a deque, the
        # locked retirement happens on a normal thread later
        fs = lint(tmp_path, """
            import threading
            from collections import deque

            class Ledger:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._entries = {}
                    self._deferred = deque()

                def drop(self, key):
                    with self._lock:
                        self._entries.pop(key, None)

                def drop_deferred(self, key):
                    self._deferred.append(key)

            class Owner:
                def __init__(self, ledger, key):
                    self._ledger = ledger
                    self.key = key

                def release(self):
                    pass

                def __del__(self):
                    self.release()
        """, select=["TL012"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def close(self):
                    # tracelint: disable=TL012 -- fixture: RLock, short sections
                    with self._lock:
                        self._items.clear()

                def __del__(self):
                    self.close()
        """, select=["TL012"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL013 — callback invoked under a held lock
# ------------------------------------------------------------------ #

class TestTL013CallbackUnderLock:
    def test_on_token_under_condition(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Stream:
                def __init__(self, on_token):
                    self._cv = threading.Condition()
                    self._toks = []
                    self._on_token = on_token

                def push(self, tok):
                    with self._cv:
                        self._toks.append(tok)
                        self._on_token(0, tok)
        """, select=["TL013"])
        assert rules_of(fs) == ["TL013"]
        assert "_on_token" in fs[0].message
        assert "Stream._cv" in fs[0].message

    def test_param_callback_under_module_lock(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            _lock = threading.Lock()
            _subs = []

            def register(callback):
                with _lock:
                    _subs.append(callback)
                    callback(len(_subs))
        """, select=["TL013"])
        assert rules_of(fs) == ["TL013"]

    def test_callback_outside_lock_is_clean(self, tmp_path):
        # the _push-outside-_lock discipline: append under the lock,
        # fire the callback after releasing it
        fs = lint(tmp_path, """
            import threading

            class Stream:
                def __init__(self, on_token):
                    self._cv = threading.Condition()
                    self._toks = []
                    self._on_token = on_token

                def push(self, tok):
                    with self._cv:
                        self._toks.append(tok)
                        self._cv.notify_all()
                    if self._on_token is not None:
                        self._on_token(0, tok)
        """, select=["TL013"])
        assert fs == []

    def test_project_internal_hook_method_is_clean(self, tmp_path):
        # a name that matches the callback vocabulary but resolves to a
        # method of the project is internal, not user-supplied
        fs = lint(tmp_path, """
            import threading

            class Prof:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = []

                def _flush_hook(self):
                    pass

                def record(self, row):
                    with self._lock:
                        self._rows.append(row)
                        self._flush_hook()
        """, select=["TL013"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            _lock = threading.Lock()

            def register(callback):
                with _lock:
                    # tracelint: disable=TL013 -- fixture: callback is doc'd lock-free
                    callback(1)
        """, select=["TL013"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL014 — thread lifecycle
# ------------------------------------------------------------------ #

class TestTL014ThreadLifecycle:
    def test_non_daemon_unjoined_class_thread(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

                def _run(self):
                    pass
        """, select=["TL014"])
        assert rules_of(fs) == ["TL014"]
        assert "daemon" in fs[0].message and "join" in fs[0].message

    def test_daemon_thread_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    self._thread = threading.Thread(target=self._run,
                                                    daemon=True)
                    self._thread.start()

                def _run(self):
                    pass
        """, select=["TL014"])
        assert fs == []

    def test_joined_on_close_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

                def _run(self):
                    pass

                def close(self):
                    self._thread.join(timeout=5)
        """, select=["TL014"])
        assert fs == []

    def test_blocking_get_without_pill(self, tmp_path):
        fs = lint(tmp_path, """
            import queue
            import threading

            class Ring:
                def __init__(self):
                    self._q = queue.Queue()
                    self._thread = threading.Thread(
                        target=self._produce, daemon=True)
                    self._thread.start()

                def _produce(self):
                    self._q.put(1)

                def take(self):
                    return self._q.get()
        """, select=["TL014"])
        assert rules_of(fs) == ["TL014"]
        assert "poison-pill" in fs[0].message

    def test_sentinel_pill_on_close_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import queue
            import threading

            _END = object()

            class Ring:
                def __init__(self):
                    self._q = queue.Queue()
                    self._thread = threading.Thread(
                        target=self._produce, daemon=True)
                    self._thread.start()

                def _produce(self):
                    self._q.put(1)

                def take(self):
                    return self._q.get()

                def close(self):
                    self._q.put_nowait(_END)
        """, select=["TL014"])
        assert fs == []

    def test_bounded_get_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import queue
            import threading

            class Ring:
                def __init__(self):
                    self._q = queue.Queue()
                    self._thread = threading.Thread(
                        target=self._produce, daemon=True)
                    self._thread.start()

                def _produce(self):
                    self._q.put(1)

                def take(self):
                    return self._q.get(timeout=0.2)
        """, select=["TL014"])
        assert fs == []

    def test_positional_timeout_get_is_bounded(self, tmp_path):
        # review regression: get(True, 1.0) has a positional timeout
        # and wakes on its own — not an unbounded blocking get
        fs = lint(tmp_path, """
            import queue
            import threading

            class Ring:
                def __init__(self):
                    self._q = queue.Queue()
                    self._thread = threading.Thread(
                        target=self._produce, daemon=True)
                    self._thread.start()

                def _produce(self):
                    self._q.put(1)

                def take(self):
                    return self._q.get(True, 1.0)
        """, select=["TL014"])
        assert fs == []

    def test_thread_stored_into_pool_and_joined_is_clean(self, tmp_path):
        # review regression: a local handle appended to a worker pool
        # (and joined from it on teardown) has transferred ownership
        fs = lint(tmp_path, """
            import threading

            class Pool:
                def __init__(self):
                    self._workers = []

                def spawn(self, fn):
                    t = threading.Thread(target=fn)
                    t.start()
                    self._workers.append(t)

                def close(self):
                    for t in self._workers:
                        t.join()
        """, select=["TL014"])
        assert fs == []

    def test_local_thread_returned_transfers_ownership(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t

            def fire_and_forget(fn):
                t = threading.Thread(target=fn)
                t.start()
        """, select=["TL014"])
        assert rules_of(fs) == ["TL014"]
        assert "fire_and_forget" in fs[0].message

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    # tracelint: disable=TL014 -- fixture: joined by the owner
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

                def _run(self):
                    pass
        """, select=["TL014"])
        assert fs == []


# ------------------------------------------------------------------ #
# TL015 — telemetry schema / fault-site contract
# ------------------------------------------------------------------ #

def _tele_docs(tmp_path, kinds=(), metrics=()):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    f = d / "TELEMETRY.md"
    lines = ["## Event log", "", "### Event schema", "",
             "| kind | fields |", "|---|---|"]
    lines += [f"| `{k}` | stuff |" for k in kinds]
    lines += ["", "## Metrics schema", "", "| name | kind |", "|---|---|"]
    lines += [f"| `{m}` | counter |" for m in metrics]
    f.write_text("\n".join(lines) + "\n")
    return str(f)


def _fault_docs(tmp_path, sites):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    f = d / "ENV_VARS.md"
    site_s = " / ".join(f"`{s}`" for s in sites)
    f.write_text(
        "| Variable | Default | Effect |\n|---|---|---|\n"
        f"| `MXNET_FAULT_INJECT` | unset | rules. Sites: {site_s}. "
        "Kinds: `raise` (`os.kill` for kill). |\n")
    return str(f)


class TestTL015TelemetryContract:
    def test_documented_kinds_and_metrics_are_clean(self, tmp_path):
        docs = _tele_docs(tmp_path, kinds=("boot",),
                          metrics=("requests_total",))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def up():
                telemetry.emit("boot", ok=1)
                telemetry.counter("requests_total").inc()
        """, select=["TL015"], telemetry_docs=docs)
        assert fs == []

    def test_event_drift_is_bidirectional(self, tmp_path):
        # ISSUE acceptance: an emitted-but-undocumented kind fails AND
        # a documented-but-never-emitted kind fails
        docs = _tele_docs(tmp_path, kinds=("boot", "ghost"))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def up():
                telemetry.emit("boot")
                telemetry.emit("rogue", oops=1)
        """, select=["TL015"], telemetry_docs=docs)
        assert rules_of(fs) == ["TL015", "TL015"]
        msgs = {f.message for f in fs}
        assert any("`rogue`" in m and "emitted here" in m for m in msgs)
        assert any("`ghost`" in m and "never" in m for m in msgs)
        doc_hit = [f for f in fs if "`ghost`" in f.message]
        assert doc_hit[0].path.endswith("TELEMETRY.md")

    def test_metric_drift_is_bidirectional(self, tmp_path):
        docs = _tele_docs(tmp_path, metrics=("good_total", "ghost_total"))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def up():
                telemetry.counter("good_total").inc()
                telemetry.gauge("rogue_depth").set(1)
        """, select=["TL015"], telemetry_docs=docs)
        msgs = " ".join(f.message for f in fs)
        assert "`rogue_depth`" in msgs and "`ghost_total`" in msgs

    def test_fstring_metric_family_covers_doc_rows(self, tmp_path):
        # the _CounterView shape: f"serve_{k}_total" covers the
        # concrete documented family names in the stale direction
        docs = _tele_docs(tmp_path,
                          metrics=("serve_step_dispatches_total",))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def make(k):
                return telemetry.counter(f"serve_{k}_total", server="s")
        """, select=["TL015"], telemetry_docs=docs)
        assert fs == []

    def test_emit_forwarder_wrapper_counts(self, tmp_path):
        # tools/launch.py's _emit(kind, **fields) wrapper: a literal
        # through the forwarder is an emit of that kind
        docs = _tele_docs(tmp_path, kinds=("boot",))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def _emit(kind, **fields):
                telemetry.emit(kind, **fields)

            def up():
                _emit("rogue", rank=0)
                _emit("boot")
        """, select=["TL015"], telemetry_docs=docs)
        assert rules_of(fs) == ["TL015"]
        assert "`rogue`" in fs[0].message

    def test_fault_site_drift_is_bidirectional(self, tmp_path):
        docs = _fault_docs(tmp_path, ["serve.pump", "serve.ghost"])
        fs = lint(tmp_path, """
            from mxnet_tpu.telemetry.faults import fault_point

            def pump():
                fault_point("serve.pump")
                fault_point("serve.mystery")
        """, select=["TL015"], env_docs=docs)
        msgs = " ".join(f.message for f in fs)
        assert "`serve.mystery`" in msgs and "`serve.ghost`" in msgs
        # the Kinds: tail ('os.kill') must not count as a site
        assert "os.kill" not in msgs

    def test_suppressed(self, tmp_path):
        docs = _tele_docs(tmp_path, kinds=("boot",))
        fs = lint(tmp_path, """
            from mxnet_tpu import telemetry

            def up():
                telemetry.emit("boot")
                # tracelint: disable=TL015 -- fixture: internal debug-only kind
                telemetry.emit("rogue")
        """, select=["TL015"], telemetry_docs=docs)
        assert fs == []

    def test_repo_parity_gate(self):
        """The TL015 self-check mirror of the TL005 gate: code event
        kinds / metric names / fault sites and the docs tables agree,
        both directions, over the full lint target."""
        r = cli(["mxnet_tpu/", "tools/", "benchmark/", "--select",
                 "TL015", "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_external_env_docs_does_not_blind_telemetry_scan(
            self, tmp_path):
        """Review regression: an --env-docs override outside the repo
        must not re-root the TELEMETRY.md stale-direction scan — each
        docs file is reconciled against the tree that owns it."""
        d = tmp_path / "docs"
        d.mkdir()
        (d / "ENV_VARS.md").write_text(
            "| Variable | Default | Effect |\n|---|---|---|\n")
        r = cli(["mxnet_tpu/telemetry/faults.py", "--env-docs",
                 str(d / "ENV_VARS.md"), "--select", "TL015",
                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []


# ------------------------------------------------------------------ #
# TL016–TL019 — the executable-contract family (tracelint v4) over a
# miniature operand-schema registry mirroring serve/schema.py's shape
# ------------------------------------------------------------------ #

_SCHEMA_FIXTURE = """
    EXECUTABLES = {
        "admit": {
            "module": "engine",
            "getter": "admit_fn",
            "operands": ("params", "prompts", "meta", "pages",
                         "kp", "vp", "pos", "tok", "active"),
            "donated": ("kp", "vp"),
        },
    }
    SLOT_STATE = (
        ("pos", "int32", 1),
        ("tok", "int32", 1),
        ("active", "bool", 1),
    )
"""


class TestTL016DonationDrift:
    def test_stale_literal_positions(self, tmp_path):
        """Literal donate indices that disagree with the registry's
        donated positions — the producer half of the PR-18 class."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import jax

                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    return (kp, vp, pos, tok, active)

                fn = jax.jit(admit, donate_argnums=(5, 6))
            """}, select=["TL016"])
        assert rules_of(fs) == ["TL016"]
        assert "disagree with the operand schema" in fs[0].message
        assert fs[0].severity == "error"

    def test_inserted_operand_without_donate_shift(self, tmp_path):
        """The exact PR-18 recycled-page shape: a new operand lands in
        the signature, the literal donation pair does not move, and the
        'right' indices now donate the wrong buffers."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import jax

                def admit(params, prompts, extra, meta, pages,
                          kp, vp, pos, tok, active):
                    return (kp, vp, pos, tok, active)

                fn = jax.jit(admit, donate_argnums=(4, 5))
            """}, select=["TL016"])
        assert rules_of(fs) == ["TL016"]
        assert "PR-18" in fs[0].message
        assert "'pages'" in fs[0].message

    def test_jit_donate_derivation_is_clean(self, tmp_path):
        """Deriving the indices from the registry is the sanctioned
        pattern — the runtime validates the signature at build time."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import jax
                import schema

                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    return (kp, vp, pos, tok, active)

                fn = jax.jit(admit,
                             donate_argnums=schema.jit_donate(
                                 "admit", admit))
            """}, select=["TL016"])
        assert fs == []

    def test_matching_literal_is_clean(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import jax

                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    return (kp, vp, pos, tok, active)

                fn = jax.jit(admit, donate_argnums=(4, 5))
            """}, select=["TL016"])
        assert fs == []

    def test_non_registry_index_past_arity(self, tmp_path):
        """Outside the registry the producer-side TL002 generalization:
        a donation index past the wrapped function's positional arity
        donates a buffer that does not exist."""
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                return w - g

            fn = jax.jit(step, donate_argnums=(2,))
        """, select=["TL016"])
        assert rules_of(fs) == ["TL016"]
        assert "exceed" in fs[0].message

    def test_suppressed(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import jax

                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    return (kp, vp, pos, tok, active)

                # tracelint: disable=TL016 -- fixture: transitional donation map
                fn = jax.jit(admit, donate_argnums=(5, 6))
            """}, select=["TL016"])
        assert fs == []


class TestTL017SlotStateLayout:
    def test_hard_coded_meta_column(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    valid = meta[:, 0]
                    return (kp, vp, pos, tok, active)
            """}, select=["TL017"])
        assert rules_of(fs) == ["TL017"]
        assert "meta column index 0" in fs[0].message

    def test_dispatch_side_meta_builder_flagged(self, tmp_path):
        """A module that fetches executables through registry getters
        builds the rows those bodies unpack — its hand-numbered writes
        drift the same way."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def push(self, meta):
                        fn = self.progs.admit_fn(4)
                        meta[:, 1] = 0
                        return fn
            """}, select=["TL017"])
        assert rules_of(fs) == ["TL017"]

    def test_state_tuple_arity_drift(self, tmp_path):
        """A column threaded through some scatter sites but not the
        schema: the tuple's arity disagrees with kp, vp + SLOT_STATE."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    ttl = pos
                    return (kp, vp, pos, tok, active, ttl)
            """}, select=["TL017"])
        assert rules_of(fs) == ["TL017"]
        assert "6 elements" in fs[0].message
        assert "declares 5" in fs[0].message

    def test_literal_byte_total(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                _SLOT_STATE_BYTES = 9
            """}, select=["TL017"])
        assert rules_of(fs) == ["TL017"]
        assert "slot_state_bytes()" in fs[0].message

    def test_schema_indexing_is_clean(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                import schema

                _SLOT_STATE_BYTES = schema.slot_state_bytes()
                _AM = schema.meta_cols("admit")

                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    valid = meta[:, _AM["valid"]]
                    return (kp, vp, pos, tok, active)
            """}, select=["TL017"])
        assert fs == []

    def test_meta_outside_contract_scope_is_clean(self, tmp_path):
        """A module that neither defines executables nor dispatches
        them can call its locals whatever it likes."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "report.py": """
                def summarize(meta):
                    return meta[:, 0].sum()
            """}, select=["TL017"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "engine.py": """
                def admit(params, prompts, meta, pages,
                          kp, vp, pos, tok, active):
                    # tracelint: disable=TL017 -- fixture: migration shim, schema lands next PR
                    valid = meta[:, 0]
                    return (kp, vp, pos, tok, active)
            """}, select=["TL017"])
        assert fs == []


class TestTL018DispatchArity:
    def test_missing_operand_in_dispatch(self, tmp_path):
        """The 'zpages lands in 2 of 3 admission paths' class: one
        dispatch site passes one operand fewer than declared."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def pump(self):
                        fn = self.progs.admit_fn(4)
                        return fn(self.params, self.prompts, self.meta,
                                  *self._state)
            """}, select=["TL018"])
        assert rules_of(fs) == ["TL018"]
        assert "passes 8" in fs[0].message
        assert "declares 9" in fs[0].message
        assert "params, prompts, meta" in fs[0].message  # operand list

    def test_exact_arity_is_clean(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def pump(self):
                        fn = self.progs.admit_fn(4)
                        return fn(self.params, self.prompts, self.meta,
                                  self.pages, *self._state)
            """}, select=["TL018"])
        assert fs == []

    def test_immediate_getter_call_counted(self, tmp_path):
        """fn-less dispatch — getter(...)(operands...) — is the same
        call-site."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def pump(self):
                        return self.progs.admit_fn(4)(
                            self.params, self.meta, self.pages,
                            *self._state)
            """}, select=["TL018"])
        assert rules_of(fs) == ["TL018"]

    def test_uncountable_splat_is_skipped(self, tmp_path):
        """A non-state splat hides the operand count — not this rule's
        call to make."""
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def pump(self, argpack):
                        fn = self.progs.admit_fn(4)
                        return fn(*argpack)
            """}, select=["TL018"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "schema.py": _SCHEMA_FIXTURE,
            "server.py": """
                class Srv:
                    def pump(self):
                        fn = self.progs.admit_fn(4)
                        # tracelint: disable=TL018 -- fixture: legacy replay path, operand added downstream
                        return fn(self.params, self.prompts, self.meta,
                                  *self._state)
            """}, select=["TL018"])
        assert fs == []


class TestTL019PlacementDiscipline:
    def test_local_devices_chain_into_sharding(self, tmp_path):
        """The elastic-resume hazard: a host-local device list flows
        through mesh and sharding construction into device_put — every
        link in the chain is flagged."""
        fs = lint(tmp_path, """
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            def build(x):
                devs = jax.local_devices()
                mesh = Mesh(devs, ("dp",))
                sh = NamedSharding(mesh, P("dp"))
                return jax.device_put(x, sh)
        """, select=["TL019"])
        assert rules_of(fs) == ["TL019", "TL019", "TL019"]
        assert all("jax.local_devices()" in f.message for f in fs)
        assert len({f.line for f in fs}) == 3

    def test_env_read_into_partition_spec(self, tmp_path):
        fs = lint(tmp_path, """
            import os
            from jax.sharding import PartitionSpec

            def spec():
                axis = os.environ["RANK_AXIS"]
                return PartitionSpec(axis)
        """, select=["TL019"])
        assert rules_of(fs) == ["TL019"]
        assert "os.environ" in fs[0].message

    def test_pod_global_devices_are_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            def build(x):
                devs = jax.devices()
                mesh = Mesh(devs, ("dp",))
                sh = NamedSharding(mesh, P("dp"))
                return jax.device_put(x, sh)
        """, select=["TL019"])
        assert fs == []

    def test_mesh_helper_definitions_exempt(self, tmp_path):
        """The parallel.mesh helpers ARE the sanctioned boundary —
        their internals legitimately touch process locality."""
        fs = lint(tmp_path, """
            import jax
            from jax.sharding import Mesh

            def make_mesh(axes):
                devs = jax.local_devices()
                return Mesh(devs, tuple(axes))

            def global_put(x, sharding):
                rank = jax.process_index()
                return jax.make_array_from_process_local_data(
                    sharding, x)
        """, select=["TL019"])
        assert fs == []

    def test_helper_output_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from mxnet_tpu.parallel.mesh import data_sharding

            def put(x):
                sh = data_sharding()
                return jax.device_put(x, sh)
        """, select=["TL019"])
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from jax.sharding import Mesh

            def build():
                devs = jax.local_devices()
                # tracelint: disable=TL019 -- fixture: single-host tool, never runs on a pod
                return Mesh(devs, ("dp",))
        """, select=["TL019"])
        assert fs == []


# ------------------------------------------------------------------ #
# seeded historical bugs (ISSUE 14 acceptance): each of the three
# hand-caught PR-7/10/13 bug classes must fail on a mutation of the
# REAL runtime code and stay clean on HEAD
# ------------------------------------------------------------------ #

class TestSeededHistoricalBugs:
    def test_seeded_wall_clock_deadline_fails_gate(self, tmp_path):
        """The PR-13 bug class: serve close()'s drain deadline computed
        on the wall clock instead of time.monotonic() (TL011)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "server.py")).read()
        needle = "        deadline = time.monotonic() + timeout\n"
        assert needle in src
        clean = tmp_path / "server_head.py"
        clean.write_text(src)
        r = cli([str(clean), "--select", "TL011", "--format=json"])
        assert r.returncode == 0, r.stdout   # HEAD is clean
        seeded = src.replace(
            needle, "        deadline = time.time() + timeout\n", 1)
        bad = tmp_path / "server_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--select", "TL011", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL011" and "monotonic" in f["message"]
                   for f in hits)

    def _mirror(self, tmp_path, trainer_src):
        """Rebuild the trainer/memory package seam under tmp so the
        cross-module singleton resolution works like in the repo."""
        for rel in ("mxnet_tpu/__init__.py",
                    "mxnet_tpu/gluon/__init__.py",
                    "mxnet_tpu/telemetry/__init__.py"):
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text("")
        (tmp_path / "mxnet_tpu" / "telemetry" / "memory.py").write_text(
            open(os.path.join(REPO, "mxnet_tpu", "telemetry",
                              "memory.py")).read())
        (tmp_path / "mxnet_tpu" / "gluon" / "trainer.py").write_text(
            trainer_src)

    def test_seeded_finalizer_accountant_lock_fails_gate(self, tmp_path):
        """The PR-10 bug class: Trainer's GC finalizer taking the
        process-wide accountant lock instead of the lock-free
        drop_deferred path (TL012, resolved through the ACCOUNTANT
        singleton two modules away)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "gluon", "trainer.py")).read()
        needle = 'ACCOUNTANT.drop_deferred("train.params",'
        assert needle in src
        self._mirror(tmp_path, src)
        r = cli([str(tmp_path), "--select", "TL012", "--format=json"])
        assert r.returncode == 0, r.stdout   # HEAD is clean
        self._mirror(tmp_path, src.replace(
            needle, 'ACCOUNTANT.drop("train.params",', 1))
        r = cli([str(tmp_path), "--select", "TL012", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL012" and "__del__" in f["message"]
                   and f["path"].endswith("memory.py") for f in hits)

    def test_seeded_on_token_under_lock_fails_gate(self, tmp_path):
        """The PR-7 bug class: the per-token user callback invoked
        inside the stream's condition instead of after releasing it
        (TL013)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "server.py")).read()
        needle = ("        with self._cv:\n"
                  "            self._toks.append(tok)\n"
                  "            self._cv.notify_all()\n")
        assert needle in src
        clean = tmp_path / "server_head.py"
        clean.write_text(src)
        r = cli([str(clean), "--select", "TL013", "--format=json"])
        assert r.returncode == 0, r.stdout   # HEAD is clean
        seeded = src.replace(needle, (
            "        with self._cv:\n"
            "            self._toks.append(tok)\n"
            "            if self._on_token is not None:\n"
            "                self._on_token(self.request_id, tok)\n"
            "            self._cv.notify_all()\n"), 1)
        bad = tmp_path / "server_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--select", "TL013", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL013" and "_on_token" in f["message"]
                   for f in hits)


# ------------------------------------------------------------------ #
# seeded contract drift (ISSUE 20 acceptance): mutations reproducing
# the PR-18 recycled-page drift shape against the REAL serve engine/
# server must fail at error level while the HEAD copies lint clean
# ------------------------------------------------------------------ #

class TestSeededContractDrift:
    def _mirror(self, tmp_path, name, src):
        """The registry module plus one consumer, side by side — the
        linter reads EXECUTABLES/SLOT_STATE straight out of the AST,
        so no package scaffolding is needed."""
        (tmp_path / "schema.py").write_text(open(os.path.join(
            REPO, "mxnet_tpu", "serve", "schema.py")).read())
        (tmp_path / name).write_text(src)

    def test_head_engine_and_server_are_clean(self, tmp_path):
        for name in ("engine.py", "server.py"):
            src = open(os.path.join(
                REPO, "mxnet_tpu", "serve", name)).read()
            self._mirror(tmp_path, name, src)
        r = cli([str(tmp_path), "--select", "TL016,TL017,TL018",
                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_seeded_admit_operand_without_donate_shift(self, tmp_path):
        """THE PR-18 shape: an operand inserted into admit's signature
        while a literal donation pair stays put — positions 6/7 now
        name zpages/kp and the wrong buffer dies silently (TL016)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "engine.py")).read()
        sig = ("def admit(param_vals, prompts, meta, dls, pages, "
               "zpages, kp, vp,")
        don = 'donate_argnums=schema.jit_donate("admit", admit)),'
        assert sig in src and don in src
        seeded = src.replace(
            sig, "def admit(param_vals, prompts, scratch_rows, meta, "
                 "dls, pages, zpages, kp, vp,", 1
        ).replace(don, "donate_argnums=(6, 7)),", 1)
        self._mirror(tmp_path, "engine.py", seeded)
        r = cli([str(tmp_path), "--select", "TL016", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL016" and "PR-18" in f["message"]
                   and f["severity"] == "error" for f in hits)

    def test_seeded_state_column_through_three_sites(self, tmp_path):
        """A tenth slot-state column threaded through the three
        new-state construction sites but not the schema: every drifted
        tuple is flagged (TL017)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "engine.py")).read()
        needle = "(kp, vp, pos, tok, active, stop, keys, dl, spec)"
        assert src.count(needle) == 3
        seeded = src.replace(
            needle, "(kp, vp, pos, tok, active, stop, keys, dl, spec, "
                    "ttl)")
        self._mirror(tmp_path, "engine.py", seeded)
        r = cli([str(tmp_path), "--select", "TL017", "--format=json"])
        assert r.returncode == 1
        hits = [f for f in json.loads(r.stdout)["findings"]
                if f["rule"] == "TL017"]
        assert len(hits) == 3
        assert all("10 elements" in f["message"] and
                   "declares 9" in f["message"] for f in hits)

    def test_seeded_literal_byte_total(self, tmp_path):
        """Hard-coding the 29 back in place of the schema-priced total
        is flagged (TL017) — the ledger must not drift from the
        layout."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "engine.py")).read()
        needle = "_SLOT_STATE_BYTES = schema.slot_state_bytes()"
        assert needle in src
        seeded = src.replace(needle, "_SLOT_STATE_BYTES = 29", 1)
        self._mirror(tmp_path, "engine.py", seeded)
        r = cli([str(tmp_path), "--select", "TL017", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL017" and
                   "slot_state_bytes()" in f["message"] for f in hits)

    def test_seeded_dispatch_drops_zpages(self, tmp_path):
        """The 'zpages lands in 2 of 3 admission paths' class: the COW
        admission dispatch loses an operand (TL018)."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "serve", "server.py")).read()
        needle = ("fn(meta, dls, srcs, dsts, zpages,\n"
                  "                           *self._state)")
        assert needle in src
        seeded = src.replace(
            needle, "fn(meta, dls, srcs, dsts,\n"
                    "                           *self._state)", 1)
        self._mirror(tmp_path, "server.py", seeded)
        r = cli([str(tmp_path), "--select", "TL018", "--format=json"])
        assert r.returncode == 1
        hits = json.loads(r.stdout)["findings"]
        assert any(f["rule"] == "TL018" and "passes 13" in f["message"]
                   and "declares 14" in f["message"] for f in hits)


# ------------------------------------------------------------------ #
# SARIF output
# ------------------------------------------------------------------ #

class TestSarif:
    BAD = """
        import jax

        def step(w, g):
            lr = float(g)
            return w - lr * g

        fn = jax.jit(step)
    """

    def test_minimal_sarif_2_1_0_shape(self, tmp_path):
        """The SARIF 2.1.0 minimal-schema shape pin: version, tool
        driver with a rule table, results with ruleId/level/message/
        physical locations."""
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent(self.BAD))
        r = cli([str(bad), "--format", "sarif"])
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == "2.1.0"
        assert doc["$schema"].endswith("sarif-2.1.0.json")
        run = doc["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "tracelint"
        rule_ids = {rl["id"] for rl in driver["rules"]}
        assert {"TL001", "TL011", "TL015"} <= rule_ids
        res = run["results"][0]
        assert res["ruleId"] == "TL001"
        assert res["level"] == "error"
        assert "float" in res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad.py")
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1

    def test_clean_run_has_empty_results(self, tmp_path):
        (tmp_path / "ok.py").write_text("x = 1\n")
        r = cli([str(tmp_path), "--format", "sarif"])
        assert r.returncode == 0
        assert json.loads(r.stdout)["runs"][0]["results"] == []

    def test_warn_severity_maps_to_warning_level(self, tmp_path):
        (tmp_path / "warny.py").write_text(textwrap.dedent("""
            from jax import lax

            def ring_pass(x, axis="sp"):
                return lax.ppermute(x, axis_name=axis, perm=[])

            def fold(x):
                return lax.psum(x, "sp")
        """))
        r = cli([str(tmp_path), "--format", "sarif"])
        assert r.returncode == 0   # warnings don't fail the gate
        res = json.loads(r.stdout)["runs"][0]["results"]
        assert res and res[0]["level"] == "warning"

    def test_v4_contract_rules_in_driver_and_results(self, tmp_path):
        """The v4 rule table rides the same sorted(RULES) rendering:
        TL016–TL019 appear in the driver and fire at error level."""
        for name, source in {
                "schema.py": _SCHEMA_FIXTURE,
                "engine.py": """
                    import jax

                    def admit(params, prompts, meta, pages,
                              kp, vp, pos, tok, active):
                        return (kp, vp, pos, tok, active)

                    fn = jax.jit(admit, donate_argnums=(5, 6))
                """}.items():
            (tmp_path / name).write_text(textwrap.dedent(source))
        r = cli([str(tmp_path), "--select", "TL016", "--format",
                 "sarif"])
        assert r.returncode == 1
        run = json.loads(r.stdout)["runs"][0]
        rule_ids = {rl["id"] for rl in run["tool"]["driver"]["rules"]}
        assert {"TL016", "TL017", "TL018", "TL019"} <= rule_ids
        res = run["results"][0]
        assert res["ruleId"] == "TL016"
        assert res["level"] == "error"


# ------------------------------------------------------------------ #
# --jobs — parallel lint determinism (all three formats)
# ------------------------------------------------------------------ #

class TestJobs:
    def _tree(self, tmp_path):
        for i in range(3):
            (tmp_path / f"mod{i}.py").write_text(textwrap.dedent(f"""
                import jax

                def step{i}(w, g):
                    lr = float(g)
                    return w - lr * g

                fn{i} = jax.jit(step{i})
            """))

    def test_parallel_output_identical_to_serial(self, tmp_path):
        self._tree(tmp_path)
        for fmt in ("text", "json", "sarif"):
            serial = cli([str(tmp_path), f"--format={fmt}"])
            parallel = cli([str(tmp_path), f"--format={fmt}",
                            "--jobs", "3"])
            assert serial.returncode == parallel.returncode == 1, fmt
            assert serial.stdout == parallel.stdout, fmt

    def test_jobs_accepted_on_clean_tree(self, tmp_path):
        (tmp_path / "ok.py").write_text("x = 1\n")
        r = cli([str(tmp_path), "--jobs", "2"])
        assert r.returncode == 0, r.stdout


# ------------------------------------------------------------------ #
# --changed-only — the pre-commit fast path: report scoped to the
# git-changed set, byte-identical to a full run filtered to it
# ------------------------------------------------------------------ #

class TestChangedOnly:
    BAD = """
        import jax

        def step{i}(w, g):
            lr = float(g)
            return w - lr * g

        fn{i} = jax.jit(step{i})
    """

    def _git(self, cwd, *args):
        subprocess.run(
            ["git", "-c", "user.name=t", "-c", "user.email=t@t",
             *args],
            cwd=str(cwd), check=True, capture_output=True, env=_ENV)

    def _seed_repo(self, tmp_path):
        for i in range(2):
            (tmp_path / f"mod{i}.py").write_text(
                textwrap.dedent(self.BAD.format(i=i)))
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", ".")
        self._git(tmp_path, "commit", "-qm", "seed")

    def _cli(self, cwd, args):
        # run from inside the throwaway checkout; the package resolves
        # off PYTHONPATH so --changed-only scopes to THAT repo's diff
        return subprocess.run(
            [sys.executable, "-m", "tools.tracelint"] + args,
            capture_output=True, text=True, cwd=str(cwd),
            env=dict(_ENV, PYTHONPATH=REPO))

    def test_byte_identical_to_filtered_full_run(self, tmp_path):
        self._seed_repo(tmp_path)
        p = tmp_path / "mod1.py"
        p.write_text(p.read_text() + "\n# touched\n")
        full = self._cli(tmp_path, [".", "--format=json"])
        changed = self._cli(tmp_path, [".", "--changed-only",
                                       "--format=json"])
        assert full.returncode == changed.returncode == 1
        want = [f for f in json.loads(full.stdout)["findings"]
                if f["path"].endswith("mod1.py")]
        got = json.loads(changed.stdout)["findings"]
        assert want and got == want

    def test_clean_changed_file_passes_despite_dirty_neighbors(
            self, tmp_path):
        """Only the changed set is REPORTED — committed findings in
        untouched modules don't block the pre-commit run."""
        self._seed_repo(tmp_path)
        (tmp_path / "newmod.py").write_text("x = 1\n")   # untracked
        r = self._cli(tmp_path, [".", "--changed-only",
                                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_no_changes_is_clean(self, tmp_path):
        self._seed_repo(tmp_path)
        r = self._cli(tmp_path, [".", "--changed-only",
                                 "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_outside_git_checkout_is_usage_error(self, tmp_path):
        (tmp_path / "ok.py").write_text("x = 1\n")
        r = self._cli(tmp_path, [".", "--changed-only"])
        assert r.returncode == 2
        assert "git" in r.stderr


# ------------------------------------------------------------------ #
# perf: the shared lock analysis must keep the serial full-target run
# near the PR-11 mark (loose wall-clock ceiling, not a microbenchmark)
# ------------------------------------------------------------------ #

class TestSerialRunBudget:
    def test_full_target_serial_run_stays_fast(self):
        import time as _time

        t0 = _time.monotonic()
        run_paths([os.path.join(REPO, p)
                   for p in ("mxnet_tpu", "tools", "benchmark")])
        dt = _time.monotonic() - t0
        # PR-11 anchored ~9s; the v3 rules ride the shared lock/aux
        # analyses, so even a slow CI container stays well under this
        assert dt < 30.0, f"serial tracelint run took {dt:.1f}s"
