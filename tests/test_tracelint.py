"""tracelint test suite (ISSUE 5): per-rule fixtures — true positive,
true negative, suppressed — plus the tier-1 CI gate: a self-run over
``mxnet_tpu/`` must be clean, and a synthetic ``float(loss)`` seeded
into a fused-step body must fail it.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.tracelint import run_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def lint(tmp_path, source, name="snippet.py", **kw):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_paths([str(p)], **kw)


def rules_of(findings):
    return [f.rule for f in findings]


def cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.tracelint"] + args,
        capture_output=True, text=True, cwd=cwd, env=_ENV)


# ------------------------------------------------------------------ #
# TL001 — host sync inside traced code
# ------------------------------------------------------------------ #

class TestTL001HostSync:
    def test_float_in_jitted_fn(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "float" in fs[0].message and "step" in fs[0].message

    def test_item_via_callgraph_helper(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def helper(x):
                return x.item()

            def step(x):
                return helper(x)

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "helper" in fs[0].message

    def test_branch_on_traced_array(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            def step(x):
                s = jnp.sum(x)
                if s > 0:
                    return x
                return -x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]
        assert "branches on a traced array" in fs[0].message

    def test_numpy_materialization_in_trace_scope(self, tmp_path):
        fs = lint(tmp_path, """
            import numpy as onp
            from mxnet_tpu.gluon.block import trace_scope

            def run(key, vals):
                with trace_scope(key, True) as aux:
                    host = onp.asarray(vals[0])
                return host
        """)
        assert rules_of(fs) == ["TL001"]
        assert "onp.asarray" in fs[0].message

    def test_true_negatives(self, tmp_path):
        # host work outside the traced region, trace-time python on
        # hyperparameters/shapes, identity tests: all fine
        fs = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            def host_metric(x):
                return float(x)  # never traced

            class Rule:
                momentum = 0.0

                def step(self, w, g, state):
                    n = float(w.shape[0])
                    if self.momentum == 0.0:
                        return w - g / n
                    if state is None:
                        state = jnp.zeros_like(w)
                    return w + self.momentum * state - g / n

            def outer(w, g, s):
                return Rule().step(w, g, s)

            fn = jax.jit(outer)
        """)
        assert fs == []

    def test_suppressed_with_reason(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)  # tracelint: disable=TL001 -- test fixture
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert fs == []

    def test_suppression_without_reason_is_tl000_and_keeps_finding(
            self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def step(w, g):
                lr = float(g)  # tracelint: disable=TL001
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert sorted(rules_of(fs)) == ["TL000", "TL001"]


# ------------------------------------------------------------------ #
# TL002 — donated buffer read after dispatch
# ------------------------------------------------------------------ #

class TestTL002Donation:
    def test_read_after_donating_dispatch(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                out = fn(w, g)
                return w + out
        """)
        assert rules_of(fs) == ["TL002"]
        assert "`w`" in fs[0].message

    def test_producer_method_indirection(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            class Step:
                def _make(self):
                    return jax.jit(add, donate_argnums=(1,))

                def run(self, w, g):
                    fn = self._make()
                    out = fn(w, g)
                    return g + out
        """)
        assert rules_of(fs) == ["TL002"]
        assert "`g`" in fs[0].message

    def test_rebind_from_result_is_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                w = fn(w, g)
                return w + 1
        """)
        assert fs == []

    def test_phase_polymorphic_producer_intersects(self, tmp_path):
        # the FusedStep._compile regression: a compiler returning
        # different jits per phase must not union donated positions
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            class Step:
                def _make(self, phase):
                    if phase == "micro":
                        return jax.jit(add, donate_argnums=(0,))
                    return jax.jit(add, donate_argnums=(1,))

                def run(self, w, g):
                    fn = self._make("micro")
                    out = fn(w, g)
                    return w + g + out
        """)
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def add(a, b):
                return a + b

            def outer(w, g):
                fn = jax.jit(add, donate_argnums=(0,))
                out = fn(w, g)
                return w + out  # tracelint: disable=TL002 -- fixture
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL003 — retrace hazards
# ------------------------------------------------------------------ #

class TestTL003Retrace:
    def test_list_in_cache_key(self, tmp_path):
        fs = lint(tmp_path, """
            def lookup(cache, shape):
                opts = [shape]
                key = (shape, opts)
                return cache.get(key)
        """)
        assert rules_of(fs) == ["TL003"]
        assert "a list" in fs[0].message

    def test_lambda_and_id_keys(self, tmp_path):
        fs = lint(tmp_path, """
            def store(cache, f, shape):
                cache[(shape, lambda x: x)] = 1
                cache[(id(f), shape)] = 2
        """)
        assert sorted(rules_of(fs)) == ["TL003", "TL003"]
        msgs = " ".join(f.message for f in fs)
        assert "lambda" in msgs and "identity key" in msgs

    def test_jit_inside_loop(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def build(fns):
                outs = []
                for f in fns:
                    outs.append(jax.jit(f))
                return outs
        """)
        assert "TL003" in rules_of(fs)
        assert "inside a loop" in fs[0].message

    def test_hashable_key_and_hoisted_jit_are_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def get(cache, arr, training, hyper_key):
                key = (tuple(arr.shape), str(arr.dtype), training,
                       hyper_key)
                fn = cache.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x + 1)
                    cache[key] = fn
                return fn
        """)
        assert fs == []

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            def store(cache, f, shape):
                # bounded registry, evicted on pickle:
                # tracelint: disable=TL003 -- fixture justification
                cache[(id(f), shape)] = 2
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL004 — lock discipline
# ------------------------------------------------------------------ #

class TestTL004Locks:
    def test_unlocked_mutation_of_protected_field(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    self._items.clear()
        """)
        assert rules_of(fs) == ["TL004"]
        assert "_items" in fs[0].message and "drop" in fs[0].message

    def test_lock_order_inversion(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class AB:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._x = 0

                def one(self):
                    with self._a:
                        with self._b:
                            self._x = 1

                def two(self):
                    with self._b:
                        with self._a:
                            self._x = 2
        """)
        assert rules_of(fs) == ["TL004"]
        assert "inversion" in fs[0].message

    def test_consistent_locking_and_init_are_fine(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []      # pre-sharing: exempt

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    with self._lock:
                        self._items.clear()

                def peek(self):
                    return len(self._items)  # read, not mutation
        """)
        assert fs == []

    def test_module_level_lock(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            _lock = threading.Lock()
            _registry = {}

            def put(k, v):
                with _lock:
                    _registry[k] = v

            def drop(k):
                _registry.pop(k)
        """)
        assert rules_of(fs) == ["TL004"]
        assert "_registry" in fs[0].message

    def test_suppressed(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                def drop(self):
                    self._items.clear()  # tracelint: disable=TL004 -- fixture
        """)
        assert fs == []


# ------------------------------------------------------------------ #
# TL005 — env-hatch registry
# ------------------------------------------------------------------ #

class TestTL005EnvRegistry:
    def _docs(self, tmp_path):
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        f = d / "ENV_VARS.md"
        f.write_text("| Variable | Default | Effect |\n|---|---|---|\n"
                     "| `MXNET_DOCUMENTED` | 1 | real |\n"
                     "| `MXNET_STALE` | 1 | nobody reads me |\n")
        return str(f)

    def test_undocumented_read_and_stale_row(self, tmp_path):
        docs = self._docs(tmp_path)
        fs = lint(tmp_path, """
            import os

            a = os.environ.get("MXNET_DOCUMENTED", "1")
            b = os.environ.get("MXNET_SECRET", "0")
        """, env_docs=docs)
        assert sorted(rules_of(fs)) == ["TL005", "TL005"]
        msgs = " ".join(f.message for f in fs)
        assert "MXNET_SECRET" in msgs and "MXNET_STALE" in msgs
        assert "MXNET_DOCUMENTED" not in msgs

    def test_registered_and_documented_is_clean(self, tmp_path):
        d = tmp_path / "docs"
        d.mkdir()
        (d / "ENV_VARS.md").write_text("| `MXNET_IGNORED_COMPAT` | 1 | "
                                       "accepted, no-op |\n")
        fs = lint(tmp_path, """
            from mxnet_tpu.base import register_env

            register_env("MXNET_IGNORED_COMPAT", 1, "no-op")
        """, env_docs=str(d / "ENV_VARS.md"))
        assert fs == []

    def test_prose_mentions_are_not_documentation(self, tmp_path):
        # a var named in a row's PROSE cell (not the first cell) is a
        # reference, not a doc row — it must not mask a stale/missing row
        d = tmp_path / "docs"
        d.mkdir()
        (d / "ENV_VARS.md").write_text(
            "| `MXNET_REAL` | 1 | replaces `MXNET_LEGACY_PROSE` |\n")
        fs = lint(tmp_path, """
            import os

            a = os.environ.get("MXNET_REAL")
        """, env_docs=str(d / "ENV_VARS.md"))
        assert fs == []


# ------------------------------------------------------------------ #
# the tier-1 gate: self-run, seeded violation, baseline
# ------------------------------------------------------------------ #

class TestGate:
    def test_self_run_is_clean(self):
        """THE CI gate: tracelint over the library must stay clean at
        merge — a regression in trace discipline fails tier-1."""
        r = cli(["mxnet_tpu/", "--format=json"])
        assert r.returncode == 0, f"tracelint found:\n{r.stdout}\n{r.stderr}"
        payload = json.loads(r.stdout)
        assert payload["findings"] == []

    def test_seeded_float_loss_fails_gate(self, tmp_path):
        """Acceptance check: a synthetic host sync in a fused-step body
        is caught (the analyzer sees through jax.jit(apply, ...))."""
        src = open(os.path.join(
            REPO, "mxnet_tpu", "gluon", "fused_step.py")).read()
        needle = ("            outs, grads, new_frozen = "
                  "pure(key, train_vals, frozen_vals,\n")
        assert needle in src
        seeded = src.replace(
            needle, needle.rstrip("\n") + "\n                loss_val = "
            "float(outs[0])  # seeded violation\n", 1)
        bad = tmp_path / "fused_step_seeded.py"
        bad.write_text(seeded)
        r = cli([str(bad), "--format=json"])
        assert r.returncode == 1
        payload = json.loads(r.stdout)
        assert any(f["rule"] == "TL001" and "float" in f["message"]
                   for f in payload["findings"])

    def test_baseline_lands_rule_warn_only(self, tmp_path):
        """--baseline lets a future rule land without failing the gate:
        recorded fingerprints are ignored, fresh findings are not."""
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            def step(w, g):
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """))
        base = tmp_path / "baseline.json"
        r = cli([str(bad), "--write-baseline", str(base)])
        assert r.returncode == 0 and base.exists()
        r = cli([str(bad), "--baseline", str(base)])
        assert r.returncode == 0, r.stdout
        # a NEW violation is still caught through the same baseline
        bad.write_text(bad.read_text().replace(
            "return w - lr * g", "return w - lr * g.item()"))
        r = cli([str(bad), "--baseline", str(base), "--format=json"])
        assert r.returncode == 1
        assert any(f["rule"] == "TL001" and "item" in f["message"]
                   for f in json.loads(r.stdout)["findings"])

    def test_select_restricts_rules(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            def step(w, g):
                return w - float(g) * g

            fn = jax.jit(step)
        """))
        assert cli([str(bad), "--select", "TL004"]).returncode == 0
        assert cli([str(bad), "--select", "TL001"]).returncode == 1
        assert cli([str(bad), "--select", "TL999"]).returncode == 2


class TestReviewRegressions:
    """Post-review regression net: partial-tree TL005, nested-class
    TL004 attribution, suppression markers inside string literals."""

    def test_single_file_lint_has_no_stale_doc_false_positives(self):
        # the natural lint-the-file-I-edited workflow: env vars read
        # elsewhere in the repo must not be reported as stale doc rows
        r = cli(["mxnet_tpu/gluon/data/dataloader.py", "--format=json"])
        assert r.returncode == 0, r.stdout
        assert json.loads(r.stdout)["findings"] == []

    def test_nested_class_owns_its_own_lock_discipline(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class Outer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def push(self, x):
                    with self._lock:
                        self._items.append(x)

                class Inner:  # unrelated single-threaded helper
                    def __init__(self):
                        self._items = []

                    def drop(self):
                        self._items.clear()
        """)
        assert fs == []

    def test_suppression_marker_inside_string_is_not_a_suppression(
            self, tmp_path):
        # core.py's own TL000 help text quotes the syntax; a string
        # must neither raise TL000 nor suppress the next line
        fs = lint(tmp_path, """
            import jax

            HELP = "write '# tracelint: disable=TLxxx -- reason'"

            def step(w, g):
                msg = "see '# tracelint: disable=TL001 -- like this'"
                lr = float(g)
                return w - lr * g

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TL001"]

    def test_self_lint_of_tracelint_itself(self):
        # the analyzer's own sources (which quote the suppression
        # syntax in strings/docstrings) must lint clean
        r = cli(["tools/tracelint/", "--format=json"])
        assert r.returncode == 0, r.stdout
