"""Model zoo tests (reference tests/python/unittest/test_gluon_model_zoo.py).

Forward passes use thumbnail/small inputs to stay fast on the CPU-mesh CI
runner; the full 224/299 forwards of every family were validated on build
(all produce (N, classes) logits).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import get_model
from mxnet_tpu.gluon.model_zoo.vision import get_resnet


class TestGetModel:
    def test_unknown_raises(self):
        with pytest.raises(MXNetError):
            get_model("resnet9000")

    def test_registry_families_construct(self):
        # one representative per family constructs + has params
        for name in ["resnet34_v2", "vgg13", "alexnet", "densenet169",
                     "squeezenet1.1", "inceptionv3", "mobilenet0.5",
                     "mobilenetv2_0.5"]:
            net = get_model(name, classes=10)
            assert len(net.collect_params()) > 0, name


class TestForward:
    def test_resnet18_v1_thumbnail_cifar(self):
        net = get_resnet(1, 18, thumbnail=True, classes=10)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(2, 3, 32, 32).astype(onp.float32))
        y = net(x)
        assert y.shape == (2, 10)

    def test_resnet18_v2_thumbnail(self):
        net = get_resnet(2, 18, thumbnail=True, classes=10)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(2, 3, 32, 32).astype(onp.float32))
        assert net(x).shape == (2, 10)

    def test_resnet_hybridize_matches_eager(self):
        net = get_resnet(1, 18, thumbnail=True, classes=10)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(2, 3, 32, 32).astype(onp.float32))
        ref = net(x)  # eager (and settles BN batch stats usage: predict)
        net.hybridize()
        out = net(x)
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                    rtol=1e-4, atol=1e-4)

    def test_vgg11_small(self):
        net = get_model("vgg11", classes=10)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(1, 3, 32, 32).astype(onp.float32))
        assert net(x).shape == (1, 10)

    def test_resnet50_bottleneck_shapes(self):
        net = get_resnet(1, 50, thumbnail=True, classes=4)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(1, 3, 32, 32).astype(onp.float32))
        assert net(x).shape == (1, 4)

    def test_resnet_trains(self):
        from mxnet_tpu import gluon, autograd
        net = get_resnet(1, 18, thumbnail=True, classes=10)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        x = mx.nd.array(onp.random.rand(4, 3, 32, 32).astype(onp.float32))
        y = mx.nd.array(onp.array([0, 1, 2, 3], onp.float32))
        losses = []
        for _ in range(3):
            with autograd.record():
                L = loss_fn(net(x), y)
            L.backward()
            trainer.step(4)
            losses.append(float(L.mean().asnumpy()))
        assert losses[-1] < losses[0]


class TestNHWCLayout:
    """layout="NHWC" (the TPU-preferred channels-last execution mode)
    must be numerically identical to NCHW — same NCHW input contract,
    same OIHW parameters, one stem transpose inside."""

    def test_resnet_nhwc_matches_nchw(self):
        x = mx.nd.array(onp.random.RandomState(0)
                        .rand(2, 3, 32, 32).astype("float32"))
        outs = {}
        for lay in ("NCHW", "NHWC"):
            mx.random.seed(0)
            net = get_resnet(1, 18, classes=10, layout=lay)
            net.initialize(mx.init.Xavier())
            net.hybridize()
            outs[lay] = net(x).asnumpy()
        onp.testing.assert_allclose(outs["NHWC"], outs["NCHW"],
                                    rtol=2e-5, atol=2e-5)

    def test_resnet_nhwc_trains(self):
        from mxnet_tpu import gluon
        mx.random.seed(0)
        net = get_resnet(1, 18, classes=4, layout="NHWC")
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        x = mx.nd.array(onp.random.RandomState(1)
                        .rand(8, 3, 32, 32).astype("float32"))
        y = mx.nd.array(onp.arange(8, dtype=onp.float32) % 4)
        losses = []
        for _ in range(4):
            with mx.autograd.record():
                l = L(net(x), y).mean()
            l.backward()
            tr.step(8)
            losses.append(float(onp.asarray(l.asnumpy())))
        assert losses[-1] < losses[0]
