"""Unified runtime telemetry (ISSUE 9): metrics registry, event log,
compile watch, exporters, profiler-facade delegation, and the
telemetry_report invariant checker.

Conventions: the registry and event ring are process-global, so tests
use test-unique metric names / event sites and measure deltas instead
of absolute values."""
import json
import os
import subprocess
import sys
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError


# --------------------------------------------------------------------- #
# registry
# --------------------------------------------------------------------- #

class TestRegistry:
    def test_counter_gauge_roundtrip(self):
        c = telemetry.counter("t_reg_counter", case="a")
        c.inc()
        c.inc(4)
        assert c.value == 5
        # same (name, labels) -> same instrument; different labels don't
        assert telemetry.counter("t_reg_counter", case="a") is c
        assert telemetry.counter("t_reg_counter", case="b") is not c
        g = telemetry.gauge("t_reg_gauge")
        g.set(2.5)
        g.add(0.5)
        assert g.value == 3.0

    def test_kind_collision_raises(self):
        telemetry.counter("t_reg_kind")
        # one exposition series per (name, labels): re-requesting it as
        # another instrument kind is a caller error, not a second metric
        with pytest.raises(TypeError, match="registered as a counter"):
            telemetry.gauge("t_reg_kind")
        telemetry.gauge("t_reg_kind", other="label")  # distinct labels ok

    def test_histogram_buckets_and_summary(self):
        h = telemetry.histogram("t_reg_hist")
        for v in (0.001, 0.003, 0.02, 0.4):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 4
        assert s["min"] == pytest.approx(0.001)
        assert s["max"] == pytest.approx(0.4)
        assert 0.001 <= s["p50"] <= 0.4
        assert s["p99"] <= 0.4    # clamped to observed max
        assert h.quantile(0.0) == pytest.approx(0.001)

    def test_histogram_empty_summary(self):
        h = telemetry.histogram("t_reg_hist_empty")
        s = h.summary()
        assert s["count"] == 0 and s["p50"] is None and s["mean"] is None

    def test_concurrent_counter_increments_not_lost(self):
        """The registry's core contract: concurrent inc() from N
        threads loses nothing (the serve scheduler + consumer threads
        both hit these)."""
        c = telemetry.counter("t_reg_concurrent")
        N, per = 8, 5000

        def work():
            for _ in range(per):
                c.inc()

        ts = [threading.Thread(target=work) for _ in range(N)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == N * per

    def test_prometheus_render(self):
        c = telemetry.counter("t_prom_counter", arm="x")
        c.inc(3)
        h = telemetry.histogram("t_prom_hist")
        h.observe(0.002)
        text = telemetry.render_prometheus()
        assert "# TYPE t_prom_counter counter" in text
        assert 't_prom_counter{arm="x"} 3' in text
        assert 't_prom_hist_bucket{le="+Inf"} 1' in text
        assert "t_prom_hist_count 1" in text

    def test_prometheus_hostile_label_values_escaped(self):
        """Satellite (ISSUE 10): label VALUES are escaped per the text
        exposition format — a backslash-laden path, an embedded quote,
        or a newline in a label (error strings end up in labels) must
        not break the scrape line."""
        c = telemetry.counter("t_prom_escape", path="a\\b",
                              msg='say "hi"\nline2')
        c.inc()
        text = telemetry.render_prometheus()
        line = next(l for l in text.splitlines()
                    if l.startswith("t_prom_escape{"))
        # labels sort by key: msg before path
        assert line == ('t_prom_escape{msg="say \\"hi\\"\\nline2",'
                        'path="a\\\\b"} 1')
        # every sample stays one line: the newline was escaped
        assert "\nline2" not in line

    def test_snapshot_and_reset(self):
        c = telemetry.counter("t_snap_counter")
        c.inc(7)
        rows = telemetry.snapshot()["t_snap_counter"]
        assert rows[0]["value"] == 7 and rows[0]["kind"] == "counter"
        telemetry.reset_metrics()
        assert c.value == 0   # cached references stay valid


# --------------------------------------------------------------------- #
# event log
# --------------------------------------------------------------------- #

class TestEvents:
    def test_emit_ring_and_filter(self):
        telemetry.emit("t_ev_kind", n=1)
        telemetry.emit("t_ev_kind", n=2)
        telemetry.emit("t_ev_other")
        evs = telemetry.events("t_ev_kind")
        assert [e["n"] for e in evs[-2:]] == [1, 2]
        assert all(e["kind"] == "t_ev_kind" for e in evs)
        assert all("ts" in e for e in telemetry.events())

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("MXNET_TELEMETRY", "0")
        assert telemetry.emit("t_ev_disabled") is None
        assert telemetry.events("t_ev_disabled") == []

    def test_jsonl_sink_writes_lines(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        sink = telemetry.add_jsonl_sink(path)
        try:
            telemetry.emit("t_ev_sink", value=onp.int32(3))
        finally:
            telemetry.remove_sink(sink)
        telemetry.emit("t_ev_sink", value=4)  # after detach: not written
        with open(path) as fh:
            rows = [json.loads(ln) for ln in fh]
        assert len(rows) == 1
        assert rows[0]["kind"] == "t_ev_sink"
        assert rows[0]["value"] == 3          # numpy scalar serialized

    def test_broken_sink_is_dropped_not_fatal(self):
        def bad(_ev):
            raise RuntimeError("boom")

        telemetry.add_sink(bad)
        with pytest.warns(UserWarning, match="sink"):
            telemetry.emit("t_ev_broken")
        telemetry.emit("t_ev_broken")   # sink gone, no warning needed
        assert len(telemetry.events("t_ev_broken")) >= 2


# --------------------------------------------------------------------- #
# compile watch
# --------------------------------------------------------------------- #

class TestCompileWatch:
    def test_compile_event_once_then_retrace_on_new_signature(self):
        import jax
        import jax.numpy as jnp

        fn = telemetry.instrument_jit(
            jax.jit(lambda x: x * 2), "t.compile", key="k",
            fields={"extra": "f"})
        before = len(telemetry.events("compile"))
        fn(jnp.ones(3))
        fn(jnp.ones(3))   # cache hit: no new event
        evs = [e for e in telemetry.events("compile")
               if e.get("site") == "t.compile"]
        assert len(telemetry.events("compile")) == before + 1
        assert evs[-1]["key"] == "k" and evs[-1]["extra"] == "f"
        assert evs[-1]["cache_size"] == 1
        assert "retrace" not in evs[-1]
        assert evs[-1]["wall_s"] > 0
        # a NEW signature is a retrace: second event, flagged
        fn(jnp.ones(5))
        evs = [e for e in telemetry.events("compile")
               if e.get("site") == "t.compile"]
        assert len(evs) == 2 and evs[-1]["retrace"] is True
        assert telemetry.counter("retraces_total",
                                 site="t.compile").value >= 1

    def test_disabled_returns_fn_unwrapped(self, monkeypatch):
        import jax

        jitted = jax.jit(lambda x: x + 1)
        monkeypatch.setenv("MXNET_TELEMETRY", "0")
        assert telemetry.instrument_jit(jitted, "t.off") is jitted
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        assert telemetry.instrument_jit(jitted, "t.on") is not jitted
        # non-jit callables pass through untouched
        plain = lambda x: x  # noqa: E731
        assert telemetry.instrument_jit(plain, "t.plain") is plain

    def test_wrapper_delegates_jit_surface(self):
        import jax
        import jax.numpy as jnp

        fn = telemetry.instrument_jit(jax.jit(lambda x: x - 1),
                                      "t.delegate")
        fn(jnp.ones(2))
        assert fn._cache_size() == 1      # the retrace-pin API
        lowered = fn.lower(jnp.ones(2))   # the AOT API
        assert lowered is not None

    def test_hlo_ops_recorded_under_env(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("MXNET_TELEMETRY_HLO", "1")
        fn = telemetry.instrument_jit(
            jax.jit(lambda x: jnp.tanh(x) @ x), "t.hlo")
        fn(jnp.ones((4, 4)))
        ev = [e for e in telemetry.events("compile")
              if e.get("site") == "t.hlo"][-1]
        assert ev["hlo_ops"] > 0

    def test_donated_buffers_survive_hlo_count(self, monkeypatch):
        """MXNET_TELEMETRY_HLO recomputes HLO from shape structs —
        it must not dereference the just-donated input buffer."""
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("MXNET_TELEMETRY_HLO", "1")
        fn = telemetry.instrument_jit(
            jax.jit(lambda x: x * 3, donate_argnums=(0,)), "t.donate")
        out = fn(jnp.ones(8))
        ev = [e for e in telemetry.events("compile")
              if e.get("site") == "t.donate"][-1]
        assert ev["hlo_ops"] > 0
        assert float(out[0]) == 3.0


# --------------------------------------------------------------------- #
# span / annotation bridging
# --------------------------------------------------------------------- #

class TestSpan:
    def test_span_observes_histogram(self):
        with telemetry.span("t_span_phase") as h:
            pass
        assert h is telemetry.histogram("t_span_phase_seconds")
        assert h.count == 1

    def test_annotation_is_noop_without_profiler(self):
        with telemetry.annotation("t_ann"):
            pass   # nullcontext — nothing to assert beyond no crash


# --------------------------------------------------------------------- #
# profiler facade (satellites)
# --------------------------------------------------------------------- #

class TestProfilerFacade:
    def test_set_config_unknown_key_raises(self):
        with pytest.raises(MXNetError, match="profile_imperativ"):
            mx.profiler.set_config(profile_imperativ=True)
        # known keys still work
        mx.profiler.set_config(aggregate_stats=True)

    def test_counter_delegates_to_registry(self):
        c = mx.profiler.Counter(name="t_prof_counter", value=3)
        c += 2
        c.decrement(1)
        assert c.value == 4
        g = telemetry.gauge("profiler_counter",
                            counter="t_prof_counter")
        assert g.value == 4

    def test_marker_emits_event(self):
        before = len(telemetry.events("marker"))
        mx.profiler.Marker(name="t_prof_marker").mark()
        evs = telemetry.events("marker")
        assert len(evs) == before + 1
        assert evs[-1]["name"] == "t_prof_marker"

    def test_dumps_reset_concurrent_no_lost_rows(self):
        """Satellite: ``dumps(reset=True)`` swaps the aggregate while
        dispatch threads record — every recorded row must appear in
        exactly one returned table (none lost to the swap, none
        duplicated across tables)."""
        from mxnet_tpu import profiler

        with profiler._lock:
            profiler._state["op_stats"] = profiler._OpStats()
        N_THREADS, PER = 4, 3000
        done = threading.Event()
        tables = []

        def record(tid):
            for i in range(PER):
                profiler._hook(f"op{tid}", 1e-6)

        def reaper():
            while not done.is_set():
                tables.append(profiler.dumps(reset=True))
            tables.append(profiler.dumps(reset=True))

        reap = threading.Thread(target=reaper)
        reap.start()
        ts = [threading.Thread(target=record, args=(i,))
              for i in range(N_THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        done.set()
        reap.join()

        total = 0
        for table in tables:
            for line in table.splitlines():
                if line.startswith("op"):
                    total += int(line.split()[1])
        assert total == N_THREADS * PER
        with profiler._lock:
            profiler._state["op_stats"] = None

    def test_dumps_reset_still_works_single_threaded(self):
        from mxnet_tpu import profiler

        with profiler._lock:
            profiler._state["op_stats"] = profiler._OpStats()
        profiler._hook("single_op", 0.001)
        table = profiler.dumps(reset=True)
        assert "single_op" in table
        assert "single_op" not in profiler.dumps()
        with profiler._lock:
            profiler._state["op_stats"] = None


# --------------------------------------------------------------------- #
# subsystem wiring
# --------------------------------------------------------------------- #

class TestFusedStepTelemetry:
    def test_fused_step_emits_compile_events_and_metrics(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn

        mx.random.seed(0)
        net = nn.Dense(4, in_units=6)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=None)
        loss_l = gluon.loss.L2Loss()

        def loss_fn(xx, yy):
            return loss_l(net(xx), yy)

        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.rand(2, 6).astype("float32"))
        y = mx.nd.array(rng.rand(2, 4).astype("float32"))
        d = telemetry.counter("fused_step_dispatches_total",
                              phase="apply")
        lat = telemetry.histogram("fused_step_seconds", phase="apply")
        before_d, before_n = d.value, lat.count
        before_c = len([e for e in telemetry.events("compile")
                        if e.get("site") == "gluon.fused_step"])
        trainer.fused_step(loss_fn, x, y)
        trainer.fused_step(loss_fn, x, y)
        comp = [e for e in telemetry.events("compile")
                if e.get("site") == "gluon.fused_step"]
        assert len(comp) == before_c + 1     # one trace, no retrace
        assert comp[-1]["phase"] == "apply"
        assert d.value == before_d + 2
        assert lat.count == before_n + 2

    def test_cached_op_compile_event(self):
        from mxnet_tpu.gluon import nn

        mx.random.seed(0)
        net = nn.Dense(3, in_units=5)
        net.initialize(mx.init.Xavier())
        net.hybridize()
        x = mx.nd.array(onp.random.RandomState(1)
                        .rand(2, 5).astype("float32"))
        before = len([e for e in telemetry.events("compile")
                      if e.get("site") == "gluon.cached_op"])
        net(x)
        net(x)
        comp = [e for e in telemetry.events("compile")
                if e.get("site") == "gluon.cached_op"]
        assert len(comp) == before + 1
        assert comp[-1]["training"] is False

    def test_kv_generate_compile_event(self):
        from mxnet_tpu.models import GPT, GPTConfig, kv_generate

        mx.random.seed(0)
        net = GPT(GPTConfig(vocab_size=61, max_length=32, num_layers=2,
                            units=16, num_heads=2, hidden_size=32))
        net.initialize(mx.init.Normal(0.02))
        prompt = onp.random.RandomState(0).randint(0, 61, (1, 4))
        before = len([e for e in telemetry.events("compile")
                      if e.get("site") == "models.kv_generate"])
        kv_generate(net, prompt, max_new_tokens=3)
        kv_generate(net, prompt, max_new_tokens=3)   # cached: no event
        comp = [e for e in telemetry.events("compile")
                if e.get("site") == "models.kv_generate"]
        assert len(comp) == before + 1
        assert comp[-1]["mode"] == "stacked"


class TestPrefetchTelemetry:
    def test_device_ring_stall_and_depth_metrics(self):
        from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter

        stalls = telemetry.counter("data_prefetch_stalls_total")
        before = stalls.value
        it = DevicePrefetchIter(iter([1, 2, 3]), None, depth=2,
                                background=True)
        out = list(it)
        assert out == [1, 2, 3]
        # the first get had nothing ready — at least one stall counted
        assert stalls.value >= before + 1
        it.close()


class TestServeCounterView:
    def test_view_is_dict_api_over_registry(self):
        from mxnet_tpu.serve.server import _CounterView

        v = _CounterView("t_view_srv")
        assert set(v) == {"step_dispatches", "admit_dispatches",
                          "sync_requests", "pool_grows", "prefix_hits",
                          "cow_copies", "chunk_dispatches",
                          "verify_dispatches", "draft_proposed",
                          "draft_accepted", "draft_rejected"}
        v.inc("step_dispatches")
        v["step_dispatches"] += 2        # MutableMapping read-modify
        assert v["step_dispatches"] == 3
        assert telemetry.counter("serve_step_dispatches_total",
                                 server="t_view_srv").value == 3
        for k in v:
            v[k] = 0                     # the reset_counters idiom
        assert dict(v) == {k: 0 for k in v}
        with pytest.raises(MXNetError):
            del v["step_dispatches"]

    def test_module_aggregate_reset_is_locked(self):
        """Satellite: reset_serve_counters racing _bump loses no
        increments — every bump lands either before a reset (erased
        with the whole aggregate) or after (kept)."""
        from mxnet_tpu.serve import server as srv_mod

        srv_mod.reset_serve_counters()
        STOP = threading.Event()

        def resetter():
            while not STOP.is_set():
                srv_mod.reset_serve_counters()

        t = threading.Thread(target=resetter)
        t.start()
        try:
            for _ in range(20000):
                srv_mod._bump("step_dispatches")
        finally:
            STOP.set()
            t.join()
        srv_mod.reset_serve_counters()
        # the real assertion is the lock discipline (tracelint TL004
        # enforces it statically); dynamically: counts stay consistent
        assert srv_mod.serve_counters["step_dispatches"] == 0


# --------------------------------------------------------------------- #
# telemetry_report
# --------------------------------------------------------------------- #

def _write_jsonl(path, events):
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")


def _serve_stream(step_dispatches=10, steps=10, retrace=False):
    evs = [
        {"ts": 1.0, "kind": "serve_config", "server": "s0",
         "pool_sizes": [2], "admit_sizes": [1, 2],
         "prefill_buckets": [8, 16], "max_total_len": 32,
         "sync_mode": False},
        {"ts": 1.1, "kind": "compile", "site": "serve.step",
         "server": "s0", "pool": 2, "wall_s": 0.5, "cache_size": 1},
        {"ts": 1.2, "kind": "compile", "site": "serve.admit",
         "server": "s0", "pool": 2, "a_bucket": 1, "p_bucket": 8,
         "wall_s": 0.4, "cache_size": 1},
        {"ts": 1.3, "kind": "serve_admit", "server": "s0", "wave": 1,
         "a_bucket": 1, "p_bucket": 8, "pool": 2, "occupancy": 0.5},
        {"ts": 1.4, "kind": "serve_request", "server": "s0",
         "request_id": 0, "reason": "max_len", "tokens": 5,
         "ttft_s": 0.01, "queue_wait_s": 0.001, "wave": 1,
         "a_bucket": 1, "p_bucket": 8, "occupancy_at_admit": 0.5},
        {"ts": 2.0, "kind": "serve_stats", "server": "s0",
         "steps": steps, "occupancy": 0.8,
         "counters": {"step_dispatches": step_dispatches,
                      "admit_dispatches": 1, "sync_requests": 0,
                      "pool_grows": 0}},
        {"ts": 2.1, "kind": "bench", "bench": "serve",
         "mode": "saturated", "tokens_per_sec": 100.0},
    ]
    if retrace:
        evs.insert(3, {"ts": 1.25, "kind": "compile",
                       "site": "serve.admit", "server": "s0",
                       "pool": 2, "a_bucket": 1, "p_bucket": 8,
                       "wall_s": 0.4, "cache_size": 2, "retrace": True})
    return evs


class TestTelemetryReport:
    def test_summary_and_check_pass(self, tmp_path):
        sys.path.insert(0, "/root/repo")
        from tools import telemetry_report

        path = str(tmp_path / "ok.jsonl")
        _write_jsonl(path, _serve_stream())
        events = telemetry_report.load(path)
        assert telemetry_report.check_serve(events) == []
        text = telemetry_report.render(events)
        assert "serve.admit" in text and "serve requests" in text
        assert "bench rows" in text

    def test_check_flags_dispatch_mismatch(self, tmp_path):
        from tools import telemetry_report

        path = str(tmp_path / "bad.jsonl")
        _write_jsonl(path, _serve_stream(step_dispatches=12, steps=10))
        fails = telemetry_report.check_serve(telemetry_report.load(path))
        assert any("12 step dispatches" in f for f in fails)

    def test_check_flags_retrace(self, tmp_path):
        from tools import telemetry_report

        path = str(tmp_path / "retrace.jsonl")
        _write_jsonl(path, _serve_stream(retrace=True))
        fails = telemetry_report.check_serve(telemetry_report.load(path))
        assert any("retrace" in f for f in fails)

    def test_check_flags_ladder_overflow(self, tmp_path):
        from tools import telemetry_report

        evs = _serve_stream()
        for i in range(8):   # 9 admit compiles > 1*2*2 ladder product
            evs.append({"ts": 3.0 + i, "kind": "compile",
                        "site": "serve.admit", "server": "s0",
                        "pool": 2, "a_bucket": 2, "p_bucket": 16 + i,
                        "wall_s": 0.1, "cache_size": 1})
        path = str(tmp_path / "ladder.jsonl")
        _write_jsonl(path, evs)
        fails = telemetry_report.check_serve(telemetry_report.load(path))
        assert any("ladder" in f for f in fails)

    def test_cli_roundtrip(self, tmp_path):
        path = str(tmp_path / "cli.jsonl")
        _write_jsonl(path, _serve_stream())
        r = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", path,
             "--check-serve"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert r.returncode == 0, r.stderr
        assert "serve checks OK" in r.stdout
        r2 = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", path,
             "--json"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert r2.returncode == 0
        parsed = json.loads(r2.stdout)
        assert parsed["events"] == len(_serve_stream())
