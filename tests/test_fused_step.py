"""Fused train step (Trainer.fused_step / gluon/fused_step.py).

Parity: N micro-batches of size B through the fused, gradient-
accumulating step (``Trainer(update_interval=N)``) must match ONE batch
of size N*B through the legacy phase-by-phase path (record → tape
backward → step) for SGD/Adam/AdamW including multi-precision — the
grads are sums over the same N*B samples and the apply rescales once by
1/(N*B) on both paths.  f32 comparisons use tight allclose (fused
forward+backward is one XLA program; reassociation differs from the
tape walk by ulps).

Dispatch-count regression (ISSUE 4 acceptance): every fused_step call is
exactly ONE XLA executable dispatch, the optimizer apply runs exactly
once per update interval, zero ops go through the registry (no tape) in
steady state, and the executable cache stops growing after the first
window.

Satellites: Trainer.zero_grad, effective-batch rescale on accumulated
step(), mid-accumulation-window errors from allreduce_grads()/update(),
MXNET_FUSED_STEP=0 escape hatch, estimator fused fit, benchmark smoke
gates.
"""
import os
import subprocess
import sys

import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.fused_step import (fused_step_enabled,
                                        reset_step_counters,
                                        step_counters)
from mxnet_tpu.optimizer.optimizer import (apply_counters,
                                           reset_apply_counters)

_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _build_net(seed=0, units=8, depth=3, bn=False, dtype=None):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        for _ in range(depth):
            net.add(nn.Dense(units, activation="relu", in_units=units))
            if bn:
                net.add(nn.BatchNorm(in_channels=units))
        net.add(nn.Dense(1, in_units=units))
    net.initialize(mx.init.Xavier())
    if dtype is not None:
        net.cast(dtype)
    return net


def _data(n, units=8, dtype=onp.float32, seed=0):
    rng = onp.random.RandomState(seed)
    return (rng.randn(n, units).astype(dtype),
            rng.randn(n, 1).astype(dtype))


def _params_np(net):
    return [onp.asarray(p.data()._data, onp.float32)
            for p in net.collect_params().values()]


def _run_fused(opt, opt_params, N, B, X, Y, windows=2, seed=0, bn=False,
               dtype=None, cast=None):
    net = _build_net(seed=seed, bn=bn, dtype=dtype)
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), opt, dict(opt_params),
                       kvstore=None, update_interval=N)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    for w in range(windows):
        for j in range(N):
            sl = slice(j * B, (j + 1) * B)
            xb, yb = X[sl], Y[sl]
            if cast:
                xb, yb = xb.astype(cast), yb.astype(cast)
            loss = tr.fused_step(loss_fn, mx.nd.array(xb), mx.nd.array(yb))
    return net, tr, loss


def _run_legacy_big_batch(opt, opt_params, NB, X, Y, windows=2, seed=0,
                          dtype=None, cast=None):
    net = _build_net(seed=seed, dtype=dtype)
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), opt, dict(opt_params),
                       kvstore=None)
    xb, yb = (X.astype(cast), Y.astype(cast)) if cast else (X, Y)
    for w in range(windows):
        with mx.autograd.record():
            loss = loss_l(net(mx.nd.array(xb)), mx.nd.array(yb))
        loss.backward()
        tr.step(NB)
    return net, tr, loss


# --------------------------------------------------------------------- #
# parity: N micro-batches (fused, accumulated) == 1 batch of N*B (legacy)
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("opt", ["sgd", "adam", "adamw"])
@pytest.mark.parametrize("N", [1, 4])
def test_accumulated_fused_matches_legacy_big_batch(opt, N):
    B = 4
    X, Y = _data(N * B)
    kw = {"learning_rate": 0.05, "wd": 0.01}
    if opt == "sgd":
        kw["momentum"] = 0.9
    netf, _, _ = _run_fused(opt, kw, N, B, X, Y)
    netl, _, _ = _run_legacy_big_batch(opt, kw, N * B, X, Y)
    for i, (a, b) in enumerate(zip(_params_np(netf), _params_np(netl))):
        onp.testing.assert_allclose(
            a, b, rtol=2e-5, atol=1e-6,
            err_msg=f"{opt} N={N} param {i}: fused-accum != legacy-NB")


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_accumulated_fused_multi_precision(opt):
    """bf16 weights + fp32 master: the fused accumulated step keeps the
    weight bf16, carries the f32 master, and tracks the legacy big-batch
    mp path (bf16-scale tolerance on weights, tight on masters)."""
    N, B = 2, 4
    X, Y = _data(N * B)
    kw = {"learning_rate": 0.05, "multi_precision": True}
    netf, trf, _ = _run_fused(opt, kw, N, B, X, Y, dtype="bfloat16",
                              cast=jnp.bfloat16)
    netl, trl, _ = _run_legacy_big_batch(opt, kw, N * B, X, Y,
                                         dtype="bfloat16",
                                         cast=jnp.bfloat16)
    for p, s in zip([p for p in netf.collect_params().values()
                     if p.grad_req != "null"],
                    [trf._states[i] for i in trf._fused_steps[
                        list(trf._fused_steps)[0]]._train_idx]):
        assert p.data()._data.dtype == jnp.bfloat16
        assert isinstance(s, tuple) and s[0].dtype == jnp.float32
    masters_f = [s[0] for s in trf._states if isinstance(s, tuple)]
    masters_l = [s[0] for s in trl._states if isinstance(s, tuple)]
    assert masters_f and len(masters_f) == len(masters_l)
    # masters advance in f32 but FROM bf16 gradients: N micro-batch
    # grads (bf16 rounding per chunk) vs one N*B-batch grad differ at
    # bf16 epsilon (~4e-3 relative) before the f32 apply even starts
    for i, (a, b) in enumerate(zip(masters_f, masters_l)):
        onp.testing.assert_allclose(
            onp.asarray(a), onp.asarray(b), rtol=1e-2, atol=1e-4,
            err_msg=f"{opt} master {i}")
    for i, (a, b) in enumerate(zip(_params_np(netf), _params_np(netl))):
        onp.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2,
                                    err_msg=f"{opt} bf16 weight {i}")


def test_fused_clip_gradient_parity():
    N, B = 2, 4
    X, Y = _data(N * B)
    kw = {"learning_rate": 0.05, "clip_gradient": 0.05}
    netf, _, _ = _run_fused("adam", kw, N, B, X, Y)
    netl, _, _ = _run_legacy_big_batch("adam", kw, N * B, X, Y)
    for a, b in zip(_params_np(netf), _params_np(netl)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_env_hatch_restores_phase_by_phase(monkeypatch):
    """MXNET_FUSED_STEP=0: fused_step runs record → tape backward →
    Trainer.step — same weights as hand-written phases, zero executable
    dispatches, and parity with the env=1 fused result."""
    B = 8
    X, Y = _data(B)
    kw = {"learning_rate": 0.05}
    netf, _, _ = _run_fused("adam", kw, 1, B, X, Y)

    monkeypatch.setenv("MXNET_FUSED_STEP", "0")
    assert not fused_step_enabled()
    reset_step_counters()
    netl, _, _ = _run_fused("adam", kw, 1, B, X, Y)
    assert step_counters["legacy_steps"] == 2
    assert step_counters["dispatches"] == 0
    for a, b in zip(_params_np(netf), _params_np(netl)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)

    # and the hand-written phase loop lands on identical weights
    net2 = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr2 = gluon.Trainer(net2.collect_params(), "adam", dict(kw),
                        kvstore=None)
    for _ in range(2):
        with mx.autograd.record():
            loss = loss_l(net2(mx.nd.array(X)), mx.nd.array(Y))
        loss.backward()
        tr2.step(B)
    for a, b in zip(_params_np(netl), _params_np(net2)):
        onp.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_env_hatch_accumulation_parity(monkeypatch):
    """The fallback also accumulates: N micro-batches with
    MXNET_FUSED_STEP=0 (grad_req='write' host accumulation) match the
    big-batch update."""
    N, B = 3, 4
    X, Y = _data(N * B)
    kw = {"learning_rate": 0.05}
    monkeypatch.setenv("MXNET_FUSED_STEP", "0")
    netf, _, _ = _run_fused("sgd", kw, N, B, X, Y)
    monkeypatch.delenv("MXNET_FUSED_STEP")
    netl, _, _ = _run_legacy_big_batch("sgd", kw, N * B, X, Y)
    for a, b in zip(_params_np(netf), _params_np(netl)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_batchnorm_aux_updates_match_legacy_micro_path(monkeypatch):
    """BN moving stats update once per micro-batch inside the executable
    (staged aux, committed after the call) — identical to the legacy
    per-micro path (env hatch), which is the right reference for aux
    state (a big batch updates BN stats once, not N times)."""
    B = 8
    X, Y = _data(2 * B)
    kw = {"learning_rate": 0.05}
    netf, _, _ = _run_fused("sgd", kw, 2, B, X, Y, windows=1, bn=True)
    monkeypatch.setenv("MXNET_FUSED_STEP", "0")
    netl, _, _ = _run_fused("sgd", kw, 2, B, X, Y, windows=1, bn=True)
    for (n, pf), pl in zip(netf.collect_params().items(),
                           netl.collect_params().values()):
        onp.testing.assert_allclose(
            onp.asarray(pf.data()._data, onp.float32),
            onp.asarray(pl.data()._data, onp.float32),
            rtol=2e-5, atol=1e-6, err_msg=n)


# --------------------------------------------------------------------- #
# dispatch-count regression (acceptance criterion)
# --------------------------------------------------------------------- #

def test_dispatch_count_one_executable_per_step_one_apply_per_interval():
    """Acceptance: on the fused path every fused_step call is exactly ONE
    XLA executable dispatch; the optimizer apply (and its donated-buffer
    weight update) runs exactly once per update interval; the standalone
    multi_update path is never dispatched (the apply is folded into the
    step executable)."""
    N, B = 4, 4
    X, Y = _data(N * B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore=None,
                       update_interval=N)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    # warm: compile micro + apply executables over one window
    for j in range(N):
        sl = slice(j * B, (j + 1) * B)
        tr.fused_step(loss_fn, mx.nd.array(X[sl]), mx.nd.array(Y[sl]))
    reset_step_counters()
    reset_apply_counters()
    windows = 2
    for w in range(windows):
        for j in range(N):
            sl = slice(j * B, (j + 1) * B)
            tr.fused_step(loss_fn, mx.nd.array(X[sl]), mx.nd.array(Y[sl]))
    assert step_counters["dispatches"] == windows * N      # 1 per call
    assert step_counters["apply_dispatches"] == windows    # 1 per interval
    assert step_counters["micro_dispatches"] == windows * (N - 1)
    assert step_counters["compiles"] == 0                  # steady state
    assert apply_counters["fused_calls"] == 0              # apply folded in
    assert apply_counters["fallback_params"] == 0


def test_no_registry_dispatch_in_steady_state(monkeypatch):
    """Steady state never re-enters Python op dispatch: zero
    ops.registry.invoke calls during a fused step (the loss_fn is only
    re-run when a new signature forces a retrace)."""
    from mxnet_tpu.ops import registry as reg

    B = 8
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))  # compile
    calls = {"n": 0}
    orig = reg.invoke

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(reg, "invoke", counting)
    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))
    assert calls["n"] == 0


def test_signature_change_retraces_once():
    B = 8
    X, Y = _data(2 * B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    reset_step_counters()
    tr.fused_step(loss_fn, mx.nd.array(X[:B]), mx.nd.array(Y[:B]))
    assert step_counters["compiles"] == 1
    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))  # new shape
    assert step_counters["compiles"] == 2
    tr.fused_step(loss_fn, mx.nd.array(X[:B]), mx.nd.array(Y[:B]))
    assert step_counters["compiles"] == 2  # both signatures cached


def test_lr_change_is_an_operand_not_a_retrace():
    B = 8
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))
    reset_step_counters()
    before = _params_np(net)
    tr.set_learning_rate(0.0)  # freeze: next update must be a no-op
    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))
    assert step_counters["compiles"] == 0
    for a, b in zip(before, _params_np(net)):
        onp.testing.assert_allclose(a, b, rtol=0, atol=1e-7)


def test_sgld_falls_back():
    """SGLD's host-RNG update rule opts the whole step out of fusion."""
    B = 4
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgld",
                       {"learning_rate": 0.01}, kvstore=None)
    reset_step_counters()
    tr.fused_step(lambda x, y: loss_l(net(x), y),
                  mx.nd.array(X), mx.nd.array(Y))
    assert step_counters["legacy_steps"] == 1
    assert step_counters["dispatches"] == 0


# --------------------------------------------------------------------- #
# Trainer satellites: zero_grad, accumulated step(), mid-window errors
# --------------------------------------------------------------------- #

def test_trainer_zero_grad_resets_add_accumulators():
    net = _build_net()
    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.grad_req = "add"
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    X, Y = _data(4)
    for _ in range(2):
        with mx.autograd.record():
            loss_l(net(mx.nd.array(X)), mx.nd.array(Y)).backward()
    g = [p for p in net.collect_params().values()
         if p.grad_req != "null"][0].grad().asnumpy()
    assert onp.abs(g).max() > 0
    tr.zero_grad()
    for p in net.collect_params().values():
        if p.grad_req != "null":
            assert onp.abs(p.grad().asnumpy()).max() == 0


def test_step_accumulated_add_rescales_by_effective_batch_once():
    """grad_req='add' + Trainer(update_interval=N): N backwards then N
    step() calls -> ONE update rescaled by 1/(N*B), matching the big
    batch; mid-window step() calls are pure accounting; the boundary
    auto-resets the 'add' accumulators."""
    N, B = 3, 4
    X, Y = _data(N * B)

    net = _build_net()
    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.grad_req = "add"
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None,
                       update_interval=N)
    before = _params_np(net)
    for j in range(N):
        sl = slice(j * B, (j + 1) * B)
        with mx.autograd.record():
            loss_l(net(mx.nd.array(X[sl])), mx.nd.array(Y[sl])).backward()
        mid = _params_np(net)
        tr.step(B)
        if j < N - 1:  # mid-window: no weight motion
            for a, b in zip(mid, _params_np(net)):
                onp.testing.assert_allclose(a, b, rtol=0, atol=0)
    assert any(onp.abs(a - b).max() > 0
               for a, b in zip(before, _params_np(net)))
    # boundary reset the accumulators
    for p in net.collect_params().values():
        if p.grad_req != "null":
            assert onp.abs(p.grad().asnumpy()).max() == 0

    netl, _, _ = _run_legacy_big_batch("sgd", {"learning_rate": 0.05},
                                       N * B, X, Y, windows=1)
    for a, b in zip(_params_np(net), _params_np(netl)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_step_with_write_grads_mid_window_raises():
    """update_interval>1 + grad_req='write' + step(): each backward
    would OVERWRITE the accumulating grads — step() fails loudly at the
    window's first call instead of silently dropping micro-batches."""
    X, Y = _data(4)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None,
                       update_interval=2)
    with mx.autograd.record():
        loss_l(net(mx.nd.array(X)), mx.nd.array(Y)).backward()
    with pytest.raises(MXNetError, match="grad_req='add'"):
        tr.step(4)


def test_allreduce_and_update_raise_mid_window():
    N, B = 4, 4
    X, Y = _data(B)
    net = _build_net()
    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.grad_req = "add"
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None,
                       update_interval=N)
    with mx.autograd.record():
        loss_l(net(mx.nd.array(X)), mx.nd.array(Y)).backward()
    tr.step(B)  # micro-batch 1 of 4 — window now open
    with pytest.raises(MXNetError, match="mid-accumulation window"):
        tr.allreduce_grads()
    with pytest.raises(MXNetError, match="mid-accumulation window"):
        tr.update(B)
    # finishing the window closes it again
    for _ in range(N - 1):
        with mx.autograd.record():
            loss_l(net(mx.nd.array(X)), mx.nd.array(Y)).backward()
        tr.step(B)
    tr.allreduce_grads()  # boundary: allowed


def test_update_interval_validation():
    net = _build_net()
    with pytest.raises(MXNetError, match="update_interval"):
        gluon.Trainer(net.collect_params(), "sgd", {}, update_interval=0)


# --------------------------------------------------------------------- #
# integration: extras, estimator, state checkpointing
# --------------------------------------------------------------------- #

def test_loss_fn_extras_ride_through():
    """loss_fn returning (loss, pred): extras come back as NDArrays from
    the same single dispatch, matching the imperative forward."""
    B = 8
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.0}, kvstore=None)

    def loss_fn(x, y):
        pred = net(x)
        return loss_l(pred, y), pred

    expect = net(mx.nd.array(X)).asnumpy()  # lr=0: weights frozen
    loss, pred = tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))
    assert loss.shape == (B,)
    onp.testing.assert_allclose(pred.asnumpy(), expect, rtol=1e-5,
                                atol=1e-6)


def test_estimator_fused_fit_path():
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X, Y = _data(16)
    net = _build_net(seed=5)
    net.hybridize()
    loss_l = gluon.loss.L2Loss()
    est = Estimator(net, loss_l,
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 0.05}),
                    fused_step=True)
    dl = DataLoader(ArrayDataset(mx.nd.array(X), mx.nd.array(Y)),
                    batch_size=8)
    reset_step_counters()
    est.fit(dl, epochs=2)
    assert step_counters["apply_dispatches"] == 4  # 2 epochs x 2 batches
    assert step_counters["legacy_steps"] == 0


def test_estimator_fused_fit_matches_legacy_fit():
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X, Y = _data(16)

    def run(fused):
        net = _build_net(seed=6)
        net.hybridize()
        est = Estimator(net, gluon.loss.L2Loss(),
                        trainer=gluon.Trainer(net.collect_params(),
                                              "adam",
                                              {"learning_rate": 0.05}),
                        fused_step=fused)
        dl = DataLoader(ArrayDataset(mx.nd.array(X), mx.nd.array(Y)),
                        batch_size=8, shuffle=False)
        est.fit(dl, epochs=2)
        return _params_np(net)

    for a, b in zip(run(True), run(False)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_save_load_states_after_fused_steps(tmp_path):
    B = 4
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore=None)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    for _ in range(3):
        tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))
    assert tr._optimizer.num_update == 3
    f = str(tmp_path / "t.states")
    tr.save_states(f)
    tr.load_states(f)
    assert tr._optimizer.num_update == 3
    # and fused + imperative paths interoperate on the same state list
    with mx.autograd.record():
        loss_fn(mx.nd.array(X), mx.nd.array(Y)).backward()
    tr.step(B)
    assert tr._optimizer.num_update == 4


def test_mixed_fused_and_imperative_steps_share_window():
    """fused_step and step() drive the same accumulation window."""
    N, B = 2, 4
    X, Y = _data(B)
    net = _build_net()
    loss_l = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None,
                       update_interval=N)

    def loss_fn(x, y):
        return loss_l(net(x), y)

    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))  # micro 1/2
    assert tr._window_pos == 1
    with pytest.raises(MXNetError, match="mid-accumulation window"):
        tr.allreduce_grads()
    tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y))  # boundary
    assert tr._window_pos == 0


def test_data_sharded_fused_step_matches_unsharded():
    """data_sharding=dp_sharding(mesh): the batch is laid over the dp
    axis, weights/states are replicated onto the mesh at build, and
    GSPMD compiles the cross-replica grad reduction INTO the step —
    same weights as the single-device fused step, still one dispatch."""
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 8})
    sh = collectives.dp_sharding(mesh)
    B = 16
    X, Y = _data(B)
    loss_l = gluon.loss.L2Loss()

    def run(data_sharding):
        net = _build_net(seed=7)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore=None)

        def loss_fn(x, y):
            return loss_l(net(x), y)

        for _ in range(3):
            tr.fused_step(loss_fn, mx.nd.array(X), mx.nd.array(Y),
                          data_sharding=data_sharding)
        return net

    nets = run(sh)
    reset_step_counters()
    netu = run(None)
    assert step_counters["dispatches"] == 3
    for a, b in zip(_params_np(nets), _params_np(netu)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


# --------------------------------------------------------------------- #
# benchmark smoke gates (tier-1)
# --------------------------------------------------------------------- #

def _run_bench(args):
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, cwd="/root/repo", env=_ENV,
                          timeout=570)


class TestFusedStepBenchSmoke:
    def test_step_profile_smoke(self):
        r = _run_bench(["benchmark/step_profile.py", "--smoke"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "fused step, N=1" in r.stdout
        assert "phase-by-phase" in r.stdout

    def test_step_breakdown_smoke(self):
        r = _run_bench(["benchmark/step_breakdown.py", "--smoke"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "train_step_fused_1" in r.stdout
        assert "train_step_phase" in r.stdout
