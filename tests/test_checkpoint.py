"""Orbax checkpoint tests (SURVEY.md §5.3/§5.4 TPU-native answer:
sharded/async checkpoints + auto-resume)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _net_and_trainer():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    return net, trainer


def _train(net, trainer, x, y, steps):
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        trainer.step(x.shape[0])


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        x = mx.nd.array(onp.random.rand(4, 5).astype(onp.float32))
        y = mx.nd.array(onp.random.rand(4, 3).astype(onp.float32))
        net, trainer = _net_and_trainer()
        _train(net, trainer, x, y, 3)
        ref = net(x).asnumpy()
        mx.checkpoint.save(str(tmp_path), 3, net, trainer)

        net2, tr2 = _net_and_trainer()
        net2(x)
        step = mx.checkpoint.restore(str(tmp_path), net2, tr2)
        assert step == 3
        onp.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-6)
        # optimizer state restored → continued training is bit-identical
        _train(net, trainer, x, y, 1)
        _train(net2, tr2, x, y, 1)
        onp.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy(),
                                    rtol=1e-6)

    def test_auto_resume_empty_dir(self, tmp_path):
        net, _ = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path / "none"), net) is None

    def test_manager_retention(self, tmp_path):
        net, _ = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path), max_to_keep=2)
        for s in (1, 2, 3):
            mgr.save(s, net)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        assert len(mgr.all_steps()) <= 2
        mgr.close()
