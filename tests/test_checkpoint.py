"""Atomic/async checkpoint + bit-exact resume tests (ISSUE 15).

Covers: the commit-or-invisible protocol (corrupt/truncated/interrupted
checkpoints are skipped loudly, never loaded, never crash auto-resume),
async save donation safety, bit-exact mid-window resume (params,
optimizer, accumulator ring, RNG, loss scaler), restore-time resharding
across meshes, the data-pipeline cursor (``DataLoader.iter_from`` fast
forward), the new fault-injection sites, and the Estimator's
``AtomicCheckpointHandler``.
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _net_and_trainer():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    return net, trainer


def _train(net, trainer, x, y, steps):
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        trainer.step(x.shape[0])


def _params_np(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


def _fused_rig(units=6, update_interval=2, seed=0):
    """Deterministic fused-step training rig: (net, trainer, step_fn)
    where step_fn(x, y) runs one fused step with a per-step RNG draw
    (so the checkpointed root key is load-bearing)."""
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(units, use_bias=False, in_units=units))
        net.add(nn.Dense(2, use_bias=False, in_units=units))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, kvstore=None,
                            update_interval=update_interval)
    loss_l = gluon.loss.L2Loss()

    def loss_fn(bx, by):
        return loss_l(net(bx), by)

    def step_fn(x, y):
        noise = mx.random.normal(shape=x.shape) * 0.01
        return trainer.fused_step(loss_fn, x + noise, y)

    return net, trainer, step_fn


def _batches(n, bs=4, units=6, seed=3):
    rng = onp.random.RandomState(seed)
    return [(mx.nd.array(rng.rand(bs, units).astype(onp.float32)),
             mx.nd.array(rng.rand(bs, 2).astype(onp.float32)))
            for _ in range(n)]


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        x = mx.nd.array(onp.random.rand(4, 5).astype(onp.float32))
        y = mx.nd.array(onp.random.rand(4, 3).astype(onp.float32))
        net, trainer = _net_and_trainer()
        _train(net, trainer, x, y, 3)
        ref = net(x).asnumpy()
        mx.checkpoint.save(str(tmp_path), 3, net, trainer)

        net2, tr2 = _net_and_trainer()
        net2(x)
        step = mx.checkpoint.restore(str(tmp_path), net2, tr2)
        assert step == 3
        onp.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-6)
        # optimizer state restored → continued training is bit-identical
        _train(net, trainer, x, y, 1)
        _train(net2, tr2, x, y, 1)
        onp.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy(),
                                    rtol=1e-6)

    def test_auto_resume_empty_dir(self, tmp_path):
        net, _ = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path / "none"), net) is None

    def test_manager_retention(self, tmp_path):
        net, _ = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path), max_to_keep=2)
        for s in (1, 2, 3):
            mgr.save(s, net)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        assert len(mgr.all_steps()) <= 2
        mgr.close()


class TestAtomicity:
    """Commit-or-invisible: only a complete, checksum-clean step dir is
    ever loaded; everything else is a loud checkpoint_corrupt event and
    a fallback, never a crash."""

    def _saved_dir(self, tmp_path, steps=(1, 2)):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        for s in steps:
            mx.checkpoint.save(str(tmp_path), s, net, trainer)
        return net, trainer

    def test_truncated_array_falls_back(self, tmp_path):
        net, trainer = self._saved_dir(tmp_path)
        step2 = tmp_path / "step_00000002"
        victim = sorted(step2.glob("arr_*.npy"))[0]
        victim.write_bytes(victim.read_bytes()[:-7])
        mx.telemetry.clear_events()
        net2, tr2 = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2, tr2) == 1
        evs = mx.telemetry.events(kind="checkpoint_corrupt")
        assert evs and "truncated" in evs[-1]["why"]

    def test_bitflip_checksum_falls_back(self, tmp_path):
        self._saved_dir(tmp_path)
        victim = sorted((tmp_path / "step_00000002").glob("arr_*.npy"))[-1]
        data = bytearray(victim.read_bytes())
        data[-1] ^= 0xFF
        victim.write_bytes(bytes(data))
        mx.telemetry.clear_events()
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) == 1
        evs = mx.telemetry.events(kind="checkpoint_corrupt")
        assert evs and "checksum" in evs[-1]["why"]

    def test_missing_manifest_falls_back(self, tmp_path):
        self._saved_dir(tmp_path)
        (tmp_path / "step_00000002" / "MANIFEST.json").unlink()
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) == 1
        assert mx.checkpoint.latest_step(str(tmp_path)) == 1

    def test_interrupted_tmp_swept_and_reported(self, tmp_path):
        self._saved_dir(tmp_path, steps=(1,))
        ghost = tmp_path / ".tmp-step_00000009-123-deadbeef"
        ghost.mkdir()
        (ghost / "arr_00000.npy").write_bytes(b"partial")
        mx.telemetry.clear_events()
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) == 1
        assert not ghost.exists()
        evs = mx.telemetry.events(kind="checkpoint_corrupt")
        assert evs and "interrupted save" in evs[-1]["why"]

    def test_explicit_corrupt_step_raises(self, tmp_path):
        self._saved_dir(tmp_path)
        victim = sorted((tmp_path / "step_00000002").glob("arr_*.npy"))[0]
        victim.write_bytes(b"")
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        with pytest.raises(MXNetError, match="failed.*verification|"
                                             "verification"):
            mx.checkpoint.restore(str(tmp_path), net2, step=2)
        with pytest.raises(MXNetError, match="no step 9"):
            mx.checkpoint.restore(str(tmp_path), net2, step=9)

    def test_verify_step_api(self, tmp_path):
        self._saved_dir(tmp_path)
        ok, why = mx.checkpoint.verify_step(str(tmp_path), 2)
        assert ok and why is None
        victim = sorted((tmp_path / "step_00000002").glob("arr_*.npy"))[0]
        victim.write_bytes(victim.read_bytes()[:-1])
        ok, why = mx.checkpoint.verify_step(str(tmp_path), 2)
        assert not ok and "truncated" in why

    def test_all_corrupt_returns_none(self, tmp_path):
        self._saved_dir(tmp_path, steps=(1,))
        (tmp_path / "step_00000001" / "MANIFEST.json").write_text("{nope")
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) is None


class TestAsyncSave:
    def test_async_snapshot_is_donation_safe(self, tmp_path):
        """save() snapshots device→host before returning, so training
        steps dispatched immediately after (which DONATE the very same
        param/state/accumulator buffers into the next executable)
        cannot corrupt the in-flight checkpoint: the restored values
        equal the values at save time, not the later ones."""
        net, trainer, step_fn = _fused_rig()
        batches = _batches(6)
        for x, y in batches[:2]:
            step_fn(x, y)
        at_save = _params_np(net)
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path),
                                              async_save=True)
        mgr.save(2, net, trainer)
        for x, y in batches[2:]:   # keep training while the write runs
            step_fn(x, y)
        mgr.wait_until_finished()
        mgr.close()
        assert not onp.allclose(
            at_save["0.weight"], _params_np(net)["0.weight"])
        net2, tr2, _ = _fused_rig(seed=9)
        assert mx.checkpoint.restore(str(tmp_path), net2, tr2) == 2
        for k, v in _params_np(net2).items():
            onp.testing.assert_array_equal(v, at_save[k])

    def test_background_write_error_surfaces(self, tmp_path,
                                             monkeypatch):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path),
                                              async_save=True)
        monkeypatch.setattr(
            mgr, "_write_step",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk gone")))
        mgr.save(1, net, trainer)
        with pytest.raises(MXNetError, match="background save failed"):
            mgr.wait_until_finished()
        mgr.close()

    def test_restore_does_not_sweep_own_live_tmp(self, tmp_path):
        """Post-review regression: restore() during an in-flight async
        save must not destroy the writer's own temp dir — only DEAD
        processes' leftovers (different pid) are swept."""
        net, trainer = self._rig(tmp_path)
        own = tmp_path / f".tmp-step_00000009-{os.getpid()}-abcd1234"
        own.mkdir()
        dead = tmp_path / ".tmp-step_00000009-99999999-abcd1234"
        dead.mkdir()
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) == 1
        assert own.exists() and not dead.exists()

    def _rig(self, tmp_path):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        return net, trainer

    def test_close_timeout_on_live_writer_raises(self, tmp_path,
                                                 monkeypatch):
        """Post-review regression: close() must not silently abandon a
        writer still mid-write — the pending save has not committed."""
        import time as _time

        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path),
                                              async_save=True)
        monkeypatch.setattr(mgr, "_write_step",
                            lambda *a, **k: _time.sleep(3.0))
        mgr.save(1, net, trainer)
        with pytest.raises(MXNetError, match="still writing"):
            mgr.close(timeout=0.2)

    def test_checkpoint_saved_event_fields(self, tmp_path):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mx.telemetry.clear_events()
        mx.checkpoint.save(str(tmp_path), 7, net, trainer)
        evs = mx.telemetry.events(kind="checkpoint_saved")
        assert len(evs) == 1
        ev = evs[0]
        assert ev["step"] == 7 and ev["bytes"] > 0
        assert ev["snapshot_s"] >= 0 and ev["write_s"] > 0


class TestBitExactResume:
    def test_mid_window_resume_is_bit_exact(self, tmp_path):
        """Kill-and-resume == uninterrupted, at a MID-WINDOW save:
        the checkpoint carries the accumulation-window position and the
        donated device accumulator ring, the optimizer schedule
        counters, and the RNG root key — continuing from the restore
        reproduces the uninterrupted run's params and states exactly."""
        batches = _batches(6)
        net, trainer, step_fn = _fused_rig(update_interval=2)
        for x, y in batches[:3]:          # step 3 = mid-window
            step_fn(x, y)
        assert trainer._window_pos == 1
        mx.checkpoint.save(str(tmp_path), 3, net, trainer)
        for x, y in batches[3:]:
            step_fn(x, y)
        ref_params = _params_np(net)
        ref_nu = trainer._optimizer.num_update

        net2, tr2, step_fn2 = _fused_rig(update_interval=2, seed=5)
        step = mx.checkpoint.restore(str(tmp_path), net2, tr2)
        assert step == 3 and tr2._window_pos == 1
        for x, y in batches[3:]:
            step_fn2(x, y)
        for k, v in _params_np(net2).items():
            onp.testing.assert_array_equal(v, ref_params[k])
        assert tr2._optimizer.num_update == ref_nu
        import jax
        for s1, s2, made in zip(trainer._states, tr2._states,
                                trainer._states_created):
            if made:
                for l1, l2 in zip(jax.tree.leaves(s1),
                                  jax.tree.leaves(s2)):
                    onp.testing.assert_array_equal(
                        onp.asarray(jax.device_get(l1)),
                        onp.asarray(jax.device_get(l2)))

    def test_boundary_resume_is_bit_exact(self, tmp_path):
        batches = _batches(6)
        net, trainer, step_fn = _fused_rig(update_interval=2)
        for x, y in batches[:4]:          # step 4 = window boundary
            step_fn(x, y)
        mx.checkpoint.save(str(tmp_path), 4, net, trainer)
        for x, y in batches[4:]:
            step_fn(x, y)
        ref = _params_np(net)
        net2, tr2, step_fn2 = _fused_rig(update_interval=2, seed=5)
        assert mx.checkpoint.restore(str(tmp_path), net2, tr2) == 4
        assert tr2._window_pos == 0
        for x, y in batches[4:]:
            step_fn2(x, y)
        for k, v in _params_np(net2).items():
            onp.testing.assert_array_equal(v, ref[k])

    def test_mid_window_save_without_ring_refuses(self, tmp_path):
        """The imperative (non-fused) accumulation window lives in the
        'add' grad buffers a checkpoint does not capture — a mid-window
        save there must refuse loudly, not silently drop the partial
        window."""
        net, trainer = _net_and_trainer()
        x = mx.nd.ones((2, 5))
        net(x)
        trainer._update_interval = 2
        trainer._window_pos = 1    # simulate the imperative mid-window
        with pytest.raises(MXNetError, match="mid-accumulation-window"):
            mx.checkpoint.save(str(tmp_path), 1, net, trainer)

    def test_loss_scaler_state_roundtrip(self, tmp_path):
        from mxnet_tpu.amp import LossScaler

        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        trainer._amp_loss_scaler = LossScaler(init_scale=2.0 ** 10)
        trainer._amp_loss_scaler.loss_scale = 384.0
        trainer._amp_loss_scaler._unskipped = 17
        mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        net2, tr2 = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        tr2._amp_loss_scaler = LossScaler()
        mx.checkpoint.restore(str(tmp_path), net2, tr2)
        assert tr2._amp_loss_scaler.loss_scale == 384.0
        assert tr2._amp_loss_scaler._unskipped == 17

    def test_rng_state_roundtrip(self):
        mx.random.seed(123)
        mx.random.uniform(shape=(3,))
        st = mx.random.get_state()
        a = mx.random.uniform(shape=(4,)).asnumpy()
        b = mx.random.uniform(shape=(4,)).asnumpy()
        mx.random.set_state(st)
        onp.testing.assert_array_equal(
            mx.random.uniform(shape=(4,)).asnumpy(), a)
        onp.testing.assert_array_equal(
            mx.random.uniform(shape=(4,)).asnumpy(), b)

    def test_save_states_mid_window_raises(self, tmp_path):
        """Satellite: Trainer.save_states/load_states keep the same
        mid-window contract as allreduce_grads() — the pickle cannot
        capture the partial window, so it refuses instead of saving a
        state that desyncs on load."""
        net, trainer, step_fn = _fused_rig(update_interval=2)
        x, y = _batches(1)[0]
        step_fn(x, y)              # window_pos -> 1
        fname = str(tmp_path / "states")
        with pytest.raises(MXNetError, match="save_states\\(\\) called "
                                             "mid-accumulation"):
            trainer.save_states(fname)
        with pytest.raises(MXNetError, match="load_states\\(\\) called "
                                             "mid-accumulation"):
            trainer.load_states(fname)
        step_fn(*_batches(1)[0])   # complete the window
        assert trainer._window_pos == 0
        trainer.save_states(fname)
        step_fn(x, y)              # start a new window...
        fs = next(iter(trainer._fused_steps.values()))
        assert fs._accum is not None
        trainer._window_pos = 0    # ...reach a boundary, then load
        trainer.load_states(fname)
        # a clean load resets the window and drops the stale ring
        assert trainer._window_pos == 0 and fs._accum is None


class TestResharding:
    def _mesh(self):
        import jax
        from mxnet_tpu import parallel

        return parallel.make_mesh({"dp": len(jax.devices())})

    def _sharded_rig(self, seed=0):
        from jax.sharding import NamedSharding, PartitionSpec

        net, trainer, step_fn = _fused_rig(units=8, seed=seed)
        mesh = self._mesh()
        sh = NamedSharding(mesh, PartitionSpec("dp"))
        repl = NamedSharding(mesh, PartitionSpec())
        for p in net.collect_params().values():
            p.set_sharding(sh if p.shape[0] % 8 == 0 else repl)
        return net, trainer, step_fn, sh

    def test_sharded_save_restores_on_single_device(self, tmp_path):
        """8-device mesh → 1-device placement: arrays are stored as
        full logical values, so restore just places them with the
        target param's (absent) sharding."""
        net, trainer, step_fn, _ = self._sharded_rig()
        x, y = _batches(1, units=8)[0]
        step_fn(x, y)
        step_fn(*_batches(1, units=8, seed=5)[0])
        ref = _params_np(net)
        mx.checkpoint.save(str(tmp_path), 2, net, trainer)
        net2, tr2, _ = _fused_rig(units=8, seed=9)   # unsharded target
        assert mx.checkpoint.restore(str(tmp_path), net2, tr2) == 2
        for k, v in _params_np(net2).items():
            onp.testing.assert_allclose(v, ref[k], rtol=1e-6)

    def test_unsharded_save_restores_onto_mesh(self, tmp_path):
        net, trainer, _ = _fused_rig(units=8)
        net(_batches(1, units=8)[0][0])
        ref = _params_np(net)
        mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        net2, tr2, _, sh = self._sharded_rig(seed=9)
        assert mx.checkpoint.restore(str(tmp_path), net2, tr2) == 1
        for name, p in net2._collect_params_with_prefix().items():
            onp.testing.assert_allclose(p.data().asnumpy(), ref[name],
                                        rtol=1e-6)
            if p.shape[0] % 8 == 0:
                assert p._data._data.sharding == sh   # resharded, not
                # silently replicated

    def test_shape_mismatch_names_both_meshes(self, tmp_path):
        net, trainer, _ = _fused_rig(units=8)
        net(_batches(1, units=8)[0][0])
        mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        net2, _, _ = _fused_rig(units=4, seed=9)
        net2(_batches(1, units=4)[0][0])
        with pytest.raises(MXNetError) as ei:
            mx.checkpoint.restore(str(tmp_path), net2)
        assert "mesh" in str(ei.value) and "shape" in str(ei.value)


class _CountingDataset(gluon.data.dataset.Dataset):
    def __init__(self, n, units=6):
        rng = onp.random.RandomState(0)
        self._x = rng.rand(n, units).astype(onp.float32)
        self.fetched = []

    def __getitem__(self, idx):
        self.fetched.append(int(idx))
        return self._x[idx]

    def __len__(self):
        return len(self._x)


class TestDataCursor:
    def test_iter_from_matches_tail(self):
        ds = _CountingDataset(20)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        full = [b.asnumpy() for b in loader]
        tail = [b.asnumpy() for b in loader.iter_from(2)]
        assert len(tail) == len(full) - 2
        for a, b in zip(full[2:], tail):
            onp.testing.assert_array_equal(a, b)

    def test_iter_from_never_loads_skipped(self):
        ds = _CountingDataset(20)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        list(loader.iter_from(3))
        assert min(ds.fetched) == 12   # batches 0..2 never touched

    def test_iter_from_rollover_refuses(self):
        """Post-review regression: rollover carries leftover indices
        across epochs in process memory — a resume cannot reconstruct
        them, so iter_from refuses instead of silently shifting batch
        boundaries."""
        ds = _CountingDataset(10)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False,
                                       last_batch="rollover")
        with pytest.raises(MXNetError, match="rollover"):
            loader.iter_from(1)
        assert len(list(loader)) == 2   # plain iteration unaffected

    def test_iter_from_past_end_raises(self):
        ds = _CountingDataset(8)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        with pytest.raises(MXNetError, match="past the end"):
            loader.iter_from(3)

    def test_seeded_random_sampler_resumes(self):
        from mxnet_tpu.gluon.data.sampler import RandomSampler

        s1 = RandomSampler(16, seed=7)
        epoch0 = list(s1)
        epoch1 = list(s1)
        assert epoch0 != epoch1
        s2 = RandomSampler(16, seed=7)
        s2.set_epoch(1)
        assert list(s2) == epoch1

    def test_seeded_shuffle_iter_from_reproduces_epoch_tail(self):
        from mxnet_tpu.gluon.data.sampler import RandomSampler

        ds = _CountingDataset(20)
        loader = gluon.data.DataLoader(
            ds, batch_size=4, sampler=RandomSampler(20, seed=3))
        full = [b.asnumpy() for b in loader]            # epoch 0
        loader.set_epoch(0)
        tail = [b.asnumpy() for b in loader.iter_from(2)]
        for a, b in zip(full[2:], tail):
            onp.testing.assert_array_equal(a, b)

    # -- elastic re-bucketing (ISSUE 19): iter_shard ------------------- #

    def test_iter_shard_union_partitions_remaining_batches(self):
        """The pod cursor contract: global batch g (>= cursor) belongs
        to exactly one rank — the union of every rank's stream is the
        remaining epoch, each batch exactly once, in global order."""
        ds = _CountingDataset(32)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        full = [b.asnumpy() for b in loader]
        for world in (1, 2, 3):
            got = {}
            for rank in range(world):
                for i, b in enumerate(
                        loader.iter_shard(2, world, rank)):
                    g = 2 + i * world + rank
                    assert g not in got      # never re-served
                    got[g] = b.asnumpy()
            assert sorted(got) == list(range(2, len(full)))  # no skips
            for g, b in got.items():
                onp.testing.assert_array_equal(b, full[g])

    def test_iter_shard_rebucket_on_shrunk_world(self):
        """Elastic resume: 2 ranks consume 4 global batches, the pod
        shrinks, 1 survivor resumes at cursor 4 — every sample of the
        epoch is served exactly once across the two generations."""
        ds = _CountingDataset(32)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        served = []
        for rank in range(2):                      # generation 0: W=2
            it = loader.iter_shard(0, 2, rank)
            served += [next(it).asnumpy() for _ in range(2)]
        for b in loader.iter_shard(4, 1, 0):       # generation 1: W=1
            served.append(b.asnumpy())
        assert len(served) == 8
        # every dataset index loaded exactly once over both generations
        assert sorted(ds.fetched) == list(range(32))

    def test_iter_shard_never_loads_foreign_batches(self):
        """A rank draws every index (the shared sampler must advance
        in lockstep) but only LOADS its own shard's samples."""
        ds = _CountingDataset(16)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        list(loader.iter_shard(0, 2, 1))
        assert sorted(set(ds.fetched)) == [4, 5, 6, 7, 12, 13, 14, 15]

    def test_iter_shard_validates(self):
        ds = _CountingDataset(8)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        with pytest.raises(MXNetError, match="shard"):
            loader.iter_shard(0, 2, 2)
        with pytest.raises(MXNetError, match="past the end"):
            loader.iter_shard(3, 2, 0)
        roll = gluon.data.DataLoader(ds, batch_size=4, shuffle=False,
                                     last_batch="rollover")
        with pytest.raises(MXNetError, match="rollover"):
            roll.iter_shard(0, 2, 0)

    def test_iter_shard_seeded_shuffle_matches_full_epoch(self):
        from mxnet_tpu.gluon.data.sampler import RandomSampler

        ds = _CountingDataset(24)
        loader = gluon.data.DataLoader(
            ds, batch_size=4, sampler=RandomSampler(24, seed=3))
        full = [b.asnumpy() for b in loader]            # epoch 0
        for rank in range(2):
            loader.set_epoch(0)
            for i, b in enumerate(loader.iter_shard(0, 2, rank)):
                onp.testing.assert_array_equal(b.asnumpy(),
                                               full[i * 2 + rank])


class TestFaultSites:
    @pytest.fixture(autouse=True)
    def _clean(self, monkeypatch):
        from mxnet_tpu.telemetry.faults import reset_faults

        monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
        reset_faults()
        yield
        monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
        reset_faults()

    def test_checkpoint_save_site_aborts_before_commit(self, tmp_path,
                                                       monkeypatch):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        monkeypatch.setenv("MXNET_FAULT_INJECT",
                           "checkpoint.save:raise:1")
        with pytest.raises(MXNetError, match="injected fault"):
            mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        # nothing committed: the fault fires AFTER the temp write,
        # BEFORE the rename — the step must be invisible, and the
        # failed writer cleaned up its own temp dir
        assert mx.checkpoint.latest_step(str(tmp_path)) is None
        assert not list(tmp_path.glob(".tmp-*"))
        monkeypatch.delenv("MXNET_FAULT_INJECT")
        from mxnet_tpu.telemetry.faults import reset_faults

        reset_faults()
        mx.checkpoint.save(str(tmp_path), 2, net, trainer)
        net2, _ = _net_and_trainer()
        net2(mx.nd.ones((1, 5)))
        assert mx.checkpoint.restore(str(tmp_path), net2) == 2

    def test_checkpoint_restore_site(self, tmp_path, monkeypatch):
        net, trainer = _net_and_trainer()
        net(mx.nd.ones((1, 5)))
        mx.checkpoint.save(str(tmp_path), 1, net, trainer)
        monkeypatch.setenv("MXNET_FAULT_INJECT",
                           "checkpoint.restore:raise:1")
        with pytest.raises(MXNetError, match="injected fault"):
            mx.checkpoint.restore(str(tmp_path), net, trainer)

    def test_data_next_site(self, monkeypatch):
        ds = _CountingDataset(20)
        loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
        monkeypatch.setenv("MXNET_FAULT_INJECT", "data.next:raise:3")
        out = []
        with pytest.raises(MXNetError, match="injected fault"):
            for b in loader:
                out.append(b)
        assert len(out) == 2   # died drawing the 3rd batch


class TestAtomicCheckpointHandler:
    def _estimator(self, seed):
        mx.random.seed(seed)
        onp.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(6, use_bias=False, in_units=6),
                    nn.Dense(2, use_bias=False, in_units=6))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-2}, kvstore=None)
        est = gluon.contrib.estimator.Estimator(
            net, gluon.loss.L2Loss(), trainer=trainer)
        return est

    def _loader(self):
        rng = onp.random.RandomState(2)
        ds = gluon.data.ArrayDataset(
            mx.nd.array(rng.rand(16, 6).astype(onp.float32)),
            mx.nd.array(rng.rand(16, 2).astype(onp.float32)))
        return gluon.data.DataLoader(ds, batch_size=4, shuffle=False)

    def test_periodic_save_and_auto_resume(self, tmp_path):
        from mxnet_tpu.gluon.contrib.estimator import \
            AtomicCheckpointHandler

        est = self._estimator(seed=0)
        h = AtomicCheckpointHandler(str(tmp_path), every_n_batches=2,
                                    every_n_epochs=None)
        est.fit(self._loader(), epochs=2, event_handlers=[h])
        assert h.resumed_step is None
        assert mx.checkpoint.latest_step(str(tmp_path)) == 8
        ref = _params_np(est.net)

        est2 = self._estimator(seed=9)    # different init on purpose
        h2 = AtomicCheckpointHandler(str(tmp_path), every_n_batches=2)
        h2.train_begin(est2)
        assert h2.resumed_step == 8 and h2.current_batch == 8
        for k, v in _params_np(est2.net).items():
            onp.testing.assert_array_equal(v, ref[k])
        h2.train_end(est2)

    def test_resume_false_starts_fresh(self, tmp_path):
        from mxnet_tpu.gluon.contrib.estimator import \
            AtomicCheckpointHandler

        est = self._estimator(seed=0)
        h = AtomicCheckpointHandler(str(tmp_path), every_n_epochs=1)
        est.fit(self._loader(), epochs=1, event_handlers=[h])
        est2 = self._estimator(seed=9)
        before = _params_np(est2.net)
        h2 = AtomicCheckpointHandler(str(tmp_path), resume=False)
        h2.train_begin(est2)
        for k, v in _params_np(est2.net).items():
            onp.testing.assert_array_equal(v, before[k])
        h2.train_end(est2)


class TestReportSections:
    def test_telemetry_report_checkpoint_and_restart_sections(
            self, tmp_path):
        """tools/telemetry_report.py renders the new sections from a
        recording alone (the offline-truth contract)."""
        import subprocess
        import sys

        rec = tmp_path / "rec.jsonl"
        events = [
            {"kind": "checkpoint_saved", "dir": "/ck", "step": 3,
             "bytes": 100, "arrays": 4, "snapshot_s": 0.001,
             "write_s": 0.01, "async_save": True},
            {"kind": "checkpoint_corrupt", "dir": "/ck", "step": 4,
             "why": "array arr_00001.npy truncated"},
            {"kind": "checkpoint_restored", "dir": "/ck", "step": 3,
             "arrays": 4},
            {"kind": "pod_restart", "restart": 1, "rank": 0,
             "why": "died_signal", "attempt": 1, "budget": 2,
             "backoff_s": 1.0},
        ]
        rec.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        r = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", str(rec)],
            capture_output=True, text=True, cwd="/root/repo")
        assert r.returncode == 0, r.stderr
        assert "checkpoints" in r.stdout and "pod restarts" in r.stdout
        assert "checkpoint_corrupt" in r.stdout
        r = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", str(rec),
             "--json"],
            capture_output=True, text=True, cwd="/root/repo")
        data = json.loads(r.stdout)
        assert data["checkpoints"][0]["saves"] == 1
        assert data["restarts"][0]["restarts"] == 1


class TestPodCheckpoint:
    """A checkpoint written by a 2-process pod restores onto ONE
    process bit-exactly (ISSUE 19): the elastic supervisor's whole
    recovery story rests on this — the survivor generation loads state
    the bigger mesh wrote, re-placed on the smaller mesh by the
    restore-time resharding path (``parallel.global_put``)."""

    def test_two_process_checkpoint_restores_on_one_process(
            self, tmp_path):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)   # 1 CPU device per launched rank
        env.pop("MXNET_FAULT_INJECT", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        ck = tmp_path / "ck"
        r = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "2",
             "--launcher", "local", "--checkpoint-dir", str(ck),
             sys.executable, "tests/fixtures/dist_pretrain.py",
             "--steps", "3", "--out",
             str(tmp_path / "pod_RANK.npz")],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=repo)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        saved = onp.load(tmp_path / "pod_0.npz")

        # fresh single-process model (this process: 8 virtual devices,
        # process_count == 1) built exactly like the fixture's, but
        # seeded differently so the restore must do ALL the work
        mx.random.seed(99)
        onp.random.seed(99)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, use_bias=False, in_units=8))
            net.add(nn.Dense(1, use_bias=False, in_units=8))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-2})
        mgr = mx.checkpoint.CheckpointManager(str(ck / "rank0"))
        res = mgr.restore(net, trainer, return_extra=True)
        mgr.close()
        assert res is not None
        step, extra = res
        assert step == 3
        assert extra["batch"] == 3 and extra["workers"] == 2

        for name, p in net._collect_params_with_prefix().items():
            onp.testing.assert_array_equal(
                p.data().asnumpy(), saved[f"param:{name}"],
                err_msg=name)
