#!/usr/bin/env python3
"""Generate ``golden.params`` — a byte-level fixture of the public
apache/mxnet NDArray binary format (the ``.params`` file layout).

This generator is deliberately INDEPENDENT of ``mxnet_tpu``: it writes
the bytes with ``struct.pack`` straight from the format specification
(``NDArray::Save`` in the public apache/mxnet ``src/ndarray/ndarray.cc``;
SURVEY.md §5.4a), so the committed fixture pins the on-disk layout the
framework's serializer must produce and parse bit-exactly.

Provenance: the environment has no network and the reference mount is
empty (SURVEY.md §0), so these bytes are derived from the public format
spec, not captured from a live MXNet run.  Layout:

  file := u64 0x112 (kMXAPINDArrayListMagic) | u64 reserved=0
        | u64 n_arrays | n * ndarray_v2_blob
        | u64 n_names  | n * (u64 len | utf8 bytes)
  ndarray_v2_blob := u32 0xF993FAC9 (NDARRAY_V2_MAGIC) | i32 stype(0=dense)
        | u32 ndim | i64 dims[ndim] | i32 devtype(1=cpu) | i32 devid
        | i32 type_flag | raw little-endian data

type_flag: 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64.
"""
import struct
import sys

import numpy as onp


def golden_arrays():
    """The fixture contents, reproducible from seeds/arange."""
    return [
        ("dense_f32", onp.arange(12, dtype=onp.float32).reshape(3, 4) / 8),
        ("vec_f16", onp.asarray([1.5, -2.25, 0.125, 1024.0],
                                dtype=onp.float16)),
        ("ints_i32", onp.asarray([[7, -3], [0, 2**31 - 1]],
                                 dtype=onp.int32)),
        ("small_i8", onp.asarray([[-128, 127]], dtype=onp.int8)),
        ("bytes_u8", onp.arange(256, dtype=onp.uint8).reshape(16, 16)),
    ]


TYPE_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
             "int32": 4, "int8": 5, "int64": 6}


def write_blob(f, arr):
    arr = onp.ascontiguousarray(arr)
    f.write(struct.pack("<I", 0xF993FAC9))          # NDARRAY_V2_MAGIC
    f.write(struct.pack("<i", 0))                   # stype: dense
    f.write(struct.pack("<I", arr.ndim))
    for d in arr.shape:
        f.write(struct.pack("<q", d))
    f.write(struct.pack("<ii", 1, 0))               # saved ctx: cpu(0)
    f.write(struct.pack("<i", TYPE_FLAG[arr.dtype.name]))
    f.write(arr.astype(arr.dtype.newbyteorder("<")).tobytes())


def main(out="golden.params"):
    items = golden_arrays()
    with open(out, "wb") as f:
        f.write(struct.pack("<QQ", 0x112, 0))
        f.write(struct.pack("<Q", len(items)))
        for _name, arr in items:
            write_blob(f, arr)
        f.write(struct.pack("<Q", len(items)))
        for name, _arr in items:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)
    print(f"wrote {out}")


if __name__ == "__main__":
    main(*sys.argv[1:])
