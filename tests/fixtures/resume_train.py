"""Deterministic resumable training worker — the rank program for the
chaos parity tests (ISSUE 15 acceptance).

Trains a small Dense chain with ``Trainer.fused_step`` (gradient
accumulation window ``--update-interval``), drawing per-step RNG noise
(so the checkpointed ``mx.random`` root key is load-bearing), feeding
batches through a ``DataLoader`` whose cursor is checkpointed as
``extra`` and restored with ``iter_from`` (fast-forward, no replay).
Every step is checkpointed (async, atomic).  On start it auto-resumes
from the newest COMPLETE checkpoint; at the end it writes the final
params + optimizer states to ``--out`` as an npz.

Fault arming is per pod-restart generation and per rank::

    --fault 0=checkpoint.save:kill:4 --fault 1=data.next:kill:3

arms ``MXNET_FAULT_INJECT`` with the given spec only when this process's
``mx.checkpoint.restart_count()`` equals the generation index and its
rank equals ``--fault-rank`` — so an injected kill does not recur
forever across supervised restarts (the supervisor never rewrites the
spec; rank code owns it).

Bit-exact contract under test: kill-and-resume (any number of times,
at any site) produces an ``--out`` numerically identical to an
uninterrupted run with the same arguments.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as onp  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--bs", type=int, default=4)
    ap.add_argument("--units", type=int, default=8)
    ap.add_argument("--update-interval", type=int, default=2)
    ap.add_argument("--dir", default=None,
                    help="checkpoint root (default MXNET_CHECKPOINT_DIR)")
    ap.add_argument("--out", required=True)
    ap.add_argument("--out-per-rank", action="store_true",
                    help="substitute the literal 'RANK' in --out with "
                         "this process's rank (multi-rank pods)")
    ap.add_argument("--fault", action="append", default=[],
                    metavar="GEN=SPEC",
                    help="arm MXNET_FAULT_INJECT=SPEC when "
                         "restart_count()==GEN and rank==--fault-rank")
    ap.add_argument("--fault-rank", type=int, default=0)
    args = ap.parse_args()

    rank = int(os.environ.get("MXNET_WORKER_ID", "0"))
    if args.out_per_rank:
        args.out = args.out.replace("RANK", str(rank))

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.heartbeat import start_heartbeat

    gen = mx.checkpoint.restart_count()
    for spec in args.fault:
        g, _, rule = spec.partition("=")
        if int(g) == gen and rank == args.fault_rank:
            os.environ["MXNET_FAULT_INJECT"] = rule
            print(f"[rank {rank} gen {gen}] armed fault {rule}",
                  flush=True)
    start_heartbeat()

    root = args.dir or os.environ.get("MXNET_CHECKPOINT_DIR")
    if not root:
        print("no checkpoint dir (--dir or MXNET_CHECKPOINT_DIR)",
              file=sys.stderr)
        return 2
    ckdir = os.path.join(root, f"rank{rank}")

    # deterministic model + data (both RNGs seeded; the checkpoint's
    # RNG capture takes over from the restore point)
    mx.random.seed(7)
    onp.random.seed(7)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(args.units, use_bias=False, in_units=args.units))
        net.add(nn.Dense(1, use_bias=False, in_units=args.units))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, kvstore=None,
                            update_interval=args.update_interval)
    loss_l = gluon.loss.L2Loss()

    def loss_fn(bx, by):
        return loss_l(net(bx), by)

    rng = onp.random.RandomState(11)
    X = rng.rand(args.steps * args.bs, args.units).astype(onp.float32)
    Y = rng.rand(args.steps * args.bs, 1).astype(onp.float32)
    dataset = gluon.data.ArrayDataset(mx.nd.array(X), mx.nd.array(Y))
    loader = gluon.data.DataLoader(dataset, batch_size=args.bs,
                                   shuffle=False)

    mgr = mx.checkpoint.CheckpointManager(ckdir, max_to_keep=3,
                                          async_save=True)
    start = 0
    res = mgr.restore(net, trainer, return_extra=True)
    if res is not None:
        step, extra = res
        start = int((extra or {}).get("batch", step))
        print(f"[rank {rank} gen {gen}] resumed step {step} "
              f"(cursor {start}, window {trainer._window_pos})",
              flush=True)

    step = start
    for bx, by in loader.iter_from(start):
        # per-step RNG consumption: resume must continue the key stream
        noise = mx.random.normal(shape=(args.bs, args.units)) * 0.01
        trainer.fused_step(loss_fn, bx + noise, by)
        step += 1
        mgr.save(step, net, trainer, extra={"batch": step})
        if step >= args.steps:
            break
    mgr.wait_until_finished()
    mgr.close()

    out = {}
    for name, p in net._collect_params_with_prefix().items():
        out[f"param:{name}"] = onp.asarray(p.data().asnumpy())
    for i, (s, created) in enumerate(zip(trainer._states,
                                         trainer._states_created)):
        if not created:
            continue
        import jax

        for j, leaf in enumerate(jax.tree.leaves(s)):
            out[f"state:{i}:{j}"] = onp.asarray(jax.device_get(leaf))
    tmp = args.out + ".tmp"
    with open(tmp, "wb") as fh:
        onp.savez(fh, **out)
    os.replace(tmp, args.out)
    print(f"[rank {rank} gen {gen}] done at step {step}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
