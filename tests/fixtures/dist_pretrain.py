"""Deterministic multi-process CPU-mesh pretrain worker — the rank
program for the pod-runtime tests (ISSUE 19 acceptance).

Every rank joins the pod via ``parallel.init_distributed`` (gloo CPU
collectives under ``JAX_PLATFORMS=cpu``), forms ONE global mesh over
``jax.devices()`` (which spans processes), and drives
``Trainer.fused_step`` with a batch sharded over the pod's ``dp``
axis: rank r feeds its slice of the GLOBAL batch and
``parallel.global_put`` assembles the pod-global array, so the jitted
step's grad reduction crosses process boundaries while staying one
executable dispatch per step per process.

Determinism/parity contract: the GLOBAL batch stream is identical for
any world size W (one shared seeded dataset; global batch g is rows
``[g*B, (g+1)*B)``; rank r of W serves slice ``[r*B/W, (r+1)*B/W)``),
so a W-process run's loss curve matches the single-process virtual
mesh numerically, and an ELASTIC resume on W' < W ranks re-buckets the
same cursor — counted in GLOBAL batches, never per-rank — onto the new
dp extent without re-serving or skipping a sample.

Checkpoint extra records ``{"batch": global_batches_done, "workers":
W}``.  Resuming with a DIFFERENT world size is refused unless
``MXNET_ELASTIC=1`` (exported by ``tools/launch.py --elastic``) — a
silently resized pod is a bug, an elastic one is a contract.

Fault arming mirrors ``resume_train.py``: ``--fault GEN=SPEC`` arms
``MXNET_FAULT_INJECT=SPEC`` only when ``restart_count()==GEN`` and
rank ``== --fault-rank``.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as onp  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--global-bs", type=int, default=8)
    ap.add_argument("--units", type=int, default=8)
    ap.add_argument("--dir", default=None,
                    help="checkpoint root (default MXNET_CHECKPOINT_DIR;"
                         " empty = no checkpointing)")
    ap.add_argument("--out", default=None,
                    help="final params/losses npz; literal 'RANK' is "
                         "substituted with this process's rank")
    ap.add_argument("--fault", action="append", default=[],
                    metavar="GEN=SPEC",
                    help="arm MXNET_FAULT_INJECT=SPEC when "
                         "restart_count()==GEN and rank==--fault-rank")
    ap.add_argument("--fault-rank", type=int, default=0)
    args = ap.parse_args()

    rank = int(os.environ.get("MXNET_WORKER_ID", "0"))
    if args.out:
        args.out = args.out.replace("RANK", str(rank))

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    gen = mx.checkpoint.restart_count()
    for spec in args.fault:
        g, _, rule = spec.partition("=")
        if int(g) == gen and rank == args.fault_rank:
            os.environ["MXNET_FAULT_INJECT"] = rule
            print(f"[rank {rank} gen {gen}] armed fault {rule}",
                  flush=True)

    parallel.init_distributed()
    import jax

    world = jax.process_count()
    assert jax.process_index() == rank or world == 1, \
        (jax.process_index(), rank)
    if args.global_bs % len(jax.devices()):
        print(f"global batch {args.global_bs} does not divide over "
              f"{len(jax.devices())} devices", file=sys.stderr)
        return 2
    local_bs = args.global_bs // world

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    data_sh = parallel.data_sharding(mesh)

    mx.random.seed(7)
    onp.random.seed(7)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(args.units, use_bias=False,
                         in_units=args.units))
        net.add(nn.Dense(1, use_bias=False, in_units=args.units))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, kvstore=None)
    loss_l = gluon.loss.L2Loss()

    def loss_fn(bx, by):
        # mean INSIDE the traced step: the loss out is replicated over
        # the pod, so every rank reads the identical scalar without a
        # cross-process gather
        return loss_l(net(bx), by).mean()

    rng = onp.random.RandomState(11)
    X = rng.rand(args.steps * args.global_bs,
                 args.units).astype(onp.float32)
    Y = rng.rand(args.steps * args.global_bs, 1).astype(onp.float32)

    root = args.dir or os.environ.get("MXNET_CHECKPOINT_DIR")
    mgr = None
    start = 0
    if root:
        ckdir = os.path.join(root, f"rank{rank}")
        mgr = mx.checkpoint.CheckpointManager(ckdir, max_to_keep=3,
                                              async_save=True)
        res = mgr.restore(net, trainer, return_extra=True)
        if res is not None:
            step, extra = res
            extra = extra or {}
            start = int(extra.get("batch", step))
            saved_world = int(extra.get("workers", world))
            if saved_world != world and \
                    os.environ.get("MXNET_ELASTIC") != "1":
                print(f"checkpoint was written by {saved_world} "
                      f"rank(s); resuming on {world} requires "
                      "MXNET_ELASTIC=1 (tools/launch.py --elastic)",
                      file=sys.stderr)
                return 3
            print(f"[rank {rank} gen {gen}] resumed at global batch "
                  f"{start} (saved by {saved_world} rank(s), now "
                  f"{world})", flush=True)

    from mxnet_tpu import telemetry

    losses = []
    lo, hi = rank * local_bs, (rank + 1) * local_bs
    for g in range(start, args.steps):
        # same chaos hook the DataLoader fires per owned batch — lets
        # the elastic tests kill/raise on an exact global batch index
        telemetry.fault_point("data.next", batch=g)
        # the global batch + the per-step RNG noise are identical on
        # every rank and for every world size; only the slice differs
        bx = X[g * args.global_bs:(g + 1) * args.global_bs]
        by = Y[g * args.global_bs:(g + 1) * args.global_bs]
        noise = onp.asarray(mx.random.normal(
            shape=(args.global_bs, args.units)).asnumpy()) * 0.01
        loss = trainer.fused_step(
            loss_fn, mx.nd.array(bx[lo:hi] + noise[lo:hi]),
            mx.nd.array(by[lo:hi]), batch_size=1,
            data_sharding=data_sh)
        val = float(onp.asarray(loss.asnumpy()).reshape(()))
        losses.append((g, val))
        print(f"[rank {rank} gen {gen}] STEP {g} loss={val:.8f}",
              flush=True)
        if mgr is not None:
            mgr.save(g + 1, net, trainer,
                     extra={"batch": g + 1, "workers": world})
    if mgr is not None:
        mgr.wait_until_finished()
        mgr.close()

    from mxnet_tpu.gluon.fused_step import step_counters

    print(f"[rank {rank} gen {gen}] DONE steps={args.steps - start} "
          f"world={world} compiles={step_counters['compiles']} "
          f"dispatches={step_counters['dispatches']}", flush=True)

    if args.out:
        out = {"losses": onp.asarray([v for _, v in losses],
                                     onp.float64),
               "loss_steps": onp.asarray([g for g, _ in losses],
                                         onp.int64)}
        for name, p in net._collect_params_with_prefix().items():
            out[f"param:{name}"] = onp.asarray(p.data().asnumpy())
        tmp = args.out + ".tmp"
        with open(tmp, "wb") as fh:
            onp.savez(fh, **out)
        os.replace(tmp, args.out)
    parallel.barrier("dist_pretrain_done", timeout=60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
