"""Round-3 operator-corpus expansion: im2col/col2im, Module-era output
heads, legacy indexing, standalone activations, LANS/GroupAdaGrad
(SURVEY.md §3.1 operator corpus; golden + gradient tests per the
reference test model)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def _rand(*shape):
    return onp.random.RandomState(0).randn(*shape).astype("float32")


class TestIm2Col:
    def test_im2col_golden(self):
        x = onp.arange(2 * 3 * 5 * 5, dtype=onp.float32).reshape(2, 3, 5, 5)
        out = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1),
                        pad=(0, 0)).asnumpy()
        assert out.shape == (2, 3 * 9, 9)
        # golden: manual patch extraction at position (0,0) and (2,2)
        patch00 = x[0, :, 0:3, 0:3].reshape(-1)
        onp.testing.assert_allclose(out[0, :, 0], patch00)
        patch22 = x[0, :, 2:5, 2:5].reshape(-1)
        onp.testing.assert_allclose(out[0, :, 8], patch22)

    def test_im2col_stride_pad(self):
        x = _rand(1, 2, 6, 6)
        out = nd.im2col(nd.array(x), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1)).asnumpy()
        assert out.shape == (1, 18, 9)
        xp = onp.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        onp.testing.assert_allclose(
            out[0, :, 0], xp[0, :, 0:3, 0:3].reshape(-1), rtol=1e-6)

    def test_col2im_inverts_nonoverlapping(self):
        x = _rand(2, 3, 6, 6)
        cols = nd.im2col(nd.array(x), kernel=(2, 2), stride=(2, 2))
        back = nd.col2im(cols, output_size=(6, 6), kernel=(2, 2),
                         stride=(2, 2)).asnumpy()
        onp.testing.assert_allclose(back, x, rtol=1e-6)

    def test_col2im_accumulates_overlap(self):
        x = onp.ones((1, 1, 4, 4), onp.float32)
        cols = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1))
        back = nd.col2im(cols, output_size=(4, 4), kernel=(3, 3),
                         stride=(1, 1)).asnumpy()
        # center pixels belong to 4 overlapping 3x3 patches
        assert back[0, 0, 1, 1] == 4.0
        assert back[0, 0, 0, 0] == 1.0

    def test_conv_via_im2col_matches_convolution(self):
        """im2col + GEMM == Convolution (the reference's CPU conv path)."""
        x = _rand(2, 3, 8, 8)
        w = _rand(4, 3, 3, 3)
        ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             num_filter=4, no_bias=True, pad=(1, 1))
        cols = nd.im2col(nd.array(x), kernel=(3, 3), pad=(1, 1))
        gemm = onp.einsum("ok,nkl->nol", w.reshape(4, -1), cols.asnumpy())
        onp.testing.assert_allclose(gemm.reshape(ref.shape), ref.asnumpy(),
                                    rtol=1e-4, atol=1e-4)


class TestOutputHeads:
    def test_linear_regression_grad(self):
        d = nd.array(_rand(4, 3))
        lab = nd.array(_rand(4, 3))
        d.attach_grad()
        with autograd.record():
            out = nd.LinearRegressionOutput(d, lab)
        out.backward()
        onp.testing.assert_allclose(out.asnumpy(), d.asnumpy())
        # reference "1/m": divide by outputs per example (3), not batch (4)
        onp.testing.assert_allclose(
            d.grad.asnumpy(), (d.asnumpy() - lab.asnumpy()) / 3, rtol=1e-5)

    def test_logistic_regression_grad(self):
        d = nd.array(_rand(5, 2))
        lab = nd.array((onp.random.RandomState(1).rand(5, 2) > 0.5)
                       .astype("float32"))
        d.attach_grad()
        with autograd.record():
            out = nd.LogisticRegressionOutput(d, lab)
        out.backward()
        sig = 1 / (1 + onp.exp(-d.asnumpy()))
        onp.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
        onp.testing.assert_allclose(d.grad.asnumpy(),
                                    (sig - lab.asnumpy()) / 2, rtol=1e-5)

    def test_mae_regression_grad(self):
        d = nd.array(_rand(3, 2))
        lab = nd.array(onp.zeros((3, 2), "float32"))
        d.attach_grad()
        with autograd.record():
            out = nd.MAERegressionOutput(d, lab)
        out.backward()
        onp.testing.assert_allclose(d.grad.asnumpy(),
                                    onp.sign(d.asnumpy()) / 2, rtol=1e-5)

    def test_svm_output_grad_squared_hinge(self):
        d = nd.array(onp.asarray([[2.0, 1.5, -1.0]], "float32"))
        lab = nd.array(onp.asarray([0.0], "float32"))
        d.attach_grad()
        with autograd.record():
            out = nd.SVMOutput(d, lab, margin=1.0)
        out.backward()
        onp.testing.assert_allclose(out.asnumpy(), d.asnumpy())
        g = d.grad.asnumpy()[0]
        # class 1 violates the margin (1.5 - 2 + 1 = 0.5 > 0): grad 2*0.5
        assert g[1] == pytest.approx(1.0)
        assert g[2] == pytest.approx(0.0)      # no violation
        assert g[0] == pytest.approx(-1.0)     # minus the sum


class TestLegacyIndexing:
    def test_choose_element(self):
        d = nd.array(_rand(4, 5))
        idx = nd.array(onp.asarray([0, 2, 4, 1], "float32"))
        out = nd.choose_element_0index(d, idx).asnumpy()
        expect = d.asnumpy()[onp.arange(4), [0, 2, 4, 1]]
        onp.testing.assert_allclose(out, expect)

    def test_fill_element(self):
        d = nd.array(onp.zeros((3, 4), "float32"))
        vals = nd.array(onp.asarray([7.0, 8.0, 9.0], "float32"))
        idx = nd.array(onp.asarray([1, 0, 3], "float32"))
        out = nd.fill_element_0index(d, vals, idx).asnumpy()
        assert out[0, 1] == 7 and out[1, 0] == 8 and out[2, 3] == 9
        assert out.sum() == 24


class TestActivationOps:
    @pytest.mark.parametrize("name,ref", [
        ("selu", lambda x: 1.0507009873554805 * onp.where(
            x > 0, x, 1.6732632423543772 * (onp.exp(x) - 1))),
        ("erfc", lambda x: 1 - onp.vectorize(__import__("math").erf)(x)),
    ])
    def test_golden(self, name, ref):
        x = _rand(3, 4)
        out = getattr(nd, name)(nd.array(x)).asnumpy()
        onp.testing.assert_allclose(out, ref(x), rtol=1e-5, atol=1e-6)

    def test_elu_prelu(self):
        x = _rand(2, 3)
        out = nd.elu(nd.array(x), alpha=0.5).asnumpy()
        onp.testing.assert_allclose(
            out, onp.where(x > 0, x, 0.5 * (onp.exp(x) - 1)), rtol=1e-5)
        g = nd.array(onp.asarray([0.1, 0.2, 0.3], "float32"))
        out = nd.prelu(nd.array(x), g).asnumpy()
        onp.testing.assert_allclose(
            out, onp.where(x >= 0, x, x * onp.asarray([0.1, 0.2, 0.3])),
            rtol=1e-5)

    def test_logit_inverts_sigmoid(self):
        p = onp.asarray([0.1, 0.5, 0.9], "float32")
        out = nd.logit(nd.array(p)).asnumpy()
        onp.testing.assert_allclose(1 / (1 + onp.exp(-out)), p, rtol=1e-5)

    def test_gelu_matches_erf_form(self):
        from math import erf, sqrt
        x = _rand(5)
        out = nd.gelu(nd.array(x)).asnumpy()
        ref = onp.asarray([0.5 * v * (1 + erf(v / sqrt(2))) for v in x],
                          "float32")
        onp.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestMiscOps:
    def test_softmax_cross_entropy_golden(self):
        d = _rand(4, 6)
        lab = onp.asarray([0, 3, 5, 2], "float32")
        out = float(nd.softmax_cross_entropy(
            nd.array(d), nd.array(lab)).asnumpy())
        e = onp.exp(d - d.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -sum(onp.log(p[i, int(lab[i])]) for i in range(4))
        assert out == pytest.approx(ref, rel=1e-4)

    def test_group_adagrad_row_groups(self):
        w = nd.array(onp.ones((3, 4), "float32"))
        g = nd.array(_rand(3, 4))
        h = nd.array(onp.zeros((3, 1), "float32"))
        nw, nh = nd.group_adagrad_update(w, g, h, lr=0.1)
        assert nh.shape == (3, 1)
        expect_h = (g.asnumpy() ** 2).mean(axis=1, keepdims=True)
        onp.testing.assert_allclose(nh.asnumpy(), expect_h, rtol=1e-5)
        expect_w = 1 - 0.1 * g.asnumpy() / (onp.sqrt(expect_h) + 1e-5)
        onp.testing.assert_allclose(nw.asnumpy(), expect_w, rtol=1e-5)

    def test_lans_update_moves_weights(self):
        w = nd.array(_rand(8, 8))
        g = nd.array(_rand(8, 8))
        m = nd.array(onp.zeros((8, 8), "float32"))
        v = nd.array(onp.zeros((8, 8), "float32"))
        nw, nm, nv = nd.lans_update(w, g, m, v, lr=0.01, t=1)
        assert not onp.allclose(nw.asnumpy(), w.asnumpy())
        assert onp.isfinite(nw.asnumpy()).all()
        # trust-ratio scaling keeps the step bounded
        assert onp.linalg.norm(nw.asnumpy() - w.asnumpy()) < \
            0.05 * onp.linalg.norm(w.asnumpy())

    def test_rnn_param_concat(self):
        a = nd.array(_rand(2, 3))
        b = nd.array(_rand(4,))
        out = nd.rnn_param_concat([a, b], dim=0)
        assert out.shape == (10,)

    def test_aliases(self):
        x = nd.array(_rand(2, 3))
        onp.testing.assert_allclose(nd.SwapAxis(x, dim1=0, dim2=1).asnumpy(),
                                    x.asnumpy().T)
        onp.testing.assert_allclose(
            nd.crop(x, begin=(0, 1), end=(2, 3)).asnumpy(),
            x.asnumpy()[:, 1:3])
