"""Native C++ IO runtime tests (native/mxtpu_io.cc via ctypes).

Covers the TPU analog of the reference's C++ data path (SURVEY.md §3.1
"C++ data pipeline"): record parse interop with the Python implementation,
libjpeg decode, threaded prefetch ordering.
"""
import os

import numpy as onp
import pytest

from mxnet_tpu import recordio as rio
from mxnet_tpu import _native

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native IO library not built")


@pytest.fixture
def packed_rec(tmp_path):
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    shapes = []
    for i in range(6):
        img = (onp.random.RandomState(i).rand(15 + i, 20, 3) * 255).astype(onp.uint8)
        shapes.append(img.shape)
        w.write_idx(i, rio.pack_img(rio.IRHeader(0, float(i), i, 0), img))
    w.close()
    return rec, idx, shapes


def test_native_reader_python_writer(packed_rec):
    rec, idx, shapes = packed_rec
    r = _native.NativeRecordReader(rec, idx)
    assert len(r) == 6
    h, payload = rio.unpack(r.read(4))
    assert float(h.label) == 4.0


def test_native_reader_scan_without_idx(packed_rec):
    rec, _, _ = packed_rec
    r = _native.NativeRecordReader(rec, "")
    assert len(r) == 6


def test_native_writer_python_reader(tmp_path):
    rec = str(tmp_path / "w.rec")
    idx = str(tmp_path / "w.idx")
    w = _native.NativeRecordWriter(rec, idx)
    payloads = [os.urandom(i * 13 + 1) for i in range(9)]
    for p in payloads:
        w.write(p)
    w.close()
    r = rio.MXIndexedRecordIO(idx, rec, "r")
    assert [r.read_idx(k) for k in r.keys] == payloads


def test_native_jpeg_decode_matches_reference(packed_rec):
    rec, idx, shapes = packed_rec
    r = _native.NativeRecordReader(rec, idx)
    _, payload = rio.unpack(r.read(2))
    arr = _native.decode_jpeg(payload)
    assert arr.shape == shapes[2]
    # pixel parity with the default decode path (both decode the same JPEG)
    from mxnet_tpu.image import imdecode_np
    ref = imdecode_np(payload)
    assert arr.shape == ref.shape
    # JPEG decoders may differ by small rounding; require close agreement
    assert onp.mean(onp.abs(arr.astype(int) - ref.astype(int))) < 2.0


def test_native_decode_error_not_fatal():
    with pytest.raises(IOError):
        _native.decode_jpeg(b"not a jpeg at all")


def test_prefetch_order_and_shuffle(packed_rec):
    rec, idx, _ = packed_rec
    r = _native.NativeRecordReader(rec, idx)
    order = [3, 1, 5, 0, 2, 4]
    pf = _native.NativePrefetcher(r, order, num_threads=3)
    labels = []
    for s in pf:
        h, _ = rio.unpack(s)
        labels.append(int(float(onp.asarray(h.label).reshape(-1)[0])))
    assert labels == order


def test_prefetch_decode_mode(packed_rec):
    rec, idx, shapes = packed_rec
    r = _native.NativeRecordReader(rec, idx)
    pf = _native.NativePrefetcher(r, list(range(6)), num_threads=2,
                                  decode=True)
    arrs = list(pf)
    assert [a.shape for a in arrs] == shapes


def test_record_file_dataset_uses_native(packed_rec):
    rec, _, _ = packed_rec
    from mxnet_tpu.gluon.data import RecordFileDataset
    ds = RecordFileDataset(rec)
    assert ds._native is not None
    h, _ = rio.unpack(ds[5])
    assert float(h.label) == 5.0


def test_prefetch_no_deadlock_small_capacity(tmp_path):
    """Regression: a slow first record + full queue must not deadlock
    (the consumer-awaited index is always admitted)."""
    rec = str(tmp_path / "big.rec")
    idx = str(tmp_path / "big.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(40):
        w.write_idx(i, bytes([i % 251]) * (200000 if i == 0 else 50))
    w.close()
    r = _native.NativeRecordReader(rec, idx)
    pf = _native.NativePrefetcher(r, list(range(40)), num_threads=4,
                                  capacity=4)
    out = list(pf)
    assert len(out) == 40
    assert len(out[0]) == 200000 and out[1] == bytes([1]) * 50


def test_writer_rejects_oversized_record(tmp_path):
    w = _native.NativeRecordWriter(str(tmp_path / "o.rec"), "")
    w.write(b"ok")
    with pytest.raises(IOError):
        # 2^29 exceeds the 29-bit length field; must error, not corrupt
        w.write(bytes(1 << 29))
