"""Legacy Module API tests (reference tests/python/unittest/test_module.py
coverage; SURVEY.md §3.2 Module row, §4.3 call stack)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import DataBatch
from mxnet_tpu.module import Module, BucketingModule


def _mlp_sym(num_classes=5):
    data = mx.sym.var("data")
    label = mx.sym.var("softmax_label")
    h = mx.sym.FullyConnected(data, mx.sym.var("fc1_weight"),
                              mx.sym.var("fc1_bias"), num_hidden=32,
                              name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, mx.sym.var("fc2_weight"),
                              mx.sym.var("fc2_bias"), num_hidden=num_classes,
                              name="fc2")
    return mx.sym.SoftmaxOutput(h, label, name="softmax")


@pytest.fixture
def toy_iter():
    rng = onp.random.RandomState(0)
    X = rng.rand(200, 20).astype(onp.float32)
    w = rng.rand(20, 5).astype(onp.float32)
    y = (X @ w).argmax(axis=1).astype(onp.float32)
    return mx.io.NDArrayIter(X, y, batch_size=25, shuffle=True)


class TestModule:
    def test_fit_learns(self, toy_iter):
        mod = Module(_mlp_sym(), context=mx.cpu())
        mod.fit(toy_iter, num_epoch=6, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.5),),
                initializer=mx.init.Xavier())
        acc = mod.score(toy_iter, "acc")[0][1]
        assert acc > 0.75, acc

    def test_bind_infers_param_shapes(self):
        mod = Module(_mlp_sym(), context=mx.cpu())
        mod.bind([("data", (4, 20))], [("softmax_label", (4,))])
        assert mod._exec.arg_dict["fc1_weight"].shape == (32, 20)
        assert mod._exec.arg_dict["fc2_weight"].shape == (5, 32)

    def test_forward_shape_and_predict(self, toy_iter):
        mod = Module(_mlp_sym(), context=mx.cpu())
        mod.bind(toy_iter.provide_data, toy_iter.provide_label,
                 for_training=False)
        mod.init_params(initializer=mx.init.Xavier())
        preds = mod.predict(toy_iter)
        assert preds.shape == (200, 5)
        # rows are softmax distributions
        onp.testing.assert_allclose(preds.asnumpy().sum(axis=1),
                                    onp.ones(200), rtol=1e-4)

    def test_checkpoint_roundtrip(self, toy_iter, tmp_path):
        mod = Module(_mlp_sym(), context=mx.cpu())
        mod.fit(toy_iter, num_epoch=2, initializer=mx.init.Xavier())
        ref = mod.score(toy_iter, "acc")[0][1]
        prefix = str(tmp_path / "ck")
        mod.save_checkpoint(prefix, 2)
        mod2 = Module.load(prefix, 2, context=mx.cpu())
        mod2.bind(toy_iter.provide_data, toy_iter.provide_label,
                  for_training=False)
        mod2.init_params()
        assert abs(mod2.score(toy_iter, "acc")[0][1] - ref) < 1e-6

    def test_score_before_bind_raises(self, toy_iter):
        mod = Module(_mlp_sym(), context=mx.cpu())
        with pytest.raises(MXNetError):
            mod.score(toy_iter, "acc")

    def test_fixed_params_not_updated(self, toy_iter):
        mod = Module(_mlp_sym(), context=mx.cpu(),
                     fixed_param_names=["fc1_weight"])
        mod.bind(toy_iter.provide_data, toy_iter.provide_label)
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.5),))
        before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
        batch = next(iter(toy_iter))
        mod.forward_backward(batch)
        mod.update()
        onp.testing.assert_array_equal(
            mod._exec.arg_dict["fc1_weight"].asnumpy(), before)


class TestSoftmaxOutputGrad:
    def test_ce_gradient_semantics(self):
        """backward(ones) through SoftmaxOutput == p - onehot (reference)."""
        from mxnet_tpu import autograd
        x = mx.nd.array(onp.random.rand(3, 4).astype(onp.float32))
        y = mx.nd.array(onp.array([1, 3, 0], onp.float32))
        x.attach_grad()
        with autograd.record():
            out = mx.nd.SoftmaxOutput(x, y)
        out.backward()
        p = onp.exp(x.asnumpy()) / onp.exp(x.asnumpy()).sum(1, keepdims=True)
        onehot = onp.eye(4, dtype=onp.float32)[[1, 3, 0]]
        onp.testing.assert_allclose(x.grad.asnumpy(), p - onehot, rtol=1e-4,
                                    atol=1e-5)

    def test_multi_output_channel_axis(self):
        """multi_output=True softmaxes over axis 1 of (n, c, d1) inputs with
        (n, d1) labels (reference NCHW segmentation semantics)."""
        from mxnet_tpu import autograd
        rng = onp.random.RandomState(3)
        x = rng.rand(2, 4, 5).astype(onp.float32)
        y = rng.randint(0, 4, (2, 5)).astype(onp.float32)
        xd, yd = mx.nd.array(x), mx.nd.array(y)
        xd.attach_grad()
        with autograd.record():
            out = mx.nd.SoftmaxOutput(xd, yd, multi_output=True)
        out.backward()
        e = onp.exp(x - x.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        onp.testing.assert_allclose(out.asnumpy(), p, rtol=1e-5, atol=1e-6)
        onehot = onp.eye(4, dtype=onp.float32)[y.astype(int)]  # (2, 5, 4)
        grad = p - onehot.transpose(0, 2, 1)
        onp.testing.assert_allclose(xd.grad.asnumpy(), grad, rtol=1e-4,
                                    atol=1e-5)


class TestBucketing:
    @staticmethod
    def _sym_gen(seq_len):
        d = mx.sym.var("data")
        l = mx.sym.var("softmax_label")
        f = mx.sym.FullyConnected(d, mx.sym.var("fc_weight"),
                                  mx.sym.var("fc_bias"), num_hidden=4,
                                  flatten=False, name="fc")
        # multi_output softmaxes over axis 1 (reference semantics), so put
        # the class axis there: (n, seq, c) -> (n, c, seq)
        f = mx.sym.transpose(f, axes=(0, 2, 1))
        return (mx.sym.SoftmaxOutput(f, l, multi_output=True),
                ("data",), ("softmax_label",))

    def test_buckets_share_params(self):
        bm = BucketingModule(self._sym_gen, default_bucket_key=10,
                             context=mx.cpu())
        bm.bind([("data", (8, 10, 5))], [("softmax_label", (8, 10))])
        bm.init_params(initializer=mx.init.Xavier())
        bm.init_optimizer(optimizer="sgd",
                          optimizer_params=(("learning_rate", 0.1),))
        rng = onp.random.RandomState(0)
        for key in (10, 6, 10, 6):
            b = DataBatch(
                data=[mx.nd.array(rng.rand(8, key, 5).astype(onp.float32))],
                label=[mx.nd.array(rng.randint(0, 4, (8, key))
                                   .astype(onp.float32))],
                bucket_key=key,
                provide_data=[("data", (8, key, 5))],
                provide_label=[("softmax_label", (8, key))])
            bm.forward(b, is_train=True)
            bm.backward()
            bm.update()
        assert sorted(bm._buckets) == [6, 10]
        assert (bm._buckets[6]._exec.arg_dict["fc_weight"]
                is bm._buckets[10]._exec.arg_dict["fc_weight"])
