"""Fused decode step (ops/decode_fused.py): the one-kernel-per-token
path must reproduce the per-op scan step — greedy token parity on the
default batched-prefill path, exact K/V cache writes, gating rules.
Interpret mode on CPU; the perf claims live in benchmark/decode_bench.py
and BASELINE.md (VERDICT r4 item 2).

Reference arms pin ``stacked="off"``: the megakernel replicates the
UNROLLED per-layer math, and the stacked-scan arm (the new ``fused="off"``
default) can flip rare bf16 greedy near-ties against it (1-ulp
rounding-order class — see tests/test_stacked_decode.py for the
stacked↔unrolled parity suite)."""
import os

import numpy as onp
import pytest

os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # per-test (not module-level): other modules delete this env var in
    # their teardown, and _interpret() reads it at call time
    monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")


import jax.numpy as jnp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def _model(units=128, heads=4, hidden=512, layers=2, init=0.15):
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=97, max_length=64, num_layers=layers,
                        units=units, num_heads=heads, hidden_size=hidden))
    # sharper-than-default init: an untrained near-flat logit field makes
    # greedy argmax a coin flip at 1-ulp hidden-state noise, which is
    # rounding-order sensitivity, not decoder behavior
    net.initialize(mx.init.Normal(init))
    net.cast("bfloat16")
    return net


class TestFusedDecode:
    def test_greedy_parity_batched_prefill(self):
        from mxnet_tpu.models import kv_generate
        net = _model()
        for seed, (b, p) in [(0, (1, 5)), (1, (2, 7))]:
            prompt = onp.random.RandomState(seed).randint(0, 97, (b, p))
            ref = kv_generate(net, prompt, max_new_tokens=10,
                              temperature=0.0, fused="off", stacked="off")
            out = kv_generate(net, prompt, max_new_tokens=10,
                              temperature=0.0, fused="on")
            onp.testing.assert_array_equal(out, ref)

    def test_scan_prefill_single_step_parity(self):
        """Per-step parity through a fused teacher-forced history (the
        scan-prefill mode): the next sampled token must match across
        many prompts.  Long scan streams may legitimately flip rare
        near-ties (1-ulp chunked-accumulation noise, same class as an
        XLA tiling change) — that is asserted NOT to happen in a single
        step."""
        from mxnet_tpu.models import kv_generate
        net = _model()
        for s in range(6):
            prompt = onp.random.RandomState(s).randint(0, 97, (1, 6))
            ref = kv_generate(net, prompt, max_new_tokens=1,
                              temperature=0.0, prefill="scan",
                              fused="off", stacked="off")
            out = kv_generate(net, prompt, max_new_tokens=1,
                              temperature=0.0, prefill="scan",
                              fused="on")
            onp.testing.assert_array_equal(out, ref)

    def test_int8_fused_matches_int8_unfused(self):
        """int8 fused stream vs the per-op q8_matvec path: identical
        quantized weights, so greedy tokens must match (VERDICT r4
        item 2: int8 re-measured through the fused kernel)."""
        from mxnet_tpu.models import kv_generate
        net = _model()
        prompt = onp.random.RandomState(4).randint(0, 97, (1, 5))
        ref = kv_generate(net, prompt, max_new_tokens=8,
                          temperature=0.0, weights="int8", fused="off",
                          stacked="off")
        out = kv_generate(net, prompt, max_new_tokens=8,
                          temperature=0.0, weights="int8", fused="on")
        onp.testing.assert_array_equal(out, ref)

    def test_llama_gqa_parity_native_and_int8(self):
        """Llama family through the fused kernel: RMSNorm, lane-rolled
        RoPE, grouped-query attention (KV < H), SwiGLU — greedy tokens
        must match the per-op path in both weight modes."""
        from mxnet_tpu.models import Llama, LlamaConfig, kv_generate
        mx.random.seed(0)
        cfg = LlamaConfig(vocab_size=97, max_length=64, num_layers=2,
                          units=128, num_heads=4, num_kv_heads=2,
                          hidden_size=256)
        net = Llama(cfg)
        net.initialize(mx.init.Normal(0.15))
        net.cast("bfloat16")
        prompt = onp.random.RandomState(0).randint(0, 97, (1, 5))
        ref = kv_generate(net, prompt, max_new_tokens=10,
                          temperature=0.0, fused="off", stacked="off")
        out = kv_generate(net, prompt, max_new_tokens=10,
                          temperature=0.0, fused="on")
        onp.testing.assert_array_equal(out, ref)
        r8 = kv_generate(net, prompt, max_new_tokens=8, temperature=0.0,
                         weights="int8", fused="off",
                         stacked="off")
        o8 = kv_generate(net, prompt, max_new_tokens=8, temperature=0.0,
                         weights="int8", fused="on")
        onp.testing.assert_array_equal(o8, r8)

    def test_sampled_mode_deterministic(self):
        from mxnet_tpu.models import kv_generate
        net = _model()
        prompt = onp.random.RandomState(2).randint(0, 97, (1, 4))
        a = kv_generate(net, prompt, max_new_tokens=6, temperature=0.9,
                        top_k=8, seed=5, fused="on")
        b = kv_generate(net, prompt, max_new_tokens=6, temperature=0.9,
                        top_k=8, seed=5, fused="on")
        onp.testing.assert_array_equal(a, b)
        assert ((0 <= a) & (a < 97)).all()

    def test_fused_on_raises_when_unsupported(self):
        from mxnet_tpu.base import MXNetError
        from mxnet_tpu.models import kv_generate
        net = _model()
        net.cast("float32")  # kernel is bf16-only
        prompt = onp.zeros((1, 4), onp.int32)
        with pytest.raises(MXNetError, match="fused"):
            kv_generate(net, prompt, max_new_tokens=2, temperature=0.0,
                        fused="on")

    def test_weight_update_invalidates_pack(self):
        """The packed stream must repack after a weight rebind (the
        pinned-source discipline shared with the q8 cache)."""
        from mxnet_tpu.models import kv_generate
        net = _model()
        prompt = onp.random.RandomState(3).randint(0, 97, (1, 4))
        out1 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, fused="on")
        # rebind one weight: decodes must change and match the unfused
        # path run after the same edit
        w = net.blocks[0].attn.qkv.weight
        w.set_data(mx.nd.from_jax(-w.data()._data))
        out2 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, fused="on")
        ref2 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, fused="off", stacked="off")
        onp.testing.assert_array_equal(out2, ref2)
        assert (out1 != out2).any()

    def test_supported_gate(self):
        from mxnet_tpu.models import GPTConfig
        from mxnet_tpu.ops.decode_fused import fused_decode_supported
        cfg = GPTConfig(vocab_size=97, max_length=64, num_layers=2,
                        units=128, num_heads=4, hidden_size=512)
        assert fused_decode_supported(cfg, 1, 32, jnp.bfloat16)
        assert not fused_decode_supported(cfg, 8, 32, jnp.bfloat16)
        assert not fused_decode_supported(cfg, 1, 32, jnp.float32)
