"""Cross-dtype consistency sweep over the core op corpus — the reference's
``check_consistency`` test model (SURVEY.md §4: "same op across
(ctx,dtype) lists"; here dtype is the axis, ctx being a single virtual
mesh).  Also finite-difference gradient checks on representative ops
(``check_numeric_gradient``, the reference's other op-test pillar)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_consistency, check_numeric_gradient

_DT = ("float32", "float16", "bfloat16")


def _r(*shape):
    return onp.random.RandomState(0).rand(*shape).astype(onp.float32)


UNARY = ["relu", "sigmoid", "tanh", "exp", "log1p", "sqrt", "square",
         "abs", "erf", "softsign", "rsqrt", "cbrt", "sin", "cos"]


@pytest.mark.parametrize("name", UNARY)
def test_unary_consistent_across_dtypes(name):
    fn = getattr(mx.nd, name)
    check_consistency(lambda x: fn(x), [_r(4, 5) + 0.1], dtypes=_DT)


BINARY = ["broadcast_add", "broadcast_sub", "broadcast_mul",
          "broadcast_div", "broadcast_maximum", "broadcast_minimum"]


@pytest.mark.parametrize("name", BINARY)
def test_binary_consistent_across_dtypes(name):
    fn = getattr(mx.nd, name)
    check_consistency(lambda a, b: fn(a, b), [_r(4, 5), _r(4, 5) + 0.5],
                      dtypes=_DT)


@pytest.mark.parametrize("case", [
    ("dot", lambda a, b: mx.nd.dot(a, b), [_r(8, 16), _r(16, 4)]),
    ("FullyConnected",
     lambda x, w: mx.nd.FullyConnected(x, w, None, num_hidden=4,
                                       no_bias=True),
     [_r(8, 16), _r(4, 16)]),
    ("softmax", lambda x: mx.nd.softmax(x), [_r(4, 10)]),
    ("LayerNorm",
     lambda x, g, b: mx.nd.LayerNorm(x, g, b),
     [_r(4, 8), _r(8), _r(8)]),
    ("mean", lambda x: mx.nd.mean(x, axis=1), [_r(4, 8)]),
], ids=lambda c: c[0] if isinstance(c, tuple) else str(c))
def test_compound_consistent_across_dtypes(case):
    _, fn, inputs = case
    check_consistency(fn, inputs, dtypes=_DT)


@pytest.mark.parametrize("case", [
    ("tanh", lambda x: mx.nd.tanh(x).sum(), [(3, 4)]),
    ("sigmoid", lambda x: mx.nd.sigmoid(x).sum(), [(3, 4)]),
    ("LayerNorm",
     lambda x: mx.nd.LayerNorm(x, mx.nd.ones(6),
                               mx.nd.zeros(6)).sum(), [(2, 6)]),
    ("GELU", lambda x: mx.nd.Activation(x, act_type="gelu").sum(), [(3, 4)]),
    ("mish", lambda x: mx.nd.mish(x).sum(), [(3, 4)]),
    ("hard_sigmoid", lambda x: mx.nd.hard_sigmoid(x).sum(), [(3, 4)]),
], ids=lambda c: c[0] if isinstance(c, tuple) else str(c))
def test_numeric_gradient(case):
    _, fn, shapes = case
    rng = onp.random.RandomState(0)
    inputs = [rng.rand(*s).astype(onp.float32) * 2 - 1 for s in shapes]
    check_numeric_gradient(fn, inputs)
