"""Fault-tolerant runtime (ISSUE 13): the fault-injection harness,
serving deadlines / cancellation / the scheduler watchdog, bounded
distributed init + barriers, the kvstore server's per-request error
replies, and the failure-cause report.

The serving chaos gauntlet pins the acceptance bars: a fault-injected
scheduler death fails all in-flight streams with the underlying error
while submit() raises cleanly afterward; a deadline-expired and a
cancelled request each free their pool slot at a step boundary with
co-resident streams token-identical to an undisturbed run (greedy and
sampled), at ONE executable dispatch per decode step — retirement
costs zero extra dispatches (the launch-supervisor half of the
gauntlet lives in tests/test_launch_supervised.py).
"""
import os
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import faults


def _gpt(layers=2, units=32, heads=4, hidden=64, vocab=97,
         max_length=64):
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=vocab, max_length=max_length,
                        num_layers=layers, units=units, num_heads=heads,
                        hidden_size=hidden))
    net.initialize(mx.init.Normal(0.02))
    return net


def _prompt(seed, n, vocab=97):
    return onp.random.RandomState(seed).randint(0, vocab, (n,))


def _drain(server):
    while server.pump():
        pass


def _ref(net, prompt, n, **kw):
    from mxnet_tpu.models import kv_generate
    kw.setdefault("temperature", 0.0)
    return list(kv_generate(net, prompt[None], max_new_tokens=n,
                            **kw)[0, prompt.size:])


@pytest.fixture(scope="module")
def net():
    return _gpt()


@pytest.fixture(autouse=True)
def _fresh_faults():
    """Every test starts with zeroed fault counters (the spec env is
    per-test via monkeypatch; the hit counts are process-global)."""
    faults.reset_faults()
    yield
    faults.reset_faults()


class _FakeClock:
    """Deterministic stand-in for DecodeServer._clock: deadline expiry
    becomes a scripted event, not a wall-clock race."""

    def __init__(self, start):
        self.t = float(start)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------- #
# the injection harness itself
# --------------------------------------------------------------------- #

class TestFaultHarness:
    def test_parse_spec(self):
        rules = faults.parse_fault_spec(
            "serve.step:raise:3, kvstore.push:delay:1:0.5")
        assert rules[0] == ("serve.step", "raise", 3, None)
        assert rules[1] == ("kvstore.push", "delay", 1, 0.5)

    @pytest.mark.parametrize("bad", ["serve.step", "x:boom:1",
                                     "x:raise:zero", "x:raise:0",
                                     ":raise:1"])
    def test_malformed_spec_rejected(self, bad):
        with pytest.raises(MXNetError, match="MXNET_FAULT_INJECT"):
            faults.parse_fault_spec(bad)

    def test_unset_is_noop(self, monkeypatch):
        monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
        telemetry.clear_events()
        for _ in range(3):
            faults.fault_point("anywhere")   # no raise, no event
        assert telemetry.events("fault_injected") == []

    def test_fires_once_on_nth_hit(self, monkeypatch):
        monkeypatch.setenv("MXNET_FAULT_INJECT", "t.site:raise:3")
        faults.reset_faults()
        telemetry.clear_events()
        faults.fault_point("t.site")
        faults.fault_point("t.other")        # other sites don't count
        faults.fault_point("t.site")
        with pytest.raises(MXNetError, match="injected fault at t.site"):
            faults.fault_point("t.site")
        faults.fault_point("t.site")         # single-shot: hit 4 passes
        evs = telemetry.events("fault_injected")
        assert len(evs) == 1
        assert evs[0]["site"] == "t.site"
        assert evs[0]["fault_kind"] == "raise"

    def test_kvstore_site_fires_with_context(self, monkeypatch):
        """Post-review regression: the kvstore sites pass store-kind
        context; an armed rule there must inject the fault (and emit
        its event), not die on an emit() kwarg collision."""
        from mxnet_tpu.kvstore import create

        monkeypatch.setenv("MXNET_FAULT_INJECT", "kvstore.push:raise:1")
        faults.reset_faults()
        telemetry.clear_events()
        kv = create("local")
        kv.init("k", mx.nd.zeros(2))
        with pytest.raises(MXNetError, match="injected fault at "
                                             "kvstore.push"):
            kv.push("k", mx.nd.ones(2))
        evs = telemetry.events("fault_injected")
        assert evs and evs[0]["site"] == "kvstore.push"
        monkeypatch.delenv("MXNET_FAULT_INJECT")
        kv.push("k", mx.nd.ones(2))          # store still healthy

    def test_reserved_context_keys_are_prefixed(self, monkeypatch):
        monkeypatch.setenv("MXNET_FAULT_INJECT", "t.ctx:delay:1:0.001")
        faults.reset_faults()
        telemetry.clear_events()
        faults.fault_point("t.ctx", kind="colliding", ts="also")
        ev = telemetry.events("fault_injected")[-1]
        assert ev["site"] == "t.ctx"         # the rule's site wins
        assert ev["fault_kind"] == "delay"   # ...and the rule's kind
        assert ev["ctx_kind"] == "colliding"
        assert ev["ctx_ts"] == "also"

    def test_unset_then_rearm_same_spec_fires_again(self, monkeypatch):
        """Post-review regression: unsetting the spec drops the cache,
        so re-arming the IDENTICAL spec later (a second chaos run in
        one process) fires instead of inheriting the stale fired-set."""
        monkeypatch.setenv("MXNET_FAULT_INJECT", "t.re:raise:1")
        faults.reset_faults()
        with pytest.raises(MXNetError, match="injected fault"):
            faults.fault_point("t.re")
        monkeypatch.delenv("MXNET_FAULT_INJECT")
        faults.fault_point("t.re")           # unset: no-op, cache drops
        monkeypatch.setenv("MXNET_FAULT_INJECT", "t.re:raise:1")
        with pytest.raises(MXNetError, match="injected fault"):
            faults.fault_point("t.re")       # same spec re-fires

    def test_delay_and_counter(self, monkeypatch):
        monkeypatch.setenv("MXNET_FAULT_INJECT", "t.slow:delay:1:0.05")
        faults.reset_faults()
        t0 = time.monotonic()
        faults.fault_point("t.slow")
        assert time.monotonic() - t0 >= 0.04
        rows = telemetry.snapshot().get("faults_injected_total", [])
        assert any(r["labels"].get("site") == "t.slow"
                   and r["value"] >= 1 for r in rows)


# --------------------------------------------------------------------- #
# serving: deadlines
# --------------------------------------------------------------------- #

class TestServeDeadline:
    def test_queue_lapsed_deadline_retires_without_slot(self, net):
        """A deadline that expires while the request is still queued
        retires at the admission boundary: zero slots, zero tokens,
        reason deadline_exceeded."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        clk = _FakeClock(srv._epoch)
        srv._clock = clk
        telemetry.clear_events()
        pA, pB = _prompt(0, 4), _prompt(1, 4)
        sA = srv.submit(pA, max_new_tokens=4)
        sB = srv.submit(pB, max_new_tokens=4, deadline=1.0)
        clk.advance(5.0)                   # B lapses before admission
        _drain(srv)
        assert sA.tokens(5) == _ref(net, pA, 4)
        assert sB.done and sB.tokens(5) == []
        evs = telemetry.events("deadline_exceeded")
        assert any(e["request_id"] == sB.request_id for e in evs)
        assert srv.stats()["in_flight"] == 0
        srv.close()

    @pytest.mark.parametrize("sampled", [False, True])
    def test_device_side_expiry_frees_slot_coresident_exact(
            self, net, sampled):
        """THE deadline acceptance bar: expiry retires the sequence
        DEVICE-SIDE at a step boundary; the co-resident stream is
        token-identical to the undisturbed run (greedy and sampled),
        admission cost one dispatch, and every decode step is exactly
        one executable dispatch — retirement adds none."""
        from mxnet_tpu.serve import DecodeServer
        kw = dict(temperature=0.7, top_k=7) if sampled else {}
        # spec=False: this test pins plain one-dispatch-per-step
        # accounting (speculative chaos lives in test_serve_spec.py)
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=False, autostart=False, **kw)
        clk = _FakeClock(srv._epoch)
        srv._clock = clk
        N = 10
        pA, pB = _prompt(2, 5), _prompt(3, 4)
        sA = srv.submit(pA, max_new_tokens=N, seed=11)
        sB = srv.submit(pB, max_new_tokens=N, seed=42, deadline=3.5)
        srv.reset_counters()
        while srv.pump():
            clk.advance(1.0)     # steps dispatch at now = 1, 2, 3, ...
        refA = _ref(net, pA, N, seed=11, **kw)
        refB = _ref(net, pB, N, seed=42, **kw)
        assert sA.tokens(5) == refA          # co-resident: exact
        got = sB.tokens(5)
        assert 0 < len(got) < N              # retired early, mid-decode
        assert got == refB[:len(got)]        # a prefix of its own run
        # dispatch accounting: 1 admit for the wave, one executable
        # dispatch per decode step (A runs its full budget), nothing
        # extra for the deadline retirement
        assert srv.counters["admit_dispatches"] == 1
        assert srv.counters["step_dispatches"] == (N - 1) + 1
        assert srv._progs.step_fn()._cache_size() == 1
        # the freed slot is reusable
        pC = _prompt(4, 3)
        sC = srv.submit(pC, max_new_tokens=3, seed=7)
        _drain(srv)
        assert sC.tokens(5) == _ref(net, pC, 3, seed=7, **kw)
        srv.close()

    def test_env_default_deadline(self, net, monkeypatch):
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_DEADLINE", "0.000001")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        assert srv.default_deadline == pytest.approx(1e-6)
        s = srv.submit(_prompt(5, 4), max_new_tokens=4)
        time.sleep(0.01)
        _drain(srv)
        assert s.done and s.tokens(5) == []
        # explicit submit(deadline=) overrides the env default
        s2 = srv.submit(_prompt(5, 4), max_new_tokens=3, deadline=60.0)
        _drain(srv)
        assert s2.tokens(5) == _ref(net, _prompt(5, 4), 3)
        srv.close()

    def test_bad_deadline_rejected(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        with pytest.raises(MXNetError, match="deadline"):
            srv.submit(_prompt(6, 3), max_new_tokens=2, deadline=-1.0)
        srv.close()

    def test_step_timeout_zero_kwarg_disables_watchdog(self, net):
        """Post-review regression: step_timeout=0 via the KWARG means
        'wedge detection off' (matching the env contract), not a
        0-second hair-trigger that kills the first pump."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           step_timeout=0, autostart=False)
        assert srv.step_timeout is None
        srv.close()


# --------------------------------------------------------------------- #
# serving: cancellation
# --------------------------------------------------------------------- #

class TestServeCancel:
    @pytest.mark.parametrize("sampled", [False, True])
    def test_cancel_mid_decode_frees_slot_coresident_exact(self, net,
                                                           sampled):
        """THE cancellation acceptance bar: cancel() frees the pool
        slot at the next step boundary, the co-resident stream is
        token-identical to an undisturbed run (greedy and sampled),
        and no extra dispatch is spent."""
        from mxnet_tpu.serve import DecodeServer
        kw = dict(temperature=0.7, top_k=7) if sampled else {}
        # spec=False: pins plain step accounting (see test_serve_spec)
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=False, autostart=False, **kw)
        N = 10
        pA, pB = _prompt(10, 5), _prompt(11, 4)
        telemetry.clear_events()
        sA = srv.submit(pA, max_new_tokens=N, seed=11)
        sB = srv.submit(pB, max_new_tokens=N, seed=42)
        srv.reset_counters()
        for _ in range(3):
            srv.pump()
        assert not sB.done
        assert sB.cancel() is True
        assert sB.cancel() is True           # idempotent while closing
        _drain(srv)
        refB = _ref(net, pB, N, seed=42, **kw)
        assert sA.tokens(5) == _ref(net, pA, N, seed=11, **kw)
        assert sB.done and sB.cancelled
        got = sB.tokens(5)                   # sealed, partial, exact
        assert 0 < len(got) < N and got == refB[:len(got)]
        assert srv.counters["admit_dispatches"] == 1
        assert srv.counters["step_dispatches"] == (N - 1) + 1
        assert srv._progs.step_fn()._cache_size() == 1
        evs = telemetry.events("request_cancelled")
        assert any(e["request_id"] == sB.request_id for e in evs)
        # the freed slot re-admits
        pC = _prompt(12, 3)
        sC = srv.submit(pC, max_new_tokens=4, seed=7)
        _drain(srv)
        assert sC.tokens(5) == _ref(net, pC, 4, seed=7, **kw)
        srv.close()

    def test_cancel_queued_request_is_immediate(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        pA, pB = _prompt(13, 4), _prompt(14, 4)
        sA = srv.submit(pA, max_new_tokens=6)
        sB = srv.submit(pB, max_new_tokens=6)   # queued: 1 slot
        assert sB.cancel() is True
        assert sB.done and sB.cancelled and sB.tokens(1) == []
        _drain(srv)
        assert sA.tokens(5) == _ref(net, pA, 6)
        assert srv.stats()["pending"] == 0
        srv.close()

    def test_sync_mode_cancel_mid_generation_reports_failure(
            self, net, monkeypatch):
        """Post-review regression: the sync fallback has no step
        boundaries — cancel() of a request already inside kv_generate
        must return False (and leave the stream intact), not claim a
        cancellation that never happens.  Queued requests still cancel
        for real."""
        from mxnet_tpu.models import decoding
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_SERVE_SYNC", "1")
        srv = DecodeServer(net, max_total_len=64, autostart=False)
        started, release = threading.Event(), threading.Event()
        real = decoding.kv_generate

        def slow(*a, **k):
            started.set()
            release.wait(10)
            return real(*a, **k)

        monkeypatch.setattr(decoding, "kv_generate", slow)
        p, p2 = _prompt(70, 4), _prompt(71, 4)
        s = srv.submit(p, max_new_tokens=3)
        s2 = srv.submit(p2, max_new_tokens=3)   # stays queued
        th = threading.Thread(target=srv.pump)
        th.start()
        assert started.wait(10)
        assert s.cancel() is False          # mid-generation: no effect
        assert s2.cancel() is True          # queued: real cancel
        release.set()
        th.join(10)
        assert s.tokens(10) == _ref(net, p, 3)   # ran to completion
        assert not s.cancelled
        assert s2.cancelled and s2.tokens(1) == []
        srv.close()

    def test_cancel_after_done_is_noop(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        p = _prompt(15, 4)
        s = srv.submit(p, max_new_tokens=3)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 3)
        assert s.cancel() is False
        assert not s.cancelled               # it completed normally
        srv.close()


# --------------------------------------------------------------------- #
# serving: scheduler death + watchdog
# --------------------------------------------------------------------- #

class TestSchedulerFailure:
    def test_injected_scheduler_death_fails_all_streams(self, net,
                                                        monkeypatch):
        """Acceptance bar (b): a fault-injected dispatch failure on the
        scheduler thread fails EVERY in-flight stream with the
        underlying error, and submit() afterwards raises cleanly
        naming it."""
        from mxnet_tpu.serve import DecodeServer
        # spec=False so the pump takes serve.step dispatches (the
        # speculative serve.verify site has its own chaos suite)
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=False, autostart=False)
        p1, p2 = _prompt(20, 4), _prompt(21, 5)
        s1 = srv.submit(p1, max_new_tokens=8)
        s2 = srv.submit(p2, max_new_tokens=8)
        monkeypatch.setenv("MXNET_FAULT_INJECT", "serve.step:raise:2")
        faults.reset_faults()
        srv.start()
        with pytest.raises(MXNetError, match="injected fault"):
            s1.tokens(30)
        with pytest.raises(MXNetError, match="injected fault"):
            s2.tokens(30)
        with pytest.raises(MXNetError, match="server failed"):
            srv.submit(p1, max_new_tokens=2)

    def test_watchdog_fires_on_wedged_pump(self, net):
        """A dispatch wedged past step_timeout cannot be recovered,
        but every consumer gets the watchdog's error instead of
        blocking forever."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           step_timeout=0.25, autostart=False)
        telemetry.clear_events()
        real_pump = srv.pump

        def wedged_pump():
            time.sleep(1.2)
            return real_pump()

        srv.pump = wedged_pump
        s = srv.submit(_prompt(22, 4), max_new_tokens=6)
        srv.start()
        with pytest.raises(MXNetError, match="watchdog"):
            s.tokens(30)
        with pytest.raises(MXNetError, match="server failed"):
            srv.submit(_prompt(22, 4), max_new_tokens=2)
        assert any(e.get("server") == srv.telemetry_label
                   for e in telemetry.events("watchdog_fired"))

    def test_late_wedged_dispatch_does_not_repin_pool(self, net):
        """Post-review regression: a wedged STEP dispatch that finally
        completes after the watchdog tore the server down must not
        re-assign the pool state — the accountant/gauges already
        report those bytes freed, and stats() must agree with the
        allocator."""
        from mxnet_tpu.serve import DecodeServer

        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           step_timeout=0.25, autostart=False)
        # warm the admit/step programs pump-driven first: the wedge
        # gauge covers whole pumps, and a first-request COMPILE would
        # trip the 0.25s timeout before the wedged step ever runs
        w = srv.submit(_prompt(24, 4), max_new_tokens=3)
        _drain(srv)
        assert w.tokens(5) == _ref(net, _prompt(24, 4), 3)
        real_step = srv._progs.step_fn()
        entered, release = threading.Event(), threading.Event()

        def wedged(*a, **k):
            entered.set()
            release.wait(10)
            return real_step(*a, **k)

        srv._progs._step = wedged
        s = srv.submit(_prompt(25, 4), max_new_tokens=6)
        srv.start()
        assert entered.wait(10)
        with pytest.raises(MXNetError, match="watchdog"):
            s.tokens(30)
        assert srv._state is None
        release.set()                  # the wedged dispatch completes
        srv._thread.join(10)
        assert srv._state is None      # ...without re-pinning the pool
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="serve.kv_pool", key=srv.telemetry_label) == 0
        assert srv.stats()["pool_bytes"] == 0

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_watchdog_fires_on_dead_pump_thread(self, net):
        """A pump thread that dies WITHOUT running its failure path
        (BaseException) is caught by the watchdog — no consumer hangs."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        s = srv.submit(_prompt(23, 4), max_new_tokens=6)

        def die():
            raise SystemExit("thread torn down")

        srv.pump = die
        srv.start()
        with pytest.raises(MXNetError, match="watchdog"):
            s.tokens(30)
        with pytest.raises(MXNetError, match="server failed"):
            srv.submit(_prompt(23, 4), max_new_tokens=2)

    def test_cold_compile_does_not_trip_step_timeout(self, net):
        """Post-review regression: the first request's jit compiles
        run far longer than a tight step_timeout — the watchdog must
        treat a cold program as a compile, not a wedged dispatch, and
        the request must serve on the healthy server."""
        from mxnet_tpu.serve import DecodeServer

        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           step_timeout=0.2, autostart=True)
        p = _prompt(26, 4)
        s = srv.submit(p, max_new_tokens=4)
        assert s.tokens(60) == _ref(net, p, 4)   # served, not killed
        p2 = _prompt(27, 3)                      # warm path too
        s2 = srv.submit(p2, max_new_tokens=3)
        assert s2.tokens(60) == _ref(net, p2, 3)
        srv.close()

    def test_pump_mode_injected_fault_surfaces_to_caller(self, net,
                                                         monkeypatch):
        """autostart=False: the injected error propagates to the
        pump() caller (no scheduler thread to kill)."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        srv.submit(_prompt(24, 4), max_new_tokens=4)
        monkeypatch.setenv("MXNET_FAULT_INJECT", "serve.admit:raise:1")
        faults.reset_faults()
        with pytest.raises(MXNetError, match="injected fault"):
            srv.pump()
        monkeypatch.delenv("MXNET_FAULT_INJECT")
        srv.close(drain=False)


# --------------------------------------------------------------------- #
# TokenStream.tokens(timeout=) reuse-after-timeout (satellite)
# --------------------------------------------------------------------- #

class TestTokensTimeoutReuse:
    def test_timed_out_consumer_can_retry_and_drain(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        p = _prompt(30, 4)
        s = srv.submit(p, max_new_tokens=5)
        with pytest.raises(MXNetError, match="not finished"):
            s.tokens(timeout=0.02)
        srv.pump()                           # partial progress
        with pytest.raises(MXNetError, match="not finished"):
            s.tokens(timeout=0.02)
        _drain(srv)
        ref = _ref(net, p, 5)
        assert s.tokens(5) == ref            # same consumer, full drain
        assert s.tokens(5) == ref            # and again
        # a second consumer that timed out earlier also drains
        got = []
        th = threading.Thread(target=lambda: got.append(s.tokens(5)))
        th.start()
        th.join(5.0)
        assert not th.is_alive() and got == [ref]
        srv.close()


# --------------------------------------------------------------------- #
# bounded distributed init + barrier
# --------------------------------------------------------------------- #

class TestBoundedInit:
    def test_rendezvous_failure_is_clean_error(self, monkeypatch):
        import jax
        from mxnet_tpu.parallel import mesh

        calls = []
        shutdowns = []

        def failing_init(**kw):
            calls.append(kw)
            raise RuntimeError("connection refused")

        monkeypatch.setattr(jax.distributed, "initialize", failing_init)
        monkeypatch.setattr(jax.distributed, "shutdown",
                            lambda: shutdowns.append(1))
        monkeypatch.setattr(mesh.time, "sleep", lambda s: None)
        with pytest.raises(MXNetError, match="coordinator 127.0.0.1:1"):
            mesh.init_distributed(coordinator_address="127.0.0.1:1",
                                  num_processes=2, process_id=0,
                                  retries=2)
        assert len(calls) == 3               # 1 + 2 retries
        # EVERY failed attempt (including the last) tears the
        # partially-assigned jax state down, so both internal retries
        # and a caller-level retry genuinely re-dial (post-review
        # regression)
        assert len(shutdowns) == 3
        msg = None
        try:
            mesh.init_distributed(coordinator_address="127.0.0.1:1",
                                  num_processes=2, process_id=1,
                                  retries=0)
        except MXNetError as e:
            msg = str(e)
        assert "rank 1/2" in msg and "MXNET_INIT_TIMEOUT" in msg

    def test_subsecond_init_timeout_rounds_up(self, monkeypatch):
        """Post-review regression: a 0.5s timeout must reach jax as
        1 (ceil), never int-truncated to 0 = an immediate deadline."""
        import jax
        from mxnet_tpu.parallel import mesh

        seen = {}

        def fake(coordinator_address=None, num_processes=None,
                 process_id=None, local_device_ids=None,
                 initialization_timeout=None):
            seen["t"] = initialization_timeout
            raise RuntimeError("stop here")

        monkeypatch.setattr(jax.distributed, "initialize", fake)
        with pytest.raises(MXNetError, match="rendezvous"):
            mesh.init_distributed(coordinator_address="127.0.0.1:1",
                                  num_processes=2, process_id=0,
                                  initialization_timeout=0.5,
                                  retries=0)
        assert seen["t"] == 1

    def test_already_initialized_passes_through(self, monkeypatch):
        import jax
        from mxnet_tpu.parallel import mesh

        def already(**kw):
            # the message real jax (0.4.x) emits on double-init
            raise RuntimeError(
                "distributed.initialize should only be called once.")

        monkeypatch.setattr(jax.distributed, "initialize", already)
        with pytest.raises(RuntimeError, match="only be called once"):
            mesh.init_distributed(coordinator_address="127.0.0.1:1",
                                  num_processes=2, process_id=0,
                                  retries=3)

    def test_single_process_noop(self):
        from mxnet_tpu.parallel import mesh
        mesh.init_distributed()              # no coordinator: no-op

    @pytest.mark.parametrize("var", ["MXNET_INIT_TIMEOUT",
                                     "MXNET_INIT_RETRIES",
                                     "MXNET_BARRIER_TIMEOUT"])
    def test_malformed_timeout_knobs_are_loud(self, var, monkeypatch):
        """Post-review regression: a typo'd timeout knob (e.g. '60s')
        must raise, not silently fall back to wait-forever/defaults —
        the hang these knobs exist to prevent."""
        from mxnet_tpu.parallel import mesh

        monkeypatch.setenv(var, "60s")
        with pytest.raises(MXNetError, match=var):
            if var == "MXNET_BARRIER_TIMEOUT":
                mesh._barrier_timeout_from_env()
            elif var == "MXNET_INIT_TIMEOUT":
                mesh._init_timeout_from_env()
            else:
                mesh._init_retries_from_env()


class TestBarrierTimeout:
    def test_single_process_returns(self):
        from mxnet_tpu.parallel import mesh
        mesh.barrier("t", timeout=0.1)       # trivially passes

    def test_timeout_names_the_hang(self, monkeypatch):
        import jax
        from jax.experimental import multihost_utils
        from mxnet_tpu.parallel import mesh

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(multihost_utils, "sync_global_devices",
                            lambda tag: time.sleep(5))
        with pytest.raises(MXNetError, match="timed out"):
            mesh.barrier("t_hang", timeout=0.2)

    def test_peer_error_surfaces(self, monkeypatch):
        import jax
        from jax.experimental import multihost_utils
        from mxnet_tpu.parallel import mesh

        def boom(tag):
            raise RuntimeError("peer went away")

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(multihost_utils, "sync_global_devices",
                            boom)
        with pytest.raises(MXNetError, match="peer went away"):
            mesh.barrier("t_err", timeout=1.0)


# --------------------------------------------------------------------- #
# heartbeat writer
# --------------------------------------------------------------------- #

class TestHeartbeat:
    def test_writer_beats_and_stops(self, tmp_path):
        from mxnet_tpu.parallel import heartbeat as hb

        path = tmp_path / "rank0.hb"
        th = hb.start_heartbeat(str(path), interval=0.05)
        try:
            assert th is not None and path.exists()
            pid, count = path.read_text().split()
            assert int(pid) == os.getpid()
            m1 = path.stat().st_mtime_ns
            time.sleep(0.2)
            assert path.stat().st_mtime_ns > m1
        finally:
            hb.stop_heartbeat()
        m2 = path.stat().st_mtime_ns
        time.sleep(0.15)
        assert path.stat().st_mtime_ns == m2   # stopped = silent

    def test_noop_without_config(self, monkeypatch):
        from mxnet_tpu.parallel import heartbeat as hb

        monkeypatch.delenv("MXNET_HEARTBEAT_FILE", raising=False)
        assert hb.start_heartbeat() is None

    def test_malformed_interval_is_loud(self, monkeypatch):
        from mxnet_tpu.parallel import heartbeat as hb

        monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "1s")
        with pytest.raises(MXNetError, match="MXNET_HEARTBEAT_INTERVAL"):
            hb.heartbeat_interval()

    def test_repoint_stops_the_old_beater(self, tmp_path):
        """Post-review regression: re-pointing the heartbeat at a new
        file must stop the old thread — a leaked beater would keep the
        OLD file fresh forever, hiding a wedged rank from its
        supervisor."""
        from mxnet_tpu.parallel import heartbeat as hb

        a, b = tmp_path / "a.hb", tmp_path / "b.hb"
        hb.start_heartbeat(str(a), interval=0.03)
        try:
            hb.start_heartbeat(str(b), interval=0.03)
            assert b.exists()
            m_a = a.stat().st_mtime_ns
            time.sleep(0.15)
            assert a.stat().st_mtime_ns == m_a   # old file went silent
            assert b.stat().st_mtime_ns          # new file beats
        finally:
            hb.stop_heartbeat()


# --------------------------------------------------------------------- #
# kvstore server: per-request error replies
# --------------------------------------------------------------------- #

class TestKVStoreServerLoop:
    def test_request_error_reported_not_fatal(self):
        """Satellite (f): a failing request comes back to the
        REQUESTING rank as an error reply; the server loop survives
        and keeps serving — its death would look like a hang to every
        worker."""
        from mxnet_tpu.kvstore import create
        from mxnet_tpu.kvstore.kvstore_server import KVStoreServer

        telemetry.clear_events()
        srv = KVStoreServer(create("local"))
        th = threading.Thread(target=srv.run, daemon=True,
                              kwargs={"serve_any_role": True})
        th.start()
        try:
            # a push to an uninitialized key fails THE REQUEST, loudly
            rep = srv.submit("push", ("nope", mx.nd.ones(2)))
            with pytest.raises(MXNetError, match="not initialized"):
                rep.wait(10)
            assert th.is_alive()             # the loop survived
            # ...and the next requests serve normally
            srv.submit("init", ("w", mx.nd.zeros(2))).wait(10)
            srv.submit("push", ("w", mx.nd.ones(2))).wait(10)
            out = srv.submit("pull", ("w", mx.nd.zeros(2))).wait(10)
            onp.testing.assert_allclose(out.asnumpy(), [1.0, 1.0])
            # unknown commands are an error reply too
            with pytest.raises(MXNetError, match="unknown command"):
                srv.submit("frobnicate").wait(10)
            assert th.is_alive()
            evs = telemetry.events("kvstore_error")
            assert any(e["command"] == "push" for e in evs)
            assert any(e["command"] == "frobnicate" for e in evs)
        finally:
            srv.stop()
            th.join(5.0)
        assert not th.is_alive()

    def test_custom_handler_and_stop_fails_queued(self):
        from mxnet_tpu.kvstore import create
        from mxnet_tpu.kvstore.kvstore_server import KVStoreServer

        srv = KVStoreServer(create("local"))
        srv.handlers["echo"] = lambda server, payload: payload * 2
        rep = srv.submit("echo", 21)
        assert rep.done is False
        assert srv.serve_one(timeout=0.1) is True
        assert rep.wait(1) == 42
        assert srv.serve_one(timeout=0.01) is False   # queue empty
        # a request queued when stop() lands with NO run() loop active
        # must be drain-rejected by stop() itself, never stranded
        queued = srv.submit("echo", 2)
        srv.stop()
        with pytest.raises(MXNetError, match="stopped"):
            queued.wait(1)
        with pytest.raises(MXNetError, match="stopped"):
            srv.submit("echo", 1)

    def test_submit_racing_stop_never_strands_a_reply(self):
        """Post-review regression: a submit whose queue-put lands after
        run()'s shutdown drain must still settle its reply (rejected) —
        reply.wait() can never block the requesting rank forever."""
        from mxnet_tpu.kvstore import create
        from mxnet_tpu.kvstore.kvstore_server import KVStoreServer

        srv = KVStoreServer(create("local"))
        real_put = srv._requests.put

        def stop_then_put(item):
            srv._stop.set()        # stop() wins the race mid-submit
            real_put(item)

        srv._requests.put = stop_then_put
        rep = srv.submit("barrier")
        with pytest.raises(MXNetError, match="stopped"):
            rep.wait(1)

    def test_run_exit_via_role_change_poisons_submit(self, monkeypatch):
        """Post-review regression: run() exiting through the DMLC_ROLE
        env check (not stop()) must still poison submit() — otherwise
        later requests enqueue into a queue nobody serves and wait()
        strands the rank."""
        from mxnet_tpu.kvstore import create
        from mxnet_tpu.kvstore.kvstore_server import KVStoreServer

        monkeypatch.setenv("DMLC_ROLE", "server")
        srv = KVStoreServer(create("local"))
        th = threading.Thread(target=srv.run, daemon=True)
        th.start()
        assert srv.submit("barrier").wait(10) is None   # serving
        monkeypatch.setenv("DMLC_ROLE", "worker")       # role flips
        th.join(10)
        assert not th.is_alive()
        with pytest.raises(MXNetError, match="stopped"):
            srv.submit("barrier")

    def test_unset_role_is_noop_and_poisons(self, monkeypatch):
        """The reference contract: run() with DMLC_ROLE unset/worker
        returns immediately (after which submit() raises rather than
        stranding a reply); serve_any_role=True opts into the loop."""
        from mxnet_tpu.kvstore import create
        from mxnet_tpu.kvstore.kvstore_server import KVStoreServer

        monkeypatch.delenv("DMLC_ROLE", raising=False)
        srv = KVStoreServer(create("local"))
        srv.run()                            # no role: immediate return
        with pytest.raises(MXNetError, match="stopped"):
            srv.submit("barrier")


# --------------------------------------------------------------------- #
# failure-cause reporting
# --------------------------------------------------------------------- #

class TestFailureReport:
    def test_failure_summary_aggregates_causes(self):
        from tools.telemetry_report import failure_summary

        events = [
            {"ts": 1, "kind": "fault_injected", "site": "serve.step",
             "fault_kind": "raise"},
            {"ts": 2, "kind": "fault_injected", "site": "serve.step",
             "fault_kind": "raise"},
            {"ts": 3, "kind": "watchdog_fired", "server": "srv0",
             "reason": "wedged"},
            {"ts": 4, "kind": "deadline_exceeded", "server": "srv0",
             "request_id": 3},
            {"ts": 5, "kind": "request_cancelled", "server": "srv0",
             "request_id": 4},
            {"ts": 6, "kind": "worker_dead", "rank": 1,
             "why": "died with signal 9"},
            {"ts": 7, "kind": "kvstore_error", "command": "push",
             "error": "MXNetError('x')"},
            {"ts": 8, "kind": "serve_request", "reason": "eos"},
        ]
        rows = failure_summary(events)
        by_kind = {r["kind"]: r for r in rows}
        assert by_kind["fault_injected"]["count"] == 2
        assert by_kind["fault_injected"]["detail"] == {
            "serve.step: raise": 2}
        assert by_kind["watchdog_fired"]["count"] == 1
        assert by_kind["deadline_exceeded"]["count"] == 1
        assert by_kind["request_cancelled"]["count"] == 1
        assert by_kind["worker_dead"]["detail"] == {
            "rank 1: died with signal 9": 1}
        assert by_kind["kvstore_error"]["count"] == 1
        assert "serve_request" not in by_kind

    def test_report_renders_failures_section(self, tmp_path):
        import subprocess
        import sys as _sys
        import json

        path = tmp_path / "rec.jsonl"
        with open(path, "w") as fh:
            for ev in ({"ts": 1, "kind": "fault_injected",
                        "site": "kvstore.push", "fault_kind": "raise"},
                       {"ts": 2, "kind": "worker_dead", "rank": 2,
                        "why": "exited with code 7"}):
                fh.write(json.dumps(ev) + "\n")
        r = subprocess.run(
            [_sys.executable, "tools/telemetry_report.py", str(path)],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert r.returncode == 0, r.stderr[-500:]
        assert "failure causes" in r.stdout
        assert "fault_injected" in r.stdout
        assert "worker_dead" in r.stdout
