"""Quantization + ONNX export + custom-op tests (reference
tests/python/quantization/, tests/python-pytest/onnx/,
tests/python/unittest/test_operator.py::test_custom_op coverage)."""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.operator as mop
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib.quantization import quantize_net, QuantizedDense
from mxnet_tpu.ops.quantization import optimal_threshold_kl


class TestQuantizeOps:
    def test_quantize_dequantize_roundtrip(self):
        x = mx.nd.array(onp.linspace(-2, 2, 16).astype(onp.float32))
        q, mn, mxr = mx.nd._contrib_quantize_v2(x)
        assert str(q.dtype) == "int8"
        deq = mx.nd._contrib_dequantize(q, mn, mxr)
        onp.testing.assert_allclose(deq.asnumpy(), x.asnumpy(), atol=0.02)

    def test_calibrated_range_clips(self):
        x = mx.nd.array(onp.array([0.1, 5.0], onp.float32))
        q, mn, mxr = mx.nd._contrib_quantize_v2(x, min_calib_range=-1.0,
                                                max_calib_range=1.0)
        assert int(q.asnumpy()[1]) == 127  # clipped at the calib range

    def test_int8_matmul_matches_fp32(self):
        rng = onp.random.RandomState(0)
        a = rng.rand(8, 16).astype(onp.float32) - 0.5
        b = rng.rand(4, 16).astype(onp.float32) - 0.5
        qa, _, amax_a = mx.nd._contrib_quantize_v2(mx.nd.array(a))
        qb, _, amax_b = mx.nd._contrib_quantize_v2(mx.nd.array(b))
        acc = mx.nd.quantized_matmul_int8(qa, qb, transpose_b=True)
        scale = (float(amax_a.asnumpy()[0]) * float(amax_b.asnumpy()[0])
                 / (127.0 * 127.0))
        out = acc.asnumpy().astype(onp.float32) * scale
        onp.testing.assert_allclose(out, a @ b.T, atol=0.05)

    def test_kl_threshold_reasonable(self):
        rng = onp.random.RandomState(0)
        data = rng.normal(0, 1, 100000)
        hist, edges = onp.histogram(data, bins=1001, range=(-8, 8))
        t = optimal_threshold_kl(hist, edges)
        # optimal clip for a unit gaussian is far below the 8-sigma tail
        assert 1.0 < t < 8.0


class TestQuantizeNet:
    def test_mlp_accuracy_preserved(self):
        # pin the init stream: the 0.9 argmax-agreement bound on 64
        # samples is draw-sensitive, and an unseeded root key makes the
        # test's pass/fail depend on suite composition
        mx.random.seed(0)
        rng = onp.random.RandomState(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
        net.initialize(mx.init.Xavier())
        X = mx.nd.array(rng.rand(64, 20).astype(onp.float32))
        ref = net(X).asnumpy()
        qnet = quantize_net(net, calib_data=[X], calib_mode="naive")
        assert any(isinstance(c, QuantizedDense)
                   for c in qnet._children.values())
        out = qnet(X).asnumpy()
        rel = onp.abs(out - ref).max() / onp.abs(ref).max()
        assert rel < 0.05
        assert (out.argmax(1) == ref.argmax(1)).mean() > 0.9

    def test_entropy_mode_runs(self):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        X = mx.nd.array(onp.random.rand(32, 6).astype(onp.float32))
        qnet = quantize_net(net, calib_data=[X], calib_mode="entropy")
        assert qnet(X).shape == (32, 8)

    def test_requires_calib_data(self):
        net = gluon.nn.Dense(4)
        with pytest.raises(MXNetError):
            quantize_net(net)


class TestONNXExport:
    def test_export_conv_net(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"), gluon.nn.MaxPool2D(2),
                gluon.nn.Flatten(), gluon.nn.Dense(10))
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(1, 3, 8, 8).astype(onp.float32))
        net(x)
        prefix = str(tmp_path / "m")
        net.export(prefix)
        out = mx.onnx.export_model(
            prefix + "-symbol.json", prefix + "-0000.params",
            input_shapes=[("data", (1, 3, 8, 8))],
            onnx_file_path=str(tmp_path / "m.onnx"))
        g = json.load(open(out))
        ops = [n["op_type"] for n in g["graph"]["nodes"]]
        assert {"Conv", "BatchNormalization", "Relu", "MaxPool",
                "Gemm"} <= set(ops)
        assert g["graph"]["inputs"][0]["name"] == "data"
        assert len(g["graph"]["initializers"]) >= 6

    def test_unsupported_op_raises(self, tmp_path):
        s = mx.sym.erfinv(mx.sym.var("x"))
        with pytest.raises(MXNetError):
            mx.onnx.export_model(s, {}, onnx_file_path=str(tmp_path / "x"))


class TestCustomOp:
    def test_forward_backward(self):
        @mop.register("t_sigmoid")
        class P(mop.CustomOpProp):
            def create_operator(self, ctx, in_shapes, in_dtypes):
                class O(mop.CustomOp):
                    def forward(self, is_train, req, in_data, out_data, aux):
                        x = in_data[0]
                        self.assign(out_data[0], req[0],
                                    1.0 / (1.0 + (-x).exp()))

                    def backward(self, req, out_grad, in_data, out_data,
                                 in_grad, aux):
                        y = out_data[0]
                        self.assign(in_grad[0], req[0],
                                    out_grad[0] * y * (1 - y))
                return O()

        x = mx.nd.array(onp.array([0.0, 1.0, -1.0], onp.float32))
        x.attach_grad()
        with autograd.record():
            y = mx.nd.Custom(x, op_type="t_sigmoid")
        y.backward(mx.nd.ones(3))
        sig = 1 / (1 + onp.exp(-x.asnumpy()))
        onp.testing.assert_allclose(y.asnumpy(), sig, rtol=1e-6)
        onp.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig),
                                    rtol=1e-5)

    def test_unregistered_raises(self):
        with pytest.raises(MXNetError):
            mx.nd.Custom(mx.nd.ones(2), op_type="nope")

    def test_grad_req_add(self):
        @mop.register("t_double")
        class P(mop.CustomOpProp):
            def create_operator(self, ctx, in_shapes, in_dtypes):
                class O(mop.CustomOp):
                    def forward(self, is_train, req, in_data, out_data, aux):
                        self.assign(out_data[0], req[0], in_data[0] * 2)

                    def backward(self, req, out_grad, in_data, out_data,
                                 in_grad, aux):
                        self.assign(in_grad[0], req[0], out_grad[0] * 2)
                return O()

        x = mx.nd.ones(3)
        x.attach_grad(grad_req="add")
        for _ in range(2):
            with autograd.record():
                y = mx.nd.Custom(x, op_type="t_double")
            y.backward(mx.nd.ones(3))
        onp.testing.assert_allclose(x.grad.asnumpy(), onp.full(3, 4.0))


class TestONNXImport:
    """onnx2mx importer (VERDICT r1 item 6): round-trip numerics through
    export_model -> import_model -> Executor."""

    def _roundtrip(self, net, x, tmp_path, in_shape):
        net.initialize(mx.init.Xavier())
        ref = net(x)
        prefix = str(tmp_path / "m")
        net.export(prefix)
        path = mx.onnx.export_model(
            prefix + "-symbol.json", prefix + "-0000.params",
            input_shapes=[("data", in_shape)],
            onnx_file_path=str(tmp_path / "m.onnx"))
        sym, arg_params, aux_params = mx.onnx.import_model(path)
        exe = sym.bind(args={**arg_params, "data": x})
        out = exe.forward()[0]
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                    rtol=1e-4, atol=1e-4)
        return sym, arg_params

    def test_mlp_roundtrip(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
        x = mx.nd.array(onp.random.rand(3, 20).astype(onp.float32))
        self._roundtrip(net, x, tmp_path, (3, 20))

    def test_conv_bn_pool_roundtrip(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"), gluon.nn.MaxPool2D(2),
                gluon.nn.Flatten(), gluon.nn.Dense(10))
        x = mx.nd.array(onp.random.rand(2, 3, 8, 8).astype(onp.float32))
        net.initialize(mx.init.Xavier())
        net(x)  # settle BN shapes
        self._roundtrip(net, x, tmp_path, (2, 3, 8, 8))

    def test_zoo_model_roundtrip(self, tmp_path):
        """An exported model-zoo network must survive the ONNX round
        trip (the VERDICT's named acceptance check)."""
        from mxnet_tpu.gluon.model_zoo.vision import get_resnet
        net = get_resnet(1, 18, thumbnail=True, classes=10)
        x = mx.nd.array(onp.random.rand(1, 3, 32, 32).astype(onp.float32))
        net.initialize(mx.init.Xavier())
        net(x)
        self._roundtrip(net, x, tmp_path, (1, 3, 32, 32))

    def test_gelu_roundtrip_matches_runtime_variant(self, tmp_path):
        """Activation('gelu') is the TANH approximation at runtime; the
        exporter must emit the matching decomposition (erf would drift up
        to ~5e-4 at |x|~2).  Large activations on purpose — the variants
        coincide near 0."""
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="gelu", in_units=8))
        x = mx.nd.array((onp.random.RandomState(0).rand(4, 8) * 6 - 3)
                        .astype(onp.float32))
        self._roundtrip(net, x, tmp_path, (4, 8))

    def test_bert_tiny_roundtrip(self, tmp_path):
        """VERDICT r3 item 8: the transformer family survives the ONNX
        round trip — BERT-tiny export -> import -> matching MLM logits
        (2e-4: the fused kernel computes exp(s-m)@v/l while the portable
        decomposition computes softmax(s)@v — same math, different f32
        rounding).  Exercises the r4 converters: flash_attention
        decomposition (MatMul/Mul/Softmax/MatMul with a static
        1/sqrt(head_dim) from the InferShape pass), gelu erf
        decomposition, slice_axis->Slice, broadcast_to->Expand, and
        dot(transpose_b) for the tied MLM head."""
        from mxnet_tpu.models import BERTModel, BERTConfig
        mx.random.seed(0)
        cfg = BERTConfig(vocab_size=211, max_length=32, num_layers=2,
                         units=32, num_heads=2, hidden_size=64,
                         dropout=0.0)
        bert = BERTModel(cfg, use_pooler=False, use_mlm=True)
        bert.initialize(mx.init.Normal(0.05))
        toks = mx.nd.array(
            onp.random.RandomState(0).randint(0, 211, (2, 16)),
            dtype="int32")
        ref = bert(toks)[-1]                       # MLM logits
        bert.hybridize()
        bert(toks)
        prefix = str(tmp_path / "bert")
        bert.export(prefix)
        path = mx.onnx.export_model(
            prefix + "-symbol.json", prefix + "-0000.params",
            input_shapes=[("data", (2, 16))], input_types="int32",
            onnx_file_path=str(tmp_path / "bert.onnx"))
        sym, arg_params, aux_params = mx.onnx.import_model(path)
        exe = sym.bind(args={**arg_params, "data": toks})
        outs = exe.forward()
        onp.testing.assert_allclose(outs[-1].asnumpy(), ref.asnumpy(),
                                    rtol=2e-4, atol=2e-4)

    def test_unknown_op_raises(self, tmp_path):
        bad = {"opset": 13, "graph": {
            "nodes": [{"op_type": "NoSuchOp", "inputs": ["x"],
                       "outputs": ["y"], "name": "n0", "attrs": {}}],
            "inputs": [{"name": "x"}], "outputs": [{"name": "y"}],
            "initializers": {}}}
        p = tmp_path / "bad.onnx.json"
        p.write_text(json.dumps(bad))
        with pytest.raises(MXNetError, match="no importer"):
            mx.onnx.import_model(str(p))


class TestQuantizedConv:
    """INT8 conv + quantize_net over a conv net (VERDICT r1 item 7)."""

    def test_quantized_conv_int8_exact(self):
        rng = onp.random.RandomState(0)
        x = rng.randint(-127, 128, (2, 3, 8, 8)).astype(onp.int8)
        w = rng.randint(-127, 128, (4, 3, 3, 3)).astype(onp.int8)
        out = mx.nd.quantized_conv_int8(
            mx.nd.array(x, dtype="int8"), mx.nd.array(w, dtype="int8"),
            pad=(1, 1))
        assert out.dtype == onp.int32
        # int32 accumulation is EXACT — compare vs float conv
        import jax.numpy as jnp
        from jax import lax
        ref = lax.conv_general_dilated(
            x.astype("float32"), w.astype("float32"), (1, 1),
            [(1, 1), (1, 1)],
            dimension_numbers=lax.conv_dimension_numbers(
                x.shape, w.shape, ("NCHW", "OIHW", "NCHW")))
        onp.testing.assert_array_equal(out.asnumpy(),
                                       onp.asarray(ref, onp.int32))

    def test_quantized_conv2d_block_close_to_fp32(self):
        from mxnet_tpu.contrib.quantization import QuantizedConv2D
        rng = onp.random.RandomState(1)
        conv = gluon.nn.Conv2D(8, 3, padding=1, in_channels=3)
        conv.initialize(mx.init.Xavier())
        x = mx.nd.array(rng.rand(2, 3, 16, 16).astype(onp.float32))
        ref = conv(x)
        q = QuantizedConv2D(conv, float(onp.abs(x.asnumpy()).max()))
        out = q(x)
        err = onp.abs(out.asnumpy() - ref.asnumpy()).max()
        scale = onp.abs(ref.asnumpy()).max()
        assert err / scale < 0.03, (err, scale)

    def test_quantize_net_resnet_agreement(self):
        """quantize_net over a zoo ResNet-18: conv+dense layers swapped,
        top-1 agreement with fp32 >= 90% on structured inputs (the
        accuracy-drop assertion; real-dataset accuracy needs data the
        sandbox doesn't ship)."""
        from mxnet_tpu.gluon.model_zoo.vision import get_resnet
        from mxnet_tpu.contrib.quantization import (quantize_net,
                                                    QuantizedConv2D,
                                                    QuantizedDense)
        mx.random.seed(0)
        net = get_resnet(1, 18, thumbnail=True, classes=10)
        net.initialize(mx.init.Xavier())
        rng = onp.random.RandomState(0)
        # smooth structured inputs (CIFAR-normalized scale)
        base = rng.rand(32, 3, 32, 32).astype(onp.float32)
        for ax in (2, 3):
            base = (onp.roll(base, 1, ax) + base +
                    onp.roll(base, -1, ax)) / 3.0
        x = mx.nd.array((base - 0.5) * 4.0)
        ref = net(x).asnumpy()
        calib = [mx.nd.array((base[i:i + 8] - 0.5) * 4.0)
                 for i in range(0, 32, 8)]
        qnet = quantize_net(net, calib_data=calib, calib_mode="naive")
        n_q = [0, 0]

        def count(b):
            for c in b._children.values():
                if isinstance(c, QuantizedConv2D):
                    n_q[0] += 1
                elif isinstance(c, QuantizedDense):
                    n_q[1] += 1
                else:
                    count(c)
        count(qnet)
        assert n_q[0] >= 10, f"conv layers quantized: {n_q[0]}"
        out = qnet(x).asnumpy()
        agree = (out.argmax(1) == ref.argmax(1)).mean()
        assert agree >= 0.9, agree


class TestONNXShapeFreeDot:
    """ADVICE r4: a plain 2-D no-transpose dot must export without
    input_shapes (MatMul is semantically identical for rank 2); the
    transpose flags still demand shape proof."""

    def test_plain_dot_exports_without_shapes(self, tmp_path):
        s = mx.sym.dot(mx.sym.var("a"), mx.sym.var("b"))
        out = mx.onnx.export_model(
            s, {}, onnx_file_path=str(tmp_path / "d.onnx"))
        g = json.load(open(out))
        assert "MatMul" in [n["op_type"] for n in g["graph"]["nodes"]]

    def test_transposed_dot_without_shapes_raises(self, tmp_path):
        s = mx.sym.dot(mx.sym.var("a"), mx.sym.var("b"), transpose_b=True)
        with pytest.raises(MXNetError):
            mx.onnx.export_model(
                s, {}, onnx_file_path=str(tmp_path / "dt.onnx"))
