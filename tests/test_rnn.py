"""RNN layers and cells (reference test model: tests/python/unittest/
test_gluon_rnn.py — golden/consistency checks between fused layers and
unrolled cells)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


def _np(x):
    return x.asnumpy()


@pytest.mark.parametrize("mode,cls,cell_cls", [
    ("lstm", rnn.LSTM, rnn.LSTMCell),
    ("gru", rnn.GRU, rnn.GRUCell),
    ("rnn_relu", rnn.RNN, rnn.RNNCell),
])
def test_layer_matches_cell(mode, cls, cell_cls):
    """The fused layer and the unrolled cell share math and parameters."""
    mx.random.seed(0)
    T, N, C, H = 5, 3, 4, 6
    x = mx.nd.array(onp.random.RandomState(0).randn(T, N, C))

    layer = cls(H, input_size=C) if mode != "rnn_relu" else \
        rnn.RNN(H, activation="relu", input_size=C)
    layer.initialize(mx.init.Xavier())
    out = layer(x)
    assert out.shape == (T, N, H)

    cell = cell_cls(H, input_size=C) if mode != "rnn_relu" else \
        rnn.RNNCell(H, activation="relu", input_size=C)
    cell.initialize()
    # copy layer params into the cell
    lp = {p.name.split("_", 1)[1] if "_l0_" not in p.name else p.name:
          p for p in layer.collect_params().values()}
    mapping = {}
    for name, p in layer.collect_params().items():
        short = name[name.index("l0_") + 3:] if "l0_" in name else name
        mapping[short] = p
    for name, p in cell.collect_params().items():
        for k in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
            if name.endswith(k):
                p.set_data(mapping[k].data())
    outs, states = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    onp.testing.assert_allclose(_np(out), _np(outs), rtol=2e-5, atol=2e-5)


def test_lstm_states_and_grad():
    T, N, C, H = 4, 2, 3, 5
    lstm = rnn.LSTM(H, num_layers=2, input_size=C)
    lstm.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(1).randn(T, N, C))
    begin = lstm.begin_state(N)
    with autograd.record():
        out, states = lstm(x, begin)
        loss = (out ** 2).sum()
    loss.backward()
    assert out.shape == (T, N, H)
    assert states[0].shape == (2, N, H)
    assert states[1].shape == (2, N, H)
    g = lstm.collect_params()[lstm.prefix + "l0_i2h_weight"].grad()
    assert float(g.abs().sum().asnumpy()) > 0


def test_bidirectional_lstm_shape():
    T, N, C, H = 4, 2, 3, 5
    lstm = rnn.LSTM(H, bidirectional=True, input_size=C)
    lstm.initialize()
    out = lstm(mx.nd.array(onp.random.randn(T, N, C)))
    assert out.shape == (T, N, 2 * H)


def test_ntc_layout():
    N, T, C, H = 2, 6, 3, 4
    gru = rnn.GRU(H, layout="NTC", input_size=C)
    gru.initialize()
    out = gru(mx.nd.array(onp.random.randn(N, T, C)))
    assert out.shape == (N, T, H)


def test_deferred_input_size():
    lstm = rnn.LSTM(4)
    lstm.initialize()
    out = lstm(mx.nd.array(onp.random.randn(3, 2, 7)))
    assert out.shape == (3, 2, 4)
    assert lstm.l0_i2h_weight.shape == (16, 7)


def test_hybridized_rnn():
    lstm = rnn.LSTM(4, input_size=3)
    lstm.initialize()
    x = mx.nd.array(onp.random.RandomState(2).randn(5, 2, 3))
    ref = lstm(x).asnumpy()
    lstm.hybridize()
    out = lstm(x).asnumpy()
    onp.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-5)


def test_sequential_and_wrappers():
    T, N, C, H = 5, 2, 4, 4
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(H, input_size=C))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(H, input_size=H)))
    stack.add(rnn.DropoutCell(0.0))
    stack.initialize()
    x = mx.nd.array(onp.random.randn(T, N, C))
    outs, states = stack.unroll(T, x, layout="TNC", merge_outputs=True)
    assert outs.shape == (T, N, H)
    # LSTM contributes (h, c) per cell; dropout none
    assert len(states) == 4


def test_bidirectional_cell():
    T, N, C, H = 4, 2, 3, 5
    bi = rnn.BidirectionalCell(rnn.LSTMCell(H, input_size=C),
                               rnn.LSTMCell(H, input_size=C))
    bi.initialize()
    x = mx.nd.array(onp.random.randn(T, N, C))
    outs, states = bi.unroll(T, x, layout="TNC", merge_outputs=True)
    assert outs.shape == (T, N, 2 * H)


def test_rnn_dropout_multilayer():
    lstm = rnn.LSTM(4, num_layers=3, dropout=0.5, input_size=3)
    lstm.initialize()
    x = mx.nd.array(onp.random.randn(5, 2, 3))
    with autograd.record(train_mode=True):
        out = lstm(x)
    assert out.shape == (5, 2, 4)
    # eval mode: no dropout, deterministic
    a = lstm(x).asnumpy()
    b = lstm(x).asnumpy()
    onp.testing.assert_allclose(a, b)


def test_unroll_valid_length():
    T, N, C, H = 6, 3, 2, 4
    cell = rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    x = mx.nd.array(onp.random.randn(N, T, C))
    vl = mx.nd.array([2, 4, 6])
    outs, states = cell.unroll(T, x, layout="NTC", merge_outputs=True,
                               valid_length=vl)
    o = outs.asnumpy()
    # outputs past valid_length are zeroed
    assert abs(o[0, 2:]).max() == 0
    assert abs(o[1, 4:]).max() == 0
    assert abs(o[0, :2]).max() > 0
