"""Extended linalg family (mxnet_tpu/ops/linalg.py — reference
``src/operator/tensor/la_op.cc``): golden numerics vs numpy + gradient
checks through the tape."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def _rand_spd(n, batch=()):
    rng = onp.random.RandomState(0)
    a = rng.rand(*batch, n, n).astype(onp.float32)
    return a @ a.swapaxes(-1, -2) + n * onp.eye(n, dtype=onp.float32)


class TestLinalgGolden:
    def test_gemm(self):
        rng = onp.random.RandomState(1)
        A = rng.rand(2, 3, 4).astype(onp.float32)
        B = rng.rand(2, 4, 5).astype(onp.float32)
        C = rng.rand(2, 3, 5).astype(onp.float32)
        out = mx.nd.linalg_gemm(mx.nd.array(A), mx.nd.array(B),
                                mx.nd.array(C), alpha=2.0, beta=0.5)
        onp.testing.assert_allclose(out.asnumpy(), 2.0 * A @ B + 0.5 * C,
                                    rtol=1e-5, atol=1e-5)

    def test_trmm(self):
        rng = onp.random.RandomState(2)
        A = rng.rand(4, 4).astype(onp.float32)
        B = rng.rand(4, 3).astype(onp.float32)
        out = mx.nd.linalg_trmm(mx.nd.array(A), mx.nd.array(B), alpha=1.5)
        onp.testing.assert_allclose(out.asnumpy(),
                                    1.5 * onp.tril(A) @ B,
                                    rtol=1e-5, atol=1e-5)

    def test_potri_inverse_from_cholesky(self):
        M = _rand_spd(4)
        L = onp.linalg.cholesky(M)
        out = mx.nd.linalg_potri(mx.nd.array(L))
        onp.testing.assert_allclose(out.asnumpy(), onp.linalg.inv(M),
                                    rtol=1e-3, atol=1e-4)

    def test_gelqf(self):
        rng = onp.random.RandomState(3)
        A = rng.rand(3, 5).astype(onp.float32)
        Q, L = mx.nd.linalg_gelqf(mx.nd.array(A))
        Qn, Ln = Q.asnumpy(), L.asnumpy()
        onp.testing.assert_allclose(Ln @ Qn, A, rtol=1e-4, atol=1e-5)
        onp.testing.assert_allclose(Qn @ Qn.T, onp.eye(3), rtol=1e-4,
                                    atol=1e-5)
        # L lower-triangular
        onp.testing.assert_allclose(Ln, onp.tril(Ln), atol=1e-6)

    def test_syevd(self):
        M = _rand_spd(4)
        U, lam = mx.nd.linalg_syevd(mx.nd.array(M))
        Un, ln = U.asnumpy(), lam.asnumpy()
        # rows of U are eigenvectors: U^T diag(l) U == M
        onp.testing.assert_allclose(Un.T @ onp.diag(ln) @ Un, M,
                                    rtol=1e-3, atol=1e-3)

    def test_sumlogdiag(self):
        M = _rand_spd(5)
        out = mx.nd.linalg_sumlogdiag(mx.nd.array(M))
        onp.testing.assert_allclose(out.asnumpy(),
                                    onp.log(onp.diag(M)).sum(),
                                    rtol=1e-5)

    def test_extract_make_diag_roundtrip(self):
        rng = onp.random.RandomState(4)
        v = rng.rand(2, 3).astype(onp.float32)
        M = mx.nd.linalg_makediag(mx.nd.array(v))
        assert M.shape == (2, 3, 3)
        back = mx.nd.linalg_extractdiag(M)
        onp.testing.assert_allclose(back.asnumpy(), v, rtol=1e-6)

    def test_extract_make_trian_roundtrip(self):
        rng = onp.random.RandomState(5)
        A = rng.rand(4, 4).astype(onp.float32)
        packed = mx.nd.linalg_extracttrian(mx.nd.array(A))
        assert packed.shape == (10,)
        M = mx.nd.linalg_maketrian(packed)
        onp.testing.assert_allclose(M.asnumpy(), onp.tril(A), rtol=1e-6)

    def test_det_slogdet_inverse(self):
        M = _rand_spd(3)
        det = mx.nd.linalg_det(mx.nd.array(M))
        onp.testing.assert_allclose(det.asnumpy(), onp.linalg.det(M),
                                    rtol=1e-3)
        sign, logabs = mx.nd.linalg_slogdet(mx.nd.array(M))
        onp.testing.assert_allclose(sign.asnumpy() *
                                    onp.exp(logabs.asnumpy()),
                                    onp.linalg.det(M), rtol=1e-3)
        inv = mx.nd.linalg_inverse(mx.nd.array(M))
        onp.testing.assert_allclose(inv.asnumpy() @ M, onp.eye(3),
                                    rtol=1e-3, atol=1e-3)


class TestLinalgGrad:
    def test_det_grad(self):
        """d det(A) / dA = det(A) * A^{-T}."""
        M = _rand_spd(3)
        x = mx.nd.array(M)
        x.attach_grad()
        with autograd.record():
            d = mx.nd.linalg_det(x)
        d.backward()
        expected = onp.linalg.det(M) * onp.linalg.inv(M).T
        onp.testing.assert_allclose(x.grad.asnumpy(), expected,
                                    rtol=1e-3, atol=1e-3)

    def test_gemm_grad(self):
        rng = onp.random.RandomState(6)
        A = mx.nd.array(rng.rand(3, 4).astype(onp.float32))
        B = mx.nd.array(rng.rand(4, 2).astype(onp.float32))
        C = mx.nd.array(rng.rand(3, 2).astype(onp.float32))
        for t in (A, B, C):
            t.attach_grad()
        with autograd.record():
            out = mx.nd.linalg_gemm(A, B, C, alpha=2.0, beta=3.0)
            loss = out.sum()
        loss.backward()
        ones = onp.ones((3, 2), onp.float32)
        onp.testing.assert_allclose(A.grad.asnumpy(),
                                    2.0 * ones @ B.asnumpy().T,
                                    rtol=1e-5)
        onp.testing.assert_allclose(C.grad.asnumpy(), 3.0 * ones,
                                    rtol=1e-6)


class TestOptimizerOps:
    """mx.nd.*_update fused optimizer ops (reference optimizer_op.cc)."""

    def test_sgd_update(self):
        w = mx.nd.array(onp.full(4, 2.0, onp.float32))
        g = mx.nd.array(onp.full(4, 1.0, onp.float32))
        out = mx.nd.sgd_update(w, g, lr=0.5, wd=0.1)
        onp.testing.assert_allclose(out.asnumpy(),
                                    2.0 - 0.5 * (1.0 + 0.1 * 2.0),
                                    rtol=1e-6)

    def test_sgd_mom_matches_optimizer_class(self):
        """The op formula must match mxnet_tpu.optimizer.SGD step-by-step."""
        rng = onp.random.RandomState(0)
        w0 = rng.rand(5).astype(onp.float32)
        grads = [rng.rand(5).astype(onp.float32) for _ in range(3)]
        # op path
        w = mx.nd.array(w0)
        mom = mx.nd.zeros((5,))
        for g in grads:
            w, mom = mx.nd.sgd_mom_update(w, mx.nd.array(g), mom, lr=0.1,
                                          momentum=0.9, wd=0.01)
        # optimizer-class path
        opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.01)
        w2 = mx.nd.array(w0)
        state = opt.create_state(0, w2)
        for g in grads:
            state = opt.update(0, w2, mx.nd.array(g), state)
        onp.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-5,
                                    atol=1e-6)

    def test_adam_update_formula(self):
        rng = onp.random.RandomState(1)
        w0 = rng.rand(3).astype(onp.float32)
        g0 = rng.rand(3).astype(onp.float32)
        w, m, v = mx.nd.adam_update(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((3,)),
            mx.nd.zeros((3,)), lr=0.01, beta1=0.9, beta2=0.999,
            epsilon=1e-8)
        m_ref = 0.1 * g0
        v_ref = 0.001 * g0 * g0
        w_ref = w0 - 0.01 * m_ref / (onp.sqrt(v_ref) + 1e-8)
        onp.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5)
        onp.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-5)
        onp.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-5)

    def test_mp_sgd_keeps_fp32_master(self):
        w = mx.nd.array(onp.full(3, 1.0, onp.float16))
        w32 = mx.nd.array(onp.full(3, 1.0, onp.float32))
        g = mx.nd.array(onp.full(3, 1e-4, onp.float16))
        w_new, w32_new = mx.nd.mp_sgd_update(w, g, w32, lr=1.0)
        assert w_new.dtype == onp.float16
        assert w32_new.dtype == onp.float32
        onp.testing.assert_allclose(w32_new.asnumpy(), 1.0 - 1e-4,
                                    rtol=1e-6)

    def test_lamb_two_phase(self):
        rng = onp.random.RandomState(2)
        w0 = rng.rand(4).astype(onp.float32)
        g0 = rng.rand(4).astype(onp.float32)
        d, m, v = mx.nd.lamb_update_phase1(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((4,)),
            mx.nd.zeros((4,)), t=1, wd=0.01)
        r1 = mx.nd.array(onp.array([onp.linalg.norm(w0)], onp.float32))
        r2 = mx.nd.norm(d).reshape((1,))
        w_new = mx.nd.lamb_update_phase2(mx.nd.array(w0), d, r1, r2, lr=0.1)
        ratio = onp.linalg.norm(w0) / onp.linalg.norm(d.asnumpy())
        ref = w0 - 0.1 * ratio * d.asnumpy()
        onp.testing.assert_allclose(w_new.asnumpy(), ref, rtol=1e-4)

    def test_multi_sgd_mom(self):
        w1, g1, m1 = (onp.ones(2, onp.float32) * x for x in (1, 2, 0))
        w2, g2, m2 = (onp.ones(3, onp.float32) * x for x in (3, 4, 0))
        outs = mx.nd.multi_sgd_mom_update(
            *[mx.nd.array(a) for a in (w1, g1, m1, w2, g2, m2)],
            lrs=(0.1, 0.2), wds=(0.0, 0.0), momentum=0.9)
        assert len(outs) == 4
        onp.testing.assert_allclose(outs[0].asnumpy(), 1 - 0.1 * 2,
                                    rtol=1e-6)
        onp.testing.assert_allclose(outs[2].asnumpy(), 3 - 0.2 * 4,
                                    rtol=1e-6)

    def test_rmsprop_and_adagrad_shapes(self):
        w = mx.nd.ones((3,))
        g = mx.nd.ones((3,))
        n = mx.nd.zeros((3,))
        w2, n2 = mx.nd.rmsprop_update(w, g, n, lr=0.1)
        assert w2.shape == (3,) and float(n2.asnumpy()[0]) > 0
        h = mx.nd.zeros((3,))
        w3, h2 = mx.nd.adagrad_update(w, g, h, lr=0.1)
        assert float(h2.asnumpy()[0]) == 1.0


class TestMaketrianOffsets:
    """offset != 0 round-trips (review finding: inverted grow/shrink
    selector)."""

    @pytest.mark.parametrize("offset,lower", [(1, True), (-1, True),
                                              (1, False), (-1, False)])
    def test_roundtrip(self, offset, lower):
        rng = onp.random.RandomState(0)
        A = rng.rand(4, 4).astype(onp.float32)
        tri = onp.tril(A, offset) if lower else onp.triu(A, offset)
        packed = mx.nd.linalg_extracttrian(mx.nd.array(A), offset=offset,
                                           lower=lower)
        M = mx.nd.linalg_maketrian(packed, offset=offset, lower=lower)
        assert M.shape == (4, 4)
        onp.testing.assert_allclose(M.asnumpy(), tri, rtol=1e-6)


class TestOptimizerOpsGolden:
    """Golden formulas for the round-2 update ops not covered above."""

    def test_nag_mom(self):
        w0 = onp.array([1.0, 2.0], onp.float32)
        g0 = onp.array([0.5, -0.5], onp.float32)
        w, mom = mx.nd.nag_mom_update(mx.nd.array(w0), mx.nd.array(g0),
                                      mx.nd.zeros((2,)), lr=0.1,
                                      momentum=0.9)
        mom_ref = g0
        w_ref = w0 - 0.1 * (g0 + 0.9 * mom_ref)
        onp.testing.assert_allclose(mom.asnumpy(), mom_ref, rtol=1e-6)
        onp.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-6)

    def test_signsgd_and_signum(self):
        w0 = onp.array([1.0, 1.0], onp.float32)
        g0 = onp.array([0.3, -0.7], onp.float32)
        w = mx.nd.signsgd_update(mx.nd.array(w0), mx.nd.array(g0), lr=0.1)
        onp.testing.assert_allclose(w.asnumpy(),
                                    w0 - 0.1 * onp.sign(g0), rtol=1e-6)
        w2, m2 = mx.nd.signum_update(mx.nd.array(w0), mx.nd.array(g0),
                                     mx.nd.zeros((2,)), lr=0.1,
                                     momentum=0.9)
        m_ref = -(1 - 0.9) * g0
        onp.testing.assert_allclose(m2.asnumpy(), m_ref, rtol=1e-6)
        onp.testing.assert_allclose(w2.asnumpy(),
                                    w0 + 0.1 * onp.sign(m_ref), rtol=1e-6)

    def test_adadelta(self):
        w0 = onp.array([1.0], onp.float32)
        g0 = onp.array([0.5], onp.float32)
        w, ag, ad = mx.nd.adadelta_update(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((1,)),
            mx.nd.zeros((1,)), rho=0.9, epsilon=1e-5)
        ag_ref = 0.1 * g0 * g0
        delta = onp.sqrt(1e-5) / onp.sqrt(ag_ref + 1e-5) * g0
        onp.testing.assert_allclose(ag.asnumpy(), ag_ref, rtol=1e-5)
        onp.testing.assert_allclose(w.asnumpy(), w0 - delta, rtol=1e-5)
        onp.testing.assert_allclose(ad.asnumpy(), 0.1 * delta * delta,
                                    rtol=1e-5)

    def test_rmspropalex_centered(self):
        w0 = onp.array([1.0], onp.float32)
        g0 = onp.array([0.5], onp.float32)
        w, n, gs, d = mx.nd.rmspropalex_update(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((1,)),
            mx.nd.zeros((1,)), mx.nd.zeros((1,)), lr=0.1, gamma1=0.95,
            gamma2=0.9, epsilon=1e-8)
        n_ref = 0.05 * g0 * g0
        g_ref = 0.05 * g0
        d_ref = -0.1 * g0 / onp.sqrt(n_ref - g_ref * g_ref + 1e-8)
        onp.testing.assert_allclose(d.asnumpy(), d_ref, rtol=1e-5)
        onp.testing.assert_allclose(w.asnumpy(), w0 + d_ref, rtol=1e-5)

    def test_ftrl_sparse_zeroing(self):
        """FTRL zeroes weights whose |z| <= lamda1 (the L1 sparsity)."""
        w0 = onp.array([1.0, 1.0], onp.float32)
        g0 = onp.array([1e-4, 5.0], onp.float32)
        w, z, n = mx.nd.ftrl_update(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((2,)),
            mx.nd.zeros((2,)), lr=0.1, lamda1=0.01)
        out = w.asnumpy()
        assert abs(out[0]) < 1e-6       # tiny |z| -> zeroed
        assert abs(out[1]) > 0.0        # large grad -> survives

    def test_ftml(self):
        w0 = onp.array([1.0], onp.float32)
        g0 = onp.array([0.5], onp.float32)
        w, d, v, z = mx.nd.ftml_update(
            mx.nd.array(w0), mx.nd.array(g0), mx.nd.zeros((1,)),
            mx.nd.zeros((1,)), mx.nd.zeros((1,)), lr=0.1, beta1=0.6,
            beta2=0.999, epsilon=1e-8, t=1)
        v_ref = 0.001 * g0 * g0
        d_ref = (1 - 0.6) / 0.1 * (onp.sqrt(v_ref / (1 - 0.999)) + 1e-8)
        sigma = d_ref
        z_ref = (1 - 0.6) * g0 - sigma * w0
        onp.testing.assert_allclose(w.asnumpy(), -z_ref / d_ref, rtol=1e-4)
