"""Round-3 corpus: random/sample_* ops, mx.nd.image.*, fused multi-tensor
optimizer ops, int8 stragglers (golden + statistical tests)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestRandomOps:
    def setup_method(self, _):
        mx.random.seed(7)

    def test_random_uniform_range(self):
        x = nd._random_uniform(low=2.0, high=5.0, shape=(1000,)).asnumpy()
        assert x.min() >= 2.0 and x.max() < 5.0
        assert abs(x.mean() - 3.5) < 0.15

    def test_random_normal_moments(self):
        x = nd._random_normal(loc=1.0, scale=2.0, shape=(4000,)).asnumpy()
        assert abs(x.mean() - 1.0) < 0.15 and abs(x.std() - 2.0) < 0.15

    def test_random_poisson_mean(self):
        x = nd._random_poisson(lam=4.0, shape=(2000,)).asnumpy()
        assert abs(x.mean() - 4.0) < 0.3

    def test_randint_bounds(self):
        x = nd.random_randint(low=3, high=9, shape=(500,)).asnumpy()
        assert x.min() >= 3 and x.max() < 9

    def test_sample_normal_per_row_params(self):
        mu = nd.array(onp.asarray([0.0, 10.0], "float32"))
        sg = nd.array(onp.asarray([1.0, 0.1], "float32"))
        x = nd.sample_normal(mu, sg, shape=(2000,)).asnumpy()
        assert x.shape == (2, 2000)
        assert abs(x[0].mean()) < 0.2
        assert abs(x[1].mean() - 10) < 0.05
        assert x[1].std() < 0.2

    def test_sample_multinomial_distribution(self):
        p = nd.array(onp.asarray([[0.8, 0.2, 0.0]], "float32"))
        x = nd.sample_multinomial(p, shape=(3000,)).asnumpy()
        assert x.shape == (1, 3000)
        frac0 = (x == 0).mean()
        assert 0.75 < frac0 < 0.85
        assert not (x == 2).any()

    def test_sample_gamma_mean(self):
        a = nd.array(onp.asarray([2.0], "float32"))
        b = nd.array(onp.asarray([3.0], "float32"))
        x = nd.sample_gamma(a, b, shape=(4000,)).asnumpy()
        assert abs(x.mean() - 6.0) < 0.5  # mean = alpha * beta


class TestImageOps:
    def test_to_tensor_and_normalize(self):
        img = onp.random.RandomState(0).randint(
            0, 255, (8, 6, 3)).astype("uint8")
        t = nd.image.to_tensor(nd.array(img)).asnumpy()
        assert t.shape == (3, 8, 6)
        onp.testing.assert_allclose(
            t, img.transpose(2, 0, 1) / 255.0, rtol=1e-6)
        norm = nd.image.normalize(nd.array(t), mean=(0.5, 0.5, 0.5),
                                  std=(0.25, 0.25, 0.25)).asnumpy()
        onp.testing.assert_allclose(norm, (t - 0.5) / 0.25, rtol=1e-5)

    def test_crop_and_flips(self):
        img = onp.arange(4 * 5 * 3, dtype=onp.float32).reshape(4, 5, 3)
        c = nd.image.crop(nd.array(img), x=1, y=2, width=3,
                          height=2).asnumpy()
        onp.testing.assert_allclose(c, img[2:4, 1:4])
        lr = nd.image.flip_left_right(nd.array(img)).asnumpy()
        onp.testing.assert_allclose(lr, img[:, ::-1])
        tb = nd.image.flip_top_bottom(nd.array(img)).asnumpy()
        onp.testing.assert_allclose(tb, img[::-1])

    def test_resize_batch(self):
        img = onp.random.RandomState(1).rand(2, 8, 8, 3).astype("float32")
        out = nd.image.resize(nd.array(img), size=(4, 4)).asnumpy()
        assert out.shape == (2, 4, 4, 3)

    def test_random_brightness_scales(self):
        mx.random.seed(0)
        img = onp.full((4, 4, 3), 100.0, "float32")
        out = nd.image.random_brightness(nd.array(img), min_factor=0.5,
                                         max_factor=1.5).asnumpy()
        f = out[0, 0, 0] / 100.0
        assert 0.5 <= f <= 1.5
        onp.testing.assert_allclose(out, 100 * f, rtol=1e-5)

    def test_random_ops_draw_per_image_on_batches(self):
        mx.random.seed(0)
        batch = onp.full((16, 4, 4, 3), 100.0, "float32")
        out = nd.image.random_brightness(nd.array(batch), min_factor=0.5,
                                         max_factor=1.5).asnumpy()
        factors = out[:, 0, 0, 0] / 100.0
        assert onp.all((factors >= 0.5) & (factors <= 1.5))
        # 16 images sharing one draw is ~0 probability; require diversity
        assert onp.unique(onp.round(factors, 5)).size > 1
        for i in range(16):
            onp.testing.assert_allclose(out[i], 100 * factors[i], rtol=1e-5)
        # per-image flips: with 16 images, both outcomes should appear
        img = onp.arange(16 * 4 * 4 * 3, dtype="float32").reshape(16, 4, 4, 3)
        fl = nd.image.random_flip_left_right(nd.array(img)).asnumpy()
        flipped = onp.array([not onp.allclose(fl[i], img[i])
                             for i in range(16)])
        assert flipped.any() and not flipped.all()


class TestMultiTensorOps:
    def test_multi_adamw_matches_singles(self):
        rng = onp.random.RandomState(0)
        ws = [rng.randn(4, 4).astype("float32") for _ in range(3)]
        gs = [rng.randn(4, 4).astype("float32") for _ in range(3)]
        ms = [onp.zeros((4, 4), "float32") for _ in range(3)]
        vs = [onp.zeros((4, 4), "float32") for _ in range(3)]
        flat = []
        for w, g, m, v in zip(ws, gs, ms, vs):
            flat += [nd.array(w), nd.array(g), nd.array(m), nd.array(v)]
        outs = nd.multi_adamw_update(flat, lrs=0.01, etas=1.0, wds=0.0,
                                     step_count=1)
        assert len(outs) == 9
        # reference: single adamw math
        b1, b2, eps = 0.9, 0.999, 1e-8
        for i, (w, g) in enumerate(zip(ws, gs)):
            m = (1 - b1) * g
            v = (1 - b2) * g * g
            mhat = m / (1 - b1)
            vhat = v / (1 - b2)
            expect = w - 0.01 * mhat / (onp.sqrt(vhat) + eps)
            onp.testing.assert_allclose(outs[3 * i].asnumpy(), expect,
                                        rtol=1e-5, atol=1e-6)

    def test_preloaded_multi_sgd(self):
        w = nd.array(onp.ones((3,), "float32"))
        g = nd.array(onp.full((3,), 2.0, "float32"))
        lrs = nd.array(onp.asarray([0.1], "float32"))
        wds = nd.array(onp.asarray([0.0], "float32"))
        (nw,) = nd.preloaded_multi_sgd_update([w, g, lrs, wds])
        onp.testing.assert_allclose(nw.asnumpy(), [0.8, 0.8, 0.8],
                                    rtol=1e-6)

    def test_multi_lamb_trust_ratio_bounded(self):
        rng = onp.random.RandomState(1)
        flat = [nd.array(rng.randn(8, 8).astype("float32")),
                nd.array(rng.randn(8, 8).astype("float32")),
                nd.array(onp.zeros((8, 8), "float32")),
                nd.array(onp.zeros((8, 8), "float32"))]
        nw, nm, nv = nd.multi_lamb_update(flat, learning_rates=0.01,
                                          step_count=1)
        assert onp.isfinite(nw.asnumpy()).all()
        assert not onp.allclose(nw.asnumpy(), flat[0].asnumpy())


class TestContribStragglers:
    def test_index_copy_add(self):
        old = nd.array(onp.zeros((5, 2), "float32"))
        idx = nd.array(onp.asarray([1, 3], "float32"))
        new = nd.array(onp.ones((2, 2), "float32"))
        out = nd.contrib.index_copy(old, idx, new).asnumpy() \
            if hasattr(nd.contrib, "index_copy") else \
            nd._contrib_index_copy(old, idx, new).asnumpy()
        assert out[1].sum() == 2 and out[3].sum() == 2 and out[0].sum() == 0
        out = nd._contrib_index_add(nd.array(onp.ones((5, 2), "float32")),
                                    idx, new).asnumpy()
        assert out[1, 0] == 2 and out[0, 0] == 1

    def test_div_sqrt_dim(self):
        x = nd.array(onp.full((2, 16), 4.0, "float32"))
        onp.testing.assert_allclose(nd._contrib_div_sqrt_dim(x).asnumpy(),
                                    onp.full((2, 16), 1.0), rtol=1e-6)

    def test_gradientmultiplier_reverses(self):
        from mxnet_tpu import autograd
        x = nd.array(onp.ones((3,), "float32"))
        x.attach_grad()
        with autograd.record():
            y = nd._contrib_gradientmultiplier(x, scalar=-2.0)
            loss = (y * y).sum()
        loss.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [-4, -4, -4],
                                    rtol=1e-5)

    def test_quadratic(self):
        x = nd.array(onp.asarray([1.0, 2.0], "float32"))
        onp.testing.assert_allclose(
            nd.quadratic(x, a=1.0, b=2.0, c=3.0).asnumpy(), [6, 11],
            rtol=1e-6)

    def test_quantized_act_relu(self):
        d = nd.array(onp.asarray([-100, -5, 0, 50], "int8"))
        mn = nd.array(onp.asarray(-1.0, "float32"))
        mxv = nd.array(onp.asarray(1.0, "float32"))
        q, qmin, qmax = nd.quantized_act_int8(d, mn, mxv)
        # affine: real = (q+128)*scale + min; zero point for [-1,1] is
        # q = round(1/scale) - 128 = round(127.5) - 128 = 0
        onp.testing.assert_array_equal(q.asnumpy(), [0, 0, 0, 50])
        # range unchanged so consumers dequantize clamped values exactly
        assert float(onp.asarray(qmin.asnumpy()).reshape(())) == -1.0
        assert float(onp.asarray(qmax.asnumpy()).reshape(())) == 1.0

    def test_quantized_pooling_avg_round_trip(self):
        x = onp.asarray([[[[0, 127], [-128, 1]]]], "int8")  # NCHW 2x2
        q, mn, mx_ = nd.quantized_pooling_int8(
            nd.array(x), nd.array(onp.float32(-1)),
            nd.array(onp.float32(1)), kernel=(2, 2), pool_type="avg")
        scale = 2.0 / 255
        real = (x.astype("float32") + 128) * scale - 1
        expect = real.mean()
        got = (float(q.asnumpy().reshape(-1)[0]) + 128) * scale - 1
        assert abs(got - expect) < scale  # within one quantization step


class TestR4OpAdditions:
    """Ops added from the r4 name-gap probe: reshape_like, unique,
    make_loss, sample NB variants, and the multinomial/interp aliases."""

    def test_reshape_like(self):
        a = nd.array(onp.arange(12, dtype="float32").reshape(3, 4))
        b = nd.array(onp.zeros((2, 6), "float32"))
        out = nd.reshape_like(a, b)
        assert out.shape == (2, 6)
        onp.testing.assert_allclose(out.asnumpy().reshape(-1),
                                    onp.arange(12))

    def test_unique(self):
        out = nd.unique(nd.array(onp.asarray([3, 1, 2, 3, 1], "float32")))
        onp.testing.assert_allclose(out.asnumpy(), [1, 2, 3])

    def test_make_loss_identity_with_unit_grad(self):
        x = nd.array(onp.asarray([1.5, -2.0], "float32"))
        x.attach_grad()
        with mx.autograd.record():
            out = nd.make_loss(x)
        out.backward()
        onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())
        onp.testing.assert_allclose(x.grad.asnumpy(), [1.0, 1.0])

    def test_sample_negative_binomial_family(self):
        mx.random.seed(0)
        k = nd.array(onp.asarray([5.0, 20.0], "float32"))
        p = nd.array(onp.asarray([0.5, 0.5], "float32"))
        out = nd.sample_negative_binomial(k, p, shape=(500,))
        assert out.shape == (2, 500)
        m = out.asnumpy().mean(axis=1)
        # E[NB(k, p)] = k (1-p)/p
        onp.testing.assert_allclose(m, [5.0, 20.0], rtol=0.25)
        mu = nd.array(onp.asarray([4.0], "float32"))
        alpha = nd.array(onp.asarray([0.25], "float32"))
        out2 = nd.sample_generalized_negative_binomial(mu, alpha,
                                                       shape=(500,))
        onp.testing.assert_allclose(out2.asnumpy().mean(), 4.0, rtol=0.25)

    def test_multinomial_and_interp_aliases(self):
        mx.random.seed(0)
        probs = nd.array(onp.asarray([[0.0, 1.0, 0.0]], "float32"))
        draws = nd.multinomial(probs, shape=(8,))
        assert (draws.asnumpy() == 1).all()
        y = nd.interp(nd.array(onp.asarray([0.5], "float32")),
                      nd.array(onp.asarray([0.0, 1.0], "float32")),
                      nd.array(onp.asarray([0.0, 2.0], "float32")))
        onp.testing.assert_allclose(y.asnumpy(), [1.0])
