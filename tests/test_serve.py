"""Continuous-batching decode server (mxnet_tpu/serve/).

Parity: a served request must reproduce ``kv_generate(model,
prompt[None], ...)`` token-for-token — greedy AND sampled (the per-slot
sampler folds the request key at the absolute position, the exact
batch-1 stream), across mid-scan admissions, slot reuse and pool
growth.  Scheduler edge cases: EOS / max-length retirement on device,
pool-full backpressure, empty-queue idle (no dispatch), and the
dispatch-count regression — ONE step-executable dispatch per decode
step at steady state (ISSUE 7 acceptance).
"""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _gpt(layers=2, units=32, heads=4, hidden=64, vocab=97,
         max_length=64):
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=vocab, max_length=max_length,
                        num_layers=layers, units=units, num_heads=heads,
                        hidden_size=hidden))
    net.initialize(mx.init.Normal(0.02))
    return net


def _prompt(seed, n, vocab=97):
    return onp.random.RandomState(seed).randint(0, vocab, (n,))


def _drain(server):
    while server.pump():
        pass


def _ref(net, prompt, n, **kw):
    from mxnet_tpu.models import kv_generate
    kw.setdefault("temperature", 0.0)
    return list(kv_generate(net, prompt[None], max_new_tokens=n,
                            **kw)[0, prompt.size:])


@pytest.fixture(scope="module")
def net():
    return _gpt()


@pytest.fixture(scope="module")
def server(net):
    """Shared greedy 2-slot pool, pump-driven (compiles once for the
    whole module); every test drains it back to idle.  spec=False: this
    module pins the PLAIN one-dispatch-per-step accounting (speculative
    draft-and-verify has its own suite, test_serve_spec.py)."""
    from mxnet_tpu.serve import DecodeServer
    srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                       spec=False, autostart=False)
    yield srv
    srv.close(drain=False)


class TestServeParity:
    def test_two_ragged_requests_match_kv_generate(self, net, server):
        p1, p2 = _prompt(0, 5), _prompt(1, 3)
        s1 = server.submit(p1, max_new_tokens=8)
        s2 = server.submit(p2, max_new_tokens=4)
        _drain(server)
        assert s1.tokens(5) == _ref(net, p1, 8)
        assert s2.tokens(5) == _ref(net, p2, 4)

    def test_mid_scan_admission(self, net, server):
        """A request submitted while another is mid-decode joins at a
        step boundary; both streams stay exact."""
        p1, p2 = _prompt(2, 4), _prompt(3, 6)
        s1 = server.submit(p1, max_new_tokens=10)
        for _ in range(4):          # run a few steps of s1 alone
            server.pump()
        assert not s1.done
        s2 = server.submit(p2, max_new_tokens=5)
        _drain(server)
        assert s1.tokens(5) == _ref(net, p1, 10)
        assert s2.tokens(5) == _ref(net, p2, 5)

    def test_slot_reuse_after_retirement(self, net, server):
        """More requests than slots: retired slots re-admit from the
        queue and the recycled cache columns never leak into the new
        sequence."""
        prompts = [_prompt(10 + i, 3 + i % 3) for i in range(5)]
        streams = [server.submit(p, max_new_tokens=4 + i % 2)
                   for i, p in enumerate(prompts)]
        _drain(server)
        for i, (p, s) in enumerate(zip(prompts, streams)):
            assert s.tokens(5) == _ref(net, p, 4 + i % 2), f"req {i}"

    def test_sampled_stream_matches_batch1_seed(self, net):
        """temperature/top_k sampling: slot i draws with
        fold_in(PRNGKey(seed_i), pos) — the same stream kv_generate
        emits for that seed at batch 1."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           temperature=0.8, top_k=5, autostart=False)
        p1, p2 = _prompt(4, 5), _prompt(5, 3)
        s1 = srv.submit(p1, max_new_tokens=6, seed=11)
        s2 = srv.submit(p2, max_new_tokens=6, seed=42)
        _drain(srv)
        kw = dict(temperature=0.8, top_k=5)
        assert s1.tokens(5) == _ref(net, p1, 6, seed=11, **kw)
        assert s2.tokens(5) == _ref(net, p2, 6, seed=42, **kw)
        srv.close()

    def test_int8_pool_serving(self, net):
        """The q8 weight stream serves through the same slot pool (the
        int8 stacked scan from this PR's satellite)."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           weights="int8", autostart=False)
        p = _prompt(6, 4)
        s = srv.submit(p, max_new_tokens=5)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 5, weights="int8")
        srv.close()


class TestRetirement:
    def test_eos_retires_early(self, net):
        from mxnet_tpu.serve import DecodeServer
        # pick the token the greedy stream actually emits as "EOS"
        p = _prompt(0, 5)
        full = _ref(net, p, 8)
        eos = full[1]
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           eos_id=eos, autostart=False)
        s = srv.submit(p, max_new_tokens=8)
        _drain(srv)
        toks = s.tokens(5)
        assert toks[-1] == eos
        assert len(toks) == full.index(eos) + 1
        assert srv.stats()["in_flight"] == 0
        srv.close()

    def test_max_length_retires(self, net, server):
        p = _prompt(7, 4)
        s = server.submit(p, max_new_tokens=6)
        _drain(server)
        assert len(s.tokens(5)) == 6

    def test_single_token_budget_retires_at_admission(self, net,
                                                      server):
        """max_new_tokens=1 finishes inside the admission executable and
        never occupies a step lane."""
        p = _prompt(8, 4)
        server.reset_counters()
        s = server.submit(p, max_new_tokens=1)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 1)
        assert server.counters["admit_dispatches"] == 1
        assert server.counters["step_dispatches"] == 0

    def test_request_longer_than_cache_rejected(self, server):
        with pytest.raises(MXNetError, match="exceeds"):
            server.submit(_prompt(9, 10), max_new_tokens=60)

    def test_oversized_seed_rejected_at_submit(self, net, server):
        """A seed outside int32 must be a caller error at submit() —
        not an OverflowError on the scheduler thread that fails every
        other client's stream (post-review regression)."""
        with pytest.raises(MXNetError, match="int32"):
            server.submit(_prompt(9, 4), max_new_tokens=2, seed=2 ** 31)
        p = _prompt(9, 4)                    # the server still serves
        s = server.submit(p, max_new_tokens=2, seed=2 ** 31 - 1)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 2, seed=2 ** 31 - 1)


class TestScheduler:
    def test_empty_queue_idle_no_dispatch(self, server):
        """An idle server must not burn dispatches: pump() on an empty
        queue reports no work and launches nothing."""
        _drain(server)
        server.reset_counters()
        for _ in range(3):
            assert server.pump() is False
        assert server.counters["step_dispatches"] == 0
        assert server.counters["admit_dispatches"] == 0

    def test_pool_full_backpressure(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           max_pending=2, autostart=False)
        p = _prompt(12, 4)
        streams = [srv.submit(p, max_new_tokens=4) for _ in range(2)]
        with pytest.raises(MXNetError, match="backpressure"):
            srv.submit(p, max_new_tokens=4, nowait=True)
        _drain(srv)
        for s in streams:
            assert len(s.tokens(5)) == 4
        # queue drained — submission admits again
        s = srv.submit(p, max_new_tokens=2, nowait=True)
        _drain(srv)
        assert len(s.tokens(5)) == 2
        srv.close()

    def test_pump_mode_blocking_submit_raises(self, net):
        """With autostart=False there is no scheduler thread to drain
        the queue, so a blocking submit() at max_pending would deadlock
        the pump-driving thread — it must raise instead (post-review
        regression)."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           max_pending=2, autostart=False)
        p = _prompt(29, 4)
        streams = [srv.submit(p, max_new_tokens=3) for _ in range(2)]
        with pytest.raises(MXNetError, match="pump"):
            srv.submit(p, max_new_tokens=3)    # nowait=False
        _drain(srv)
        for s in streams:
            assert s.tokens(5) == _ref(net, p, 3)
        srv.close()

    def test_counters_are_per_instance(self, net, server):
        """Dispatch accounting must not cross-talk between servers in
        one process (the module-level serve_counters is only a
        process-wide aggregate; post-review regression)."""
        from mxnet_tpu.serve import DecodeServer
        _drain(server)
        server.reset_counters()
        other = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                             autostart=False)
        p = _prompt(31, 4)
        s = other.submit(p, max_new_tokens=3)
        _drain(other)
        assert s.tokens(5) == _ref(net, p, 3)
        assert other.counters["admit_dispatches"] == 1
        assert server.counters["admit_dispatches"] == 0
        assert server.counters["step_dispatches"] == 0
        other.close()

    def test_bad_on_token_callback_fails_only_its_stream(self, net,
                                                         server):
        """A raising per-request on_token callback fails THAT stream
        with the callback's error; the scheduler and every concurrent
        request keep serving (post-review regression)."""
        _drain(server)

        def bad(req_id, tok):
            raise RuntimeError("callback boom")

        p1, p2 = _prompt(32, 4), _prompt(33, 3)
        s1 = server.submit(p1, max_new_tokens=4, on_token=bad)
        s2 = server.submit(p2, max_new_tokens=4)
        _drain(server)
        with pytest.raises(RuntimeError, match="callback boom"):
            s1.tokens(5)
        assert s2.tokens(5) == _ref(net, p2, 4)
        p3 = _prompt(34, 3)                 # the server survives
        s3 = server.submit(p3, max_new_tokens=2)
        _drain(server)
        assert s3.tokens(5) == _ref(net, p3, 2)

    def test_close_timeout_leaves_scheduler_state_alone(self, net):
        """close() must not tear down scheduler-owned state while the
        scheduler thread is still inside pump() (a long dispatch or
        growth retrace): it raises after the join timeout, and a later
        close() finishes teardown (post-review regression)."""
        import threading
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,))
        entered, release = threading.Event(), threading.Event()
        real_pump = srv.pump

        def slow_pump():
            entered.set()
            release.wait(30)
            return real_pump()

        srv.pump = slow_pump
        assert entered.wait(5)
        s = srv.submit(_prompt(41, 3), max_new_tokens=6)
        with pytest.raises(MXNetError, match="timed out"):
            srv.close(drain=False, timeout=0.3)
        release.set()
        # the scheduler exits at its next _stopping check with the
        # request still outstanding; the advertised recovery — "call
        # close() again" — must DETECT the dead thread and self-pump
        # the drain instead of sleeping out the full timeout
        srv.close(drain=True, timeout=10.0)
        assert not srv._thread.is_alive()
        assert s.tokens(1) == _ref(net, _prompt(41, 3), 6)

    def test_close_drain_serves_request_mid_admission(self, net):
        """A request popped from the queue but still inside its
        admission dispatch must stay visible to close(drain=True): it
        finishes instead of failing with 'server closed' (post-review
        regression — pop + slot-record are atomic)."""
        import threading
        import time as _time
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,))
        real = srv._dispatch_admit
        started = threading.Event()

        def slow_admit(wave):
            started.set()
            _time.sleep(0.5)
            return real(wave)

        srv._dispatch_admit = slow_admit
        p = _prompt(35, 4)
        s = srv.submit(p, max_new_tokens=3)
        assert started.wait(10)
        srv.close(drain=True)
        assert s.tokens(5) == _ref(net, p, 3)

    def test_pool_grows_to_pinned_size(self, net):
        """Backlog beyond the current slot count grows the pool to the
        next pinned size at a step boundary; in-flight sequences carry
        their cache/position state across the growth."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2, 4),
                           autostart=False)
        p0 = _prompt(13, 4)
        s0 = srv.submit(p0, max_new_tokens=8)
        srv.pump()                       # admit s0, step once
        prompts = [_prompt(14 + i, 3) for i in range(3)]
        streams = [srv.submit(p, max_new_tokens=4) for p in prompts]
        _drain(srv)
        assert srv.counters["pool_grows"] == 1
        assert srv.stats()["num_slots"] == 4
        assert s0.tokens(5) == _ref(net, p0, 8)
        for p, s in zip(prompts, streams):
            assert s.tokens(5) == _ref(net, p, 4)
        srv.close()

    def test_background_thread_and_close_drain(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,))
        p = _prompt(20, 4)
        s = srv.submit(p, max_new_tokens=6)
        assert s.tokens(30) == _ref(net, p, 6)
        srv.close()
        with pytest.raises(MXNetError, match="closed"):
            srv.submit(p, max_new_tokens=2)


class TestDispatchCount:
    def test_one_executable_dispatch_per_decode_step(self, net, server):
        """THE acceptance regression: at steady state (full pool, no
        admissions) every decode step is exactly ONE executable
        dispatch.  N-token requests cost 1 admit + (N-1) decode steps;
        the only extra dispatch is the single trailing step in flight
        when the retirement flags reach the host."""
        _drain(server)
        N = 9
        p1, p2 = _prompt(21, 4), _prompt(22, 4)
        server.reset_counters()
        s1 = server.submit(p1, max_new_tokens=N)
        s2 = server.submit(p2, max_new_tokens=N)
        _drain(server)
        assert s1.tokens(5) == _ref(net, p1, N)
        assert s2.tokens(5) == _ref(net, p2, N)
        # both requests were pending at one step boundary: ONE batched
        # admission dispatch admits the whole wave
        assert server.counters["admit_dispatches"] == 1
        assert server.counters["step_dispatches"] == (N - 1) + 1
        # the step executable itself never retraced
        assert server._progs.step_fn()._cache_size() == 1

    def test_step_program_reused_across_waves(self, net, server):
        """A second wave of requests reuses the SAME compiled step and
        admission executables — slot admit/retire is a device-side
        masked update, not a recompile."""
        _drain(server)
        step = server._progs.step_fn()
        before = step._cache_size()
        admits = {b: f._cache_size()
                  for b, f in server._progs._admits.items()}
        p = _prompt(23, 4)
        s = server.submit(p, max_new_tokens=5)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 5)
        assert server._progs.step_fn() is step
        assert step._cache_size() == before
        for b, f in server._progs._admits.items():
            if b in admits:
                assert f._cache_size() == admits[b]


class TestCommittedState:
    def test_admit_and_step_compile_exactly_once(self, net):
        """Committed-placement regression: jit keys its executable
        cache on each argument's committed device, so the FIRST
        admission (running on the freshly initialized pool state) and
        every steady-state admission (running on jit-output state)
        must hit the SAME compiled signature.  Before
        ``pool_state_init`` committed the state with ``device_put``,
        the second admission silently recompiled (~seconds) INSIDE the
        serving loop — this pins one compile per program, ever."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        for wave in range(3):
            p = _prompt(40 + wave, 4)
            s = srv.submit(p, max_new_tokens=4)
            _drain(srv)
            assert s.tokens(5) == _ref(net, p, 4)
        assert srv._progs.step_fn()._cache_size() == 1
        assert srv._progs._admits, "no admission program compiled"
        for bucket, fn in srv._progs._admits.items():
            assert fn._cache_size() == 1, f"bucket {bucket} retraced"
        srv.close()


class TestBatchedAdmission:
    """ISSUE 8 tentpole: one bucketed ``(A, P)`` dispatch admits a
    whole wave of pending prompts.  ``admit_sizes=(1,)`` reproduces
    the per-request admission path (every wave capped at one row), so
    batched-vs-sequential parity is a ladder choice, not a second code
    path."""

    def test_wave_of_4_costs_one_admit_dispatch(self, net):
        """THE acceptance regression: k >= 4 pending prompts at one
        step boundary cost exactly 1 admit dispatch, not k."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                           autostart=False)
        prompts = [_prompt(50 + i, 3 + i) for i in range(4)]
        streams = [srv.submit(p, max_new_tokens=5) for p in prompts]
        _drain(srv)
        assert srv.counters["admit_dispatches"] == 1
        for p, s in zip(prompts, streams):
            assert s.tokens(5) == _ref(net, p, 5)
        srv.close()

    def test_batched_matches_sequential_greedy(self, net):
        """Mixed prompt lengths ACROSS prefill buckets in one wave:
        the batched streams are token-identical to the per-request
        ladder (and to kv_generate)."""
        from mxnet_tpu.serve import DecodeServer
        prompts = [_prompt(55, 3), _prompt(56, 10), _prompt(57, 5),
                   _prompt(58, 18)]           # buckets 8, 16 and 32
        budgets = [6, 4, 5, 3]
        outs = {}
        for name, ladder in (("batched", None), ("sequential", (1,))):
            srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                               admit_sizes=ladder, autostart=False)
            streams = [srv.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts, budgets)]
            _drain(srv)
            outs[name] = [s.tokens(5) for s in streams]
            expect = 1 if name == "batched" else len(prompts)
            assert srv.counters["admit_dispatches"] == expect, name
            srv.close()
        assert outs["batched"] == outs["sequential"]
        for p, n, got in zip(prompts, budgets, outs["batched"]):
            assert got == _ref(net, p, n)

    def test_batched_matches_sequential_sampled(self, net):
        """Sampled decoding: every wave row folds ITS request key at
        its own position — the batched wave reproduces the per-request
        (and offline batch-1) streams exactly."""
        from mxnet_tpu.serve import DecodeServer
        prompts = [_prompt(60 + i, 3 + 2 * i) for i in range(3)]
        outs = {}
        for name, ladder in (("batched", None), ("sequential", (1,))):
            srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                               temperature=0.7, top_k=7,
                               admit_sizes=ladder, autostart=False)
            streams = [srv.submit(p, max_new_tokens=5, seed=90 + i)
                       for i, p in enumerate(prompts)]
            _drain(srv)
            outs[name] = [s.tokens(5) for s in streams]
            srv.close()
        assert outs["batched"] == outs["sequential"]
        kw = dict(temperature=0.7, top_k=7)
        for i, (p, got) in enumerate(zip(prompts, outs["batched"])):
            assert got == _ref(net, p, 5, seed=90 + i, **kw)

    def test_wave_of_one(self, net, server):
        """A single pending request admits through the same batched
        program path (smallest A bucket; idle rows are masked)."""
        _drain(server)
        server.reset_counters()
        p = _prompt(65, 4)
        s = server.submit(p, max_new_tokens=4)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 4)
        assert server.counters["admit_dispatches"] == 1

    def test_wave_larger_than_free_slots(self, net):
        """5 pending, 2 slots: the first wave admits 2, the rest
        re-admit in waves as slots retire — parity holds and the
        dispatch count is the wave count, not the request count."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        prompts = [_prompt(70 + i, 3 + i % 3) for i in range(5)]
        streams = [srv.submit(p, max_new_tokens=4) for p in prompts]
        _drain(srv)
        for p, s in zip(prompts, streams):
            assert s.tokens(5) == _ref(net, p, 4)
        # 5 equal-budget requests through a 2-slot pool retire in
        # lockstep: ceil(5/2) = 3 waves
        assert srv.counters["admit_dispatches"] == 3
        srv.close()

    def test_wave_spills_past_largest_admit_bucket(self, net):
        """A backlog larger than the biggest pinned A bucket spills to
        a second dispatch in the SAME pump."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                           admit_sizes=(2,), autostart=False)
        prompts = [_prompt(80 + i, 4) for i in range(4)]
        streams = [srv.submit(p, max_new_tokens=3) for p in prompts]
        srv.pump()
        assert srv.counters["admit_dispatches"] == 2
        _drain(srv)
        for p, s in zip(prompts, streams):
            assert s.tokens(5) == _ref(net, p, 3)
        srv.close()

    def test_compile_count_bounded_by_ladder_product(self, net):
        """Executable count stays <= len(admit_sizes) *
        len(prefill_buckets) whatever the traffic mix, and no program
        ever retraces."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                           autostart=False)
        for wave in ([3], [1, 9], [17, 2, 4], [30], [1, 1, 1, 1]):
            streams = [srv.submit(_prompt(100 + n, n),
                                  max_new_tokens=2) for n in wave]
            _drain(srv)
            for s in streams:
                assert len(s.tokens(5)) == 2
        bound = len(srv.admit_sizes) * len(srv.prefill_buckets)
        assert len(srv._progs._admits) <= bound
        for fn in srv._progs._admits.values():
            assert fn._cache_size() == 1
        srv.close()

    def test_prompt_longer_than_largest_bucket_chunks_in(self, net):
        """Satellite (ISSUE 16): a prompt past the largest pinned
        prefill bucket is NOT rejected any more — chunked prefill
        streams it in over several dispatches, token-exact."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           prefill_buckets=(8,), prefix_cache=False,
                           autostart=False)
        p = _prompt(85, 12)              # 12 > bucket 8: two chunks
        s = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 4)
        assert srv.counters["chunk_dispatches"] == 2
        assert srv.counters["admit_dispatches"] == 0
        p2 = _prompt(86, 6)              # short prompts still admit
        s2 = srv.submit(p2, max_new_tokens=3)
        _drain(srv)
        assert s2.tokens(5) == _ref(net, p2, 3)
        assert srv.counters["admit_dispatches"] == 1
        srv.close()

    def test_prompt_longer_than_cache_names_limit(self, server):
        """The only hard length limit left is the pool cache length."""
        with pytest.raises(MXNetError, match="pool cache length"):
            server.submit(_prompt(87, 70), max_new_tokens=1)

    def test_ttft_recorded_separately(self, net, server):
        """Satellite: TokenStream.ttft = first-token arrival minus
        submit, kept separately from the per-token times list."""
        _drain(server)
        p = _prompt(88, 4)
        s = server.submit(p, max_new_tokens=3)
        assert s.ttft is None            # nothing arrived yet
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 3)
        assert s.ttft is not None and s.ttft > 0
        assert abs(s.ttft - (s.times[0] - s.submit_time)) < 1e-9
        assert len(s.times) == 3

    def test_env_ladders(self, net, monkeypatch):
        """MXNET_SERVE_ADMIT_SIZES / MXNET_SERVE_PREFILL_BUCKETS pin
        the ladders (prefill buckets clamp to the cache length);
        malformed values are a caller error at construction."""
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_ADMIT_SIZES", "1,3")
        monkeypatch.setenv("MXNET_SERVE_PREFILL_BUCKETS", "4,16,999")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(4,),
                           autostart=False)
        assert srv.admit_sizes == (1, 3)
        assert srv.prefill_buckets == (4, 16, 64)    # clamped to T
        p = _prompt(89, 6)
        s = srv.submit(p, max_new_tokens=3)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 3)
        assert (1, 16) in srv._progs._admits
        srv.close()
        monkeypatch.setenv("MXNET_SERVE_ADMIT_SIZES", "zero")
        with pytest.raises(MXNetError, match="ADMIT_SIZES"):
            DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                         autostart=False)


class TestPagedKV:
    """ISSUE 16 tentpole: the paged KV pool, COW shared-prefix caching
    and chunked prefill.  T=64 with the default 16-token pages gives 4
    pages per sequence; prompts of 32/33 tokens pin the two full-hit
    boundary cases (prompt ending ON a page boundary needs one COW
    copy; one past it shares every matched page outright)."""

    def test_full_prefix_hit_zero_prefill_dispatches(self, net):
        """THE acceptance pin: an identical prompt re-submitted after
        its producer retired admits with ZERO prefill dispatches (no
        admit, no chunk) and stays token-exact — including the eager
        COW copy of the boundary page the first step re-writes."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        p = _prompt(200, 32)             # exactly 2 full pages
        s1 = srv.submit(p, max_new_tokens=5)
        _drain(srv)
        assert s1.tokens(5) == _ref(net, p, 5)
        srv.reset_counters()
        s2 = srv.submit(p, max_new_tokens=5)
        _drain(srv)
        assert s2.tokens(5) == _ref(net, p, 5)
        assert srv.counters["prefix_hits"] == 1
        assert srv.counters["cow_copies"] == 1
        assert srv.counters["admit_dispatches"] == 0
        assert srv.counters["chunk_dispatches"] == 0
        srv.close()

    def test_prefix_hit_off_boundary_no_copy(self, net):
        """A prompt ending one past a page boundary shares every
        matched page read-only — no COW copy at all (the first owned
        page takes the recompute write)."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        p = _prompt(201, 33)             # 2 full pages + 1 token
        s1 = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s1.tokens(5) == _ref(net, p, 4)
        srv.reset_counters()
        s2 = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s2.tokens(5) == _ref(net, p, 4)
        assert srv.counters["prefix_hits"] == 1
        assert srv.counters["cow_copies"] == 0
        assert srv.counters["admit_dispatches"] == 0
        srv.close()

    def test_prefix_hit_sampled_parity(self, net):
        """A hit's first token comes from the step's recompute of the
        last prompt position with fold_in(key, L-1) — the batched
        admission's exact sampling key, so hit and miss streams match
        the offline batch-1 stream seed-for-seed."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           temperature=0.8, top_k=5, autostart=False)
        p = _prompt(202, 32)
        kw = dict(temperature=0.8, top_k=5)
        s1 = srv.submit(p, max_new_tokens=5, seed=7)
        _drain(srv)
        assert s1.tokens(5) == _ref(net, p, 5, seed=7, **kw)
        srv.reset_counters()
        s2 = srv.submit(p, max_new_tokens=5, seed=99)   # new key
        _drain(srv)
        assert s2.tokens(5) == _ref(net, p, 5, seed=99, **kw)
        assert srv.counters["prefix_hits"] == 1
        assert srv.counters["admit_dispatches"] == 0
        srv.close()

    def test_cow_fork_divergence(self, net):
        """Two prompts sharing a one-page prefix fork correctly after
        the first non-shared token: the second maps the shared page and
        streams only its divergent suffix (a partial hit), and neither
        stream perturbs the other."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        prefix = _prompt(210, 16)        # exactly one full page
        p1 = onp.concatenate([prefix, _prompt(211, 4)])
        p2 = onp.concatenate([prefix, _prompt(212, 4)])
        s1 = srv.submit(p1, max_new_tokens=5)
        _drain(srv)
        srv.reset_counters()
        s2 = srv.submit(p2, max_new_tokens=5)
        _drain(srv)
        assert s1.tokens(5) == _ref(net, p1, 5)
        assert s2.tokens(5) == _ref(net, p2, 5)
        assert srv.counters["prefix_hits"] == 1    # partial hit
        assert srv.counters["admit_dispatches"] == 0
        assert srv.counters["chunk_dispatches"] == 1   # 4-token suffix
        srv.close()

    def test_hit_first_token_costs_one_step(self, net):
        """Acceptance: prefix-hit TTFT is ONE decode step — the hit
        admission dispatches nothing, and the first pump's single step
        dispatch produces the first token."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        p = _prompt(203, 32)
        s1 = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        srv.reset_counters()
        s2 = srv.submit(p, max_new_tokens=4)
        srv.pump()                       # hit admission + 1 step
        assert srv.counters["admit_dispatches"] == 0
        assert srv.counters["chunk_dispatches"] == 0
        assert srv.counters["step_dispatches"] == 1
        srv.pump()                       # drains the step's readback
        assert len(s2.times) >= 1        # first token arrived
        _drain(srv)
        assert s2.tokens(5) == _ref(net, p, 4)
        srv.close()

    def test_refcounted_pages_freed_on_retire(self, net):
        """Retirement decrefs the slot's page row back to the free
        list; the resident pool's accountant-metered bytes never move
        (pages are recycled, not reallocated)."""
        from mxnet_tpu.serve import DecodeServer
        from mxnet_tpu.telemetry.memory import ACCOUNTANT
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           prefix_cache=False, autostart=False)
        label = srv.telemetry_label
        bytes0 = ACCOUNTANT.bytes(subsystem="serve.kv_pool", key=label)
        assert bytes0 == srv.stats()["pool_bytes"] > 0
        p = _prompt(204, 20)             # pages_for(20 + 4) = 2
        s = srv.submit(p, max_new_tokens=4)
        srv.pump()
        assert srv.stats()["pages_in_use"] == 2
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 4)
        assert srv.stats()["pages_in_use"] == 0      # refs released
        assert ACCOUNTANT.bytes(subsystem="serve.kv_pool",
                                key=label) == bytes0  # no delta
        srv.close()
        assert srv.stats()["pages_in_use"] == 0

    def test_prefix_cache_retains_only_full_pages(self, net):
        """With the cache ON, retirement keeps exactly the registered
        FULL prompt pages resident (index-owned) for future hits."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        p = _prompt(205, 20)             # one full page registered
        s = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 4)
        st = srv.stats()
        assert st["pages_in_use"] == 1 and st["prefix_nodes"] == 1
        srv.close()

    def test_env_prefix_cache_off(self, net, monkeypatch):
        """MXNET_SERVE_PREFIX_CACHE=0 disables the index: identical
        prompts re-prefill (no hits), parity unchanged."""
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_PREFIX_CACHE", "0")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        p = _prompt(206, 32)
        for _ in range(2):
            s = srv.submit(p, max_new_tokens=3)
            _drain(srv)
            assert s.tokens(5) == _ref(net, p, 3)
        assert srv.counters["prefix_hits"] == 0
        assert srv.counters["admit_dispatches"] == 2
        srv.close()

    def test_env_page_size(self, net, monkeypatch):
        """MXNET_SERVE_PAGE_SIZE pins the page granule; malformed
        values are a constructor error."""
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_PAGE_SIZE", "8")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        assert srv._progs.page == 8 and srv._progs.maxp == 8
        p = _prompt(207, 12)
        s = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 4)
        srv.close()
        monkeypatch.setenv("MXNET_SERVE_PAGE_SIZE", "none")
        with pytest.raises(MXNetError, match="PAGE_SIZE"):
            DecodeServer(net, max_total_len=64, autostart=False)

    def test_page_churn_never_retraces(self, net):
        """Steady-state discipline through the page-table operand:
        admit / hit / chunk / retire churn changes table VALUES only —
        the step executable compiles once, ever."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           prefill_buckets=(8, 16),
                           autostart=False)
        p_long = _prompt(208, 24)        # chunks (24 > bucket 16)
        p_short = _prompt(209, 6)
        for p, n in ((p_short, 4), (p_long, 4), (p_short, 3),
                     (p_long, 3)):
            s = srv.submit(p, max_new_tokens=n)
            _drain(srv)
            assert s.tokens(5) == _ref(net, p, n)
        assert srv.counters["prefix_hits"] >= 1
        assert srv.counters["chunk_dispatches"] >= 1
        assert srv._progs.step_fn()._cache_size() == 1
        for fn in srv._progs._admits.values():
            assert fn._cache_size() == 1
        for fn in srv._progs._chunks.values():
            assert fn._cache_size() == 1
        for fn in srv._progs._hits.values():
            assert fn._cache_size() == 1
        srv.close()


class TestKVQuantPages:
    """ISSUE 18: int8 page storage — representation-error pins (the
    PARITY.md tolerance), the env knob, and the one-executable
    steady-state discipline on a quantized pool."""

    def test_requant_roundtrip_bound_and_drift_free(self):
        """The PARITY.md representation pins: dequantized values sit
        within page_absmax/254 (half a code step) of the written
        values, and floor-scale requantization is drift-free — codes
        re-quantized at their own scale round-trip EXACTLY, so a
        frontier page's RMW never re-rounds already-written columns."""
        import jax.numpy as jnp

        from mxnet_tpu.models.decoding import _kv_dequant, _kv_requant

        rng = onp.random.RandomState(0)
        vals = jnp.asarray(rng.randn(2, 4, 16, 8).astype("float32"))
        codes, scales = _kv_requant(vals, 0.0)
        assert codes.dtype == jnp.int8 and scales.dtype == jnp.float32
        deq = _kv_dequant(codes, scales, jnp.float32)
        amax = onp.max(onp.abs(onp.asarray(vals)), axis=(-2, -1))
        err = onp.max(onp.abs(onp.asarray(deq - vals)), axis=(-2, -1))
        assert onp.all(err <= amax / 254.0 * (1 + 1e-5))
        # drift-free: requantizing the dequantized page at its own
        # floor scale reproduces codes and scales bit-for-bit
        codes2, scales2 = _kv_requant(deq, scales)
        assert onp.array_equal(onp.asarray(codes), onp.asarray(codes2))
        assert onp.array_equal(onp.asarray(scales),
                               onp.asarray(scales2))
        # scales only ratchet: a larger floor wins, a smaller one is
        # ignored
        _, s_up = _kv_requant(deq, scales * 2)
        assert onp.allclose(onp.asarray(s_up),
                            onp.asarray(scales) * 2)

    def test_kv_dtype_env_knob_and_validation(self, net, monkeypatch):
        """MXNET_SERVE_KV_DTYPE selects the pool storage dtype; the
        explicit constructor argument wins; malformed values are a
        constructor error naming the variable."""
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_KV_DTYPE", "int8")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           autostart=False)
        assert srv.kv_dtype == "int8"
        assert srv.stats()["kv_dtype"] == "int8"
        pb_i8 = srv.stats()["page_bytes"]
        p = _prompt(220, 6)
        s = srv.submit(p, max_new_tokens=8)
        _drain(srv)
        ref = _ref(net, p, 8)
        agree = sum(int(a == b)
                    for a, b in zip(s.tokens(5), ref)) / len(ref)
        assert agree >= 0.9, (s.tokens(5), ref)
        srv.close()
        # explicit argument beats the env
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           kv_dtype="f32", autostart=False)
        assert srv.kv_dtype == "native"
        assert srv.stats()["page_bytes"] > 2 * pb_i8
        srv.close()
        monkeypatch.setenv("MXNET_SERVE_KV_DTYPE", "int4")
        with pytest.raises(MXNetError, match="KV_DTYPE"):
            DecodeServer(net, max_total_len=64, autostart=False)

    def test_int8_churn_never_retraces(self, net):
        """The tentpole's compile discipline on the QUANTIZED pool:
        admit / hit / chunk / retire churn against int8 pages keeps
        every executable at one signature — quantization lives inside
        the same programs, not beside them."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           prefill_buckets=(8, 16), kv_dtype="int8",
                           autostart=False)
        p_long = _prompt(221, 24)        # chunks (24 > bucket 16)
        p_short = _prompt(222, 6)
        for p, n in ((p_short, 4), (p_long, 4), (p_short, 3),
                     (p_long, 3)):
            s = srv.submit(p, max_new_tokens=n)
            _drain(srv)
            got, ref = s.tokens(5), _ref(net, p, n)
            agree = sum(int(a == b) for a, b in zip(got, ref)) / n
            assert agree >= 0.9, (got, ref)
        assert srv.counters["prefix_hits"] >= 1
        assert srv.counters["chunk_dispatches"] >= 1
        assert srv._progs.step_fn()._cache_size() == 1
        for fns in (srv._progs._admits, srv._progs._chunks,
                    srv._progs._hits):
            for fn in fns.values():
                assert fn._cache_size() == 1
        srv.close()

    # recycled-page scale reset (post-review regression): the pool
    # free list is host-only bookkeeping, so a reallocated page still
    # holds its previous tenant's codes AND per-page scale on device.
    # The requantizing RMWs floor each write at the page's resident
    # scale (monotone ratchet), so WITHOUT a reset the first touch of
    # a recycled page pins its scale to the OLD tenant's dynamic range
    # — breaking the PARITY.md absmax/254 bound exactly under churn.
    # Each admission path (admit / prefix-hit / chunk) must zero the
    # scales of every freshly allocated page inside its own dispatch.

    @staticmethod
    def _scales(srv):
        (_, ks), (_, vs) = srv._state[0], srv._state[1]
        return onp.asarray(ks), onp.asarray(vs)

    def test_recycled_pages_reset_on_admit(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           prefill_buckets=(8, 16), kv_dtype="int8",
                           spec=False, autostart=False)
        # tenant A dirties pages with real (nonzero) scales, then
        # retires — its pages return to the free list un-zeroed
        pa = _prompt(230, 14)
        sa = srv.submit(pa, max_new_tokens=18)     # 2 pages, both hit
        _drain(srv)
        assert sa.tokens(5) is not None
        ks0, _ = self._scales(srv)
        dirty = {p for p in range(4) if onp.any(ks0[:, p] != 0)}
        assert dirty and dirty <= set(srv._pages._free)
        # tenant B reserves the WHOLE pool: prompt page + 3 decode-
        # frontier pages, at least one of which A dirtied
        pb = _prompt(231, 6)
        sb = srv.submit(pb, max_new_tokens=58)
        assert srv.pump()
        row = srv._slot_pages[0]
        assert len(row) == 4
        assert set(row[1:]) & dirty, (row, dirty)  # churn precondition
        ks, vs = self._scales(srv)
        # admit wrote the prompt page; the reserved-but-unwritten
        # frontier pages must carry ZERO scales (reset happened) so
        # their first RMW floors at 0, not at A's range
        assert onp.all(ks[:, row[1:]] == 0), ks[:, row[1:]]
        assert onp.all(vs[:, row[1:]] == 0), vs[:, row[1:]]
        assert onp.all(ks[:, row[0]] > 0)          # prompt page landed
        _drain(srv)
        ref = _ref(net, pb, 58)
        got = sb.tokens(5)
        agree = sum(int(a == b) for a, b in zip(got, ref)) / len(ref)
        assert agree >= 0.9, (got, ref)
        srv.close()

    def test_recycled_pages_reset_on_prefix_hit(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           prefill_buckets=(8, 16), kv_dtype="int8",
                           spec=False, autostart=False)
        p = _prompt(232, 16)                       # one full page
        sa = srv.submit(p, max_new_tokens=32)      # 3 pages dirtied
        _drain(srv)
        assert sa.tokens(5) is not None
        ks0, _ = self._scales(srv)
        dirty = {p for p in range(4) if onp.any(ks0[:, p] != 0)}
        # resubmit: full prefix hit with one COW copy (prompt ends on
        # the shared page boundary); the fresh pages are recycled
        sb = srv.submit(p, max_new_tokens=16)
        assert srv.pump()
        assert srv.counters["prefix_hits"] >= 1
        assert srv.counters["cow_copies"] >= 1
        row = srv._slot_pages[0]
        assert len(row) == 2
        assert set(row[1:]) & dirty, (row, dirty)  # churn precondition
        ks, vs = self._scales(srv)
        # row[0] is the COW dst: zeroed, then the copied scale landed
        assert onp.all(ks[:, row[0]] > 0)
        # row[1] is a recycled decode-frontier page: must be reset
        assert onp.all(ks[:, row[1]] == 0), ks[:, row[1]]
        assert onp.all(vs[:, row[1]] == 0), vs[:, row[1]]
        _drain(srv)
        ref = _ref(net, p, 16)
        got = sb.tokens(5)
        agree = sum(int(a == b) for a, b in zip(got, ref)) / len(ref)
        assert agree >= 0.9, (got, ref)
        srv.close()

    def test_recycled_pages_reset_on_chunked_prefill(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           prefill_buckets=(8, 16), kv_dtype="int8",
                           spec=False, autostart=False)
        pa = _prompt(233, 30)                      # > bucket: chunks
        srv.submit(pa, max_new_tokens=18)          # 3 pages dirtied
        _drain(srv)
        ks0, _ = self._scales(srv)
        dirty = {p for p in range(4) if onp.any(ks0[:, p] != 0)}
        pb = _prompt(234, 24)
        pb[0] = (pa[0] + 1) % 97                   # no prefix match
        sb = srv.submit(pb, max_new_tokens=40)     # needs all 4 pages
        assert srv.pump()                          # FIRST chunk only
        row = srv._slot_pages[0]
        assert len(row) == 4
        # chunk 1 (16 tokens) writes window pages row[0:2]; the pages
        # beyond it were only scale-reset by the dispatch's zrow
        assert set(row[2:]) & dirty, (row, dirty)  # churn precondition
        ks, vs = self._scales(srv)
        assert onp.all(ks[:, row[2:]] == 0), ks[:, row[2:]]
        assert onp.all(vs[:, row[2:]] == 0), vs[:, row[2:]]
        assert onp.all(ks[:, row[0]] > 0)          # chunk 1 landed
        _drain(srv)
        assert srv.counters["chunk_dispatches"] >= 2
        ref = _ref(net, pb, 40)
        got = sb.tokens(5)
        agree = sum(int(a == b) for a, b in zip(got, ref)) / len(ref)
        assert agree >= 0.9, (got, ref)
        srv.close()


class TestSyncFallback:
    def test_env_hatch_serves_synchronously(self, net, monkeypatch):
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_SYNC", "1")
        srv = DecodeServer(net, max_total_len=64, autostart=False)
        assert srv.sync_mode and "MXNET_SERVE_SYNC" in srv.sync_reason
        p = _prompt(24, 5)
        s = srv.submit(p, max_new_tokens=6)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 6)
        assert srv.counters["sync_requests"] == 1
        assert srv.counters["step_dispatches"] == 0
        srv.close()

    def test_unstackable_model_falls_back(self, monkeypatch):
        """A model the slot-pool gate rejects (non-uniform layer stack)
        still serves — through the kv_generate fallback, with the
        reason recorded."""
        from mxnet_tpu.serve import DecodeServer
        net = _gpt()
        net.blocks[1].ln1._eps = 1e-3
        srv = DecodeServer(net, max_total_len=64, autostart=False)
        assert srv.sync_mode
        assert "stacked" in srv.sync_reason
        p = _prompt(25, 4)
        s = srv.submit(p, max_new_tokens=4)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 4)
        srv.close()


class TestTokenStream:
    def test_streaming_iteration_and_detok(self, net, server):
        seen = []
        p = _prompt(26, 4)
        s = server.submit(p, max_new_tokens=4,
                          on_token=lambda rid, t: seen.append(t))
        _drain(server)
        assert list(s) == _ref(net, p, 4)      # iterator replay
        assert seen == _ref(net, p, 4)

    def test_finished_stream_reiterates(self, net, server):
        """Iterating a TokenStream is replayable: a second pass (or a
        second consumer) sees the full stream again instead of hanging
        on a consumed end-sentinel (post-review regression)."""
        import threading
        p = _prompt(28, 4)
        s = server.submit(p, max_new_tokens=4)
        _drain(server)
        ref = _ref(net, p, 4)
        assert list(s) == ref
        assert list(s) == ref                  # second pass replays
        got = []
        th = threading.Thread(target=lambda: got.append(list(s)))
        th.start()
        th.join(5.0)
        assert not th.is_alive() and got == [ref]

    def test_text_iter_detokenizes(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           detokenize=lambda t: f"<{t}>",
                           autostart=False)
        p = _prompt(27, 4)
        s = srv.submit(p, max_new_tokens=3)
        _drain(srv)
        ref = _ref(net, p, 3)
        assert s.text(5) == "".join(f"<{t}>" for t in ref)
        srv.close()


class TestServeBenchSmoke:
    def test_ragged_lengths_single_slot_pool(self):
        """A 1-slot pool (the default MXNET_SERVE_POOL_SIZES starts at
        1) has no short lanes — ragged_lengths must degenerate to
        all-full-length instead of dividing by S - 1 = 0."""
        from benchmark.serve_bench import ragged_lengths
        assert ragged_lengths(1, 8, 0.25, 5) == [8] * 5
        lens = ragged_lengths(4, 8, 0.25, 8)
        assert len(lens) == 8 and max(lens) == 8 and min(lens) >= 1

    def test_serve_bench_smoke(self, tmp_path):
        """benchmark/serve_bench.py --smoke: saturated slot-pool serving
        on a tiny geometry — parity with kv_generate, dispatch
        accounting and a throughput floor asserted inside, plus the
        ragged-arrival continuous-vs-static rows printed (the tier-1
        gate; the 0.8x/ragged-win acceptance bars are asserted by the
        compute-bound --cpu-full profile, recorded in BASELINE.md).

        The run records its telemetry stream to a JSONL
        (``MXNET_TELEMETRY_JSONL``), and ``tools/telemetry_report.py
        --check-serve`` must then reproduce the pinned serving
        invariants — ladder-bounded compile count, zero steady-state
        retraces, one step dispatch per decode step — from the
        recorded file ALONE (ISSUE 9 acceptance)."""
        jsonl = str(tmp_path / "serve_telemetry.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MXNET_TELEMETRY_JSONL=jsonl)
        r = subprocess.run(
            [sys.executable, "benchmark/serve_bench.py", "--smoke"],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=570)
        assert r.returncode == 0, r.stderr[-2000:]
        assert '"bench": "serve_smoke"' in r.stdout
        assert "serve OK" in r.stdout
        assert "telemetry OK" in r.stdout

        assert os.path.exists(jsonl), "JSONL sink never attached"
        rep = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", jsonl,
             "--check-serve"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=120)
        assert rep.returncode == 0, \
            rep.stdout[-2000:] + rep.stderr[-2000:]
        assert "serve checks OK" in rep.stdout
        assert "compile events" in rep.stdout
        assert "serve requests" in rep.stdout
        assert "bench rows" in rep.stdout
