"""Autograd tape tests (mirrors reference
``tests/python/unittest/test_autograd.py``)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_record_flags():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert autograd.is_recording()
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([2.0, 4.0]))
    assert_almost_equal(x.grad, [6.0, 12.0])


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = x * 2
        y.backward()
    assert_almost_equal(x.grad, [4.0, 4.0])


def test_grad_req_write_overwrites():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward()
    with autograd.record():
        y = x * 5
    y.backward()
    assert_almost_equal(x.grad, [5.0])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    w = nd.array([2.0])
    w.attach_grad()
    with autograd.record():
        y = x * w
    y.backward()
    assert_almost_equal(w.grad, [1.0])
    assert_almost_equal(x.grad, [0.0])


def test_multi_path_accumulation():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x * 3  # dy/dx = 2x + 3 = 7
    y.backward()
    assert_almost_equal(x.grad, [7.0])


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x  # z = const(4) * x
    z.backward()
    assert_almost_equal(x.grad, [4.0])


def test_autograd_grad_api():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    (gx,) = autograd.grad(y, [x])
    assert_almost_equal(gx, [6.0])
    # .grad untouched
    assert_almost_equal(x.grad, [0.0])


def test_grad_wrt_intermediate():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * y  # z = x^4, dz/dy = 2y = 8
    (gy,) = autograd.grad(z, [y])
    assert_almost_equal(gy, [8.0])


def test_backward_twice_raises_without_retain():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    y.backward()
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad(grad_req="add")
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert_almost_equal(x.grad, [8.0])


def test_training_flag_dropout():
    x = nd.ones((100,))
    with autograd.record(train_mode=True):
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()


def test_mark_variables():
    x = nd.array([1.0, 2.0])
    g = nd.zeros((2,))
    autograd.mark_variables(x, g)
    with autograd.record():
        y = (x * 4).sum()
    y.backward()
    assert_almost_equal(x.grad, [4.0, 4.0])


def test_custom_function():
    class MySigmoid(autograd.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = MySigmoid()
    x = nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + onp.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5, atol=1e-6)


def test_inplace_rebind_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1  # rebind; grad still flows through the mul
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad, [2.0, 2.0])


def test_setitem_inside_record_raises():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y[0] = 5.0


def test_multi_output_op_grad():
    x = nd.array(onp.arange(6, dtype="float32").reshape(2, 3))
    x.attach_grad()
    with autograd.record():
        parts = nd.split(x, num_outputs=3, axis=1)
        z = (parts[0] * 1 + parts[2] * 3).sum()
    z.backward()
    assert_almost_equal(x.grad, [[1, 0, 3], [1, 0, 3]])
