"""Sparse kernels (csr/row_sparse): goldens vs scipy + gradients.

Reference test model (SURVEY.md §4-of-reference test strategy): op-level
golden tests vs NumPy + gradient checks on the registered kernels."""
import numpy as onp
import pytest
import scipy.sparse as sp

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray import sparse


def _rand_csr(m, n, density=0.3, seed=0):
    rng = onp.random.RandomState(seed)
    mat = sp.random(m, n, density=density, random_state=rng,
                    format="csr", dtype=onp.float32)
    return mat


class TestCSR:
    def test_construct_lazy(self):
        mat = _rand_csr(8, 6)
        a = sparse.csr_matrix((mat.data, mat.indices, mat.indptr),
                              shape=mat.shape)
        # construction must NOT materialize the dense mirror
        assert a._dense_cache is None
        assert a.stype == "csr"
        assert a.shape == (8, 6)
        onp.testing.assert_allclose(a.asnumpy(), mat.toarray(), rtol=1e-6)

    def test_dot_golden(self):
        mat = _rand_csr(16, 12)
        rhs = onp.random.RandomState(1).randn(12, 5).astype(onp.float32)
        a = sparse.csr_matrix((mat.data, mat.indices, mat.indptr),
                              shape=mat.shape)
        out = sparse.dot(a, nd.array(rhs))
        onp.testing.assert_allclose(out.asnumpy(), mat @ rhs, rtol=1e-5)

    def test_dot_transpose_golden(self):
        mat = _rand_csr(16, 12, seed=2)
        rhs = onp.random.RandomState(3).randn(16, 7).astype(onp.float32)
        a = sparse.csr_matrix((mat.data, mat.indices, mat.indptr),
                              shape=mat.shape)
        out = sparse.dot(a, nd.array(rhs), transpose_a=True)
        onp.testing.assert_allclose(out.asnumpy(), mat.T @ rhs, rtol=1e-5,
                                    atol=1e-6)

    def test_dot_grad_wrt_dense(self):
        mat = _rand_csr(10, 8, seed=4)
        a = sparse.csr_matrix((mat.data, mat.indices, mat.indptr),
                              shape=mat.shape)
        rhs = nd.array(onp.random.RandomState(5).randn(8, 4)
                       .astype(onp.float32))
        rhs.attach_grad()
        with autograd.record():
            out = sparse.dot(a, rhs)
            loss = out.sum()
        loss.backward()
        # d/d(rhs) of sum(csr @ rhs) = csr^T @ ones
        expect = mat.T @ onp.ones((10, 4), onp.float32)
        onp.testing.assert_allclose(rhs.grad.asnumpy(), expect, rtol=1e-5,
                                    atol=1e-6)

    def test_elemwise_union(self):
        a_s = _rand_csr(6, 6, seed=6)
        b_s = _rand_csr(6, 6, seed=7)
        a = sparse.csr_matrix((a_s.data, a_s.indices, a_s.indptr),
                              shape=a_s.shape)
        b = sparse.csr_matrix((b_s.data, b_s.indices, b_s.indptr),
                              shape=b_s.shape)
        out = sparse.add(a, b)
        assert out.stype == "csr"
        onp.testing.assert_allclose(out.asnumpy(),
                                    (a_s + b_s).toarray(), rtol=1e-6)
        out = sparse.multiply(a, b)
        assert out.stype == "csr"
        onp.testing.assert_allclose(out.asnumpy(),
                                    a_s.multiply(b_s).toarray(), rtol=1e-6)

    def test_bf16_refresh_and_elemwise_keep_dtype(self):
        """scipy has no bf16 — the host round-trips must still work and
        must NOT silently promote to f32 (the round-1 dtype-leak trap)."""
        import jax.numpy as jnp
        mat = _rand_csr(6, 6, seed=9)
        a = sparse.csr_matrix((mat.data, mat.indices, mat.indptr),
                              shape=mat.shape, dtype="bfloat16")
        assert a.dtype == onp.dtype("bfloat16") if hasattr(
            onp, "bfloat16") else str(a.dtype) == "bfloat16"
        out = sparse.add(a, a)
        assert str(out.dtype) == "bfloat16"
        # rebind the mirror -> components re-derive through f32 scipy
        a._data = jnp.asarray(a._data) * 2
        assert str(a.data.dtype) == "bfloat16"
        onp.testing.assert_allclose(
            onp.asarray(a.asnumpy(), onp.float32),
            onp.asarray((2 * mat).toarray().astype("float32")), rtol=2e-2,
            atol=1e-2)

    def test_csr_shape_mismatch_raises(self):
        a_s, b_s = _rand_csr(4, 4), _rand_csr(5, 4, seed=1)
        a = sparse.csr_matrix((a_s.data, a_s.indices, a_s.indptr),
                              shape=a_s.shape)
        b = sparse.csr_matrix((b_s.data, b_s.indices, b_s.indptr),
                              shape=b_s.shape)
        with pytest.raises(mx.base.MXNetError):
            sparse.add(a, b)

    def test_cast_storage_round_trip(self):
        dense = onp.random.RandomState(8).randn(5, 5).astype(onp.float32)
        dense[dense < 0.5] = 0
        a = sparse.cast_storage(nd.array(dense), "csr")
        assert a.stype == "csr"
        back = sparse.cast_storage(a, "default")
        assert back.stype == "default"
        onp.testing.assert_allclose(back.asnumpy(), dense, rtol=1e-6)


class TestOperatorDispatch:
    """Python operators on sparse operands must route storage-aware
    (reference FComputeEx dispatch): sparse op same-kind-sparse keeps
    the storage type via the union kernels; mixed/scalar pairings
    densify (the reference's storage fallback) instead of crashing."""

    def test_rs_plus_rs_stays_row_sparse(self):
        a = sparse.row_sparse_array(
            (onp.arange(6, dtype=onp.float32).reshape(2, 3),
             onp.array([1, 4])), shape=(6, 3))
        b = sparse.row_sparse_array(
            (onp.ones((2, 3), onp.float32), onp.array([4, 5])),
            shape=(6, 3))
        s = a + b
        assert s.stype == "row_sparse"
        want = onp.zeros((6, 3), onp.float32)
        want[1] = [0, 1, 2]
        want[4] = [4, 5, 6]
        want[5] = 1
        onp.testing.assert_allclose(s.asnumpy(), want)
        m = a * b
        assert m.stype == "row_sparse"
        wm = onp.zeros((6, 3), onp.float32)
        wm[4] = [3, 4, 5]
        onp.testing.assert_allclose(m.asnumpy(), wm)

    def test_csr_minus_csr_stays_csr(self):
        a_s = _rand_csr(6, 6, seed=20)
        b_s = _rand_csr(6, 6, seed=21)
        a = sparse.csr_matrix((a_s.data, a_s.indices, a_s.indptr),
                              shape=a_s.shape)
        b = sparse.csr_matrix((b_s.data, b_s.indices, b_s.indptr),
                              shape=b_s.shape)
        out = a - b
        assert out.stype == "csr"
        onp.testing.assert_allclose(out.asnumpy(),
                                    (a_s - b_s).toarray(), rtol=1e-6)

    def test_mixed_densifies_scalar_scale_keeps_storage(self):
        a = sparse.row_sparse_array(
            (onp.ones((1, 3), onp.float32), onp.array([2])), shape=(4, 3))
        m = a + nd.ones((4, 3))
        assert m.stype == "default"
        onp.testing.assert_allclose(m.asnumpy()[2], [2, 2, 2])
        # scalar mul/div preserve storage (reference _mul_scalar
        # FComputeEx): no dense mirror materialization
        for out, want in [(a * 2.0, 2.0), (2.0 * a, 2.0), (a / 2.0, 0.5)]:
            assert out.stype == "row_sparse"
            assert out._dense_cache is None  # mirror never built
            onp.testing.assert_allclose(out.asnumpy()[2], [want] * 3)
        sc = 2.0 / a  # reverse div is not a scale -> dense fallback
        assert sc.stype == "default"
        # scalar add destroys sparsity -> dense
        assert (a + 1.0).stype == "default"
        # csr scalar scale also keeps storage
        c = sparse.csr_matrix(
            (onp.array([3.0], onp.float32), onp.array([1]),
             onp.array([0, 1, 1])), shape=(2, 3))
        cs = c * 3.0
        assert cs.stype == "csr" and cs._dense_cache is None
        onp.testing.assert_allclose(cs.asnumpy()[0, 1], 9.0)

    def test_broadcast_shapes_densify_not_crash(self):
        a = sparse.row_sparse_array(
            (onp.ones((2, 3), onp.float32), onp.array([0, 2])),
            shape=(4, 3))
        b = sparse.row_sparse_array(
            (onp.full((1, 3), 2.0, onp.float32), onp.array([0])),
            shape=(1, 3))
        out = a * b  # (4,3)*(1,3): union kernels can't broadcast ->
        assert out.stype == "default"  # dense fallback, correct values
        want = onp.zeros((4, 3), onp.float32)
        want[0] = want[2] = 2.0
        onp.testing.assert_allclose(out.asnumpy(), want)

    def test_operator_grads_flow_under_record(self):
        """Under autograd.record() the operators must take the RECORDED
        dense path (the union kernels build results structurally and
        record nothing) — gradients land on the sparse operands as
        dense grads, not silent zeros."""
        from mxnet_tpu import autograd
        a = sparse.row_sparse_array(
            (onp.arange(6, dtype=onp.float32).reshape(2, 3),
             onp.array([1, 4])), shape=(6, 3))
        b = sparse.row_sparse_array(
            (onp.ones((2, 3), onp.float32), onp.array([4, 5])),
            shape=(6, 3))
        a.attach_grad()
        b.attach_grad()
        with autograd.record():
            s = a * b
            loss = nd.sum(s)
        loss.backward()
        # d(sum(a*b))/da = dense(b); nonzero exactly on b's rows
        want_da = onp.zeros((6, 3), onp.float32)
        want_da[4] = want_da[5] = 1.0
        onp.testing.assert_allclose(a.grad.asnumpy(), want_da)
        # d(sum(a*b))/db = dense(a): rows 1 and 4
        want_db = onp.zeros((6, 3), onp.float32)
        want_db[1] = [0, 1, 2]
        want_db[4] = [3, 4, 5]
        onp.testing.assert_allclose(b.grad.asnumpy(), want_db)

    def test_huge_row_count_guard(self):
        class FakeRS(sparse.RowSparseNDArray):
            def __init__(self):
                pass

            @property
            def shape(self):
                return (2 ** 31, 3)

        from mxnet_tpu.base import MXNetError
        with pytest.raises(MXNetError, match="int32 row keys"):
            sparse._rs_elemwise("add", FakeRS(), FakeRS())
        with pytest.raises(MXNetError, match="int32 row indices"):
            sparse.retain(FakeRS(), nd.array(onp.array([1])))


class TestRowSparse:
    def test_dot_golden(self):
        vals = onp.random.RandomState(0).randn(3, 6).astype(onp.float32)
        idx = onp.array([1, 4, 7])
        a = sparse.row_sparse_array((vals, idx), shape=(9, 6))
        assert a._dense_cache is None  # lazy
        rhs = onp.random.RandomState(1).randn(6, 4).astype(onp.float32)
        out = sparse.dot(a, nd.array(rhs))
        dense = onp.zeros((9, 6), onp.float32)
        dense[idx] = vals
        onp.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5)

    def test_dot_transpose_golden(self):
        vals = onp.random.RandomState(2).randn(3, 6).astype(onp.float32)
        idx = onp.array([0, 2, 5])
        a = sparse.row_sparse_array((vals, idx), shape=(7, 6))
        rhs = onp.random.RandomState(3).randn(7, 4).astype(onp.float32)
        out = sparse.dot(a, nd.array(rhs), transpose_a=True)
        dense = onp.zeros((7, 6), onp.float32)
        dense[idx] = vals
        onp.testing.assert_allclose(out.asnumpy(), dense.T @ rhs,
                                    rtol=1e-5, atol=1e-6)

    def test_retain(self):
        vals = onp.arange(12, dtype=onp.float32).reshape(4, 3)
        idx = onp.array([0, 2, 5, 6])
        a = sparse.row_sparse_array((vals, idx), shape=(8, 3))
        kept = sparse.sparse_retain(a, nd.array(onp.array([2, 6])))
        onp.testing.assert_array_equal(kept.indices.asnumpy(), [2, 6])
        onp.testing.assert_allclose(kept.data.asnumpy(), vals[[1, 3]])

    def test_elemwise_index_union(self):
        a = sparse.row_sparse_array(
            (onp.ones((2, 3), onp.float32), onp.array([1, 3])), shape=(6, 3))
        b = sparse.row_sparse_array(
            (2 * onp.ones((2, 3), onp.float32), onp.array([3, 5])),
            shape=(6, 3))
        out = sparse.add(a, b)
        assert out.stype == "row_sparse"
        onp.testing.assert_array_equal(out.indices.asnumpy(), [1, 3, 5])
        expect = onp.zeros((6, 3), onp.float32)
        expect[1] = 1
        expect[3] = 3
        expect[5] = 2
        onp.testing.assert_allclose(out.asnumpy(), expect)

    def test_rebind_refreshes_components(self):
        """After something outside the sparse API rebinds ._data, the
        component accessors re-derive from the dense mirror."""
        a = sparse.row_sparse_array(
            (onp.ones((1, 2), onp.float32), onp.array([1])), shape=(4, 2))
        import jax.numpy as jnp
        new = onp.zeros((4, 2), onp.float32)
        new[3] = 7
        a._data = jnp.asarray(new)
        onp.testing.assert_array_equal(a.indices.asnumpy(), [3])
        onp.testing.assert_allclose(a.data.asnumpy(), [[7, 7]])

    def test_shape_mismatch_raises(self):
        a = sparse.row_sparse_array(
            (onp.ones((1, 3), onp.float32), onp.array([1])), shape=(4, 3))
        b = sparse.row_sparse_array(
            (onp.ones((1, 3), onp.float32), onp.array([5])), shape=(6, 3))
        with pytest.raises(mx.base.MXNetError):
            sparse.add(a, b)

    def test_zeros(self):
        z = sparse.zeros("row_sparse", (5, 4))
        assert z.stype == "row_sparse" and z.shape == (5, 4)
        assert onp.all(z.asnumpy() == 0)
        z = sparse.zeros("csr", (5, 4))
        assert z.stype == "csr"
        assert onp.all(z.asnumpy() == 0)


class TestJittableCSRUnion:
    """The r4 padded-nnz union kernel (VERDICT r3 item 6): pattern math
    entirely in jax, parity vs scipy across randomized patterns, and the
    kernel itself compiles under jax.jit (static shapes, no host sync)."""

    def _rand_csr(self, rng, shape, density):
        import scipy.sparse as sp
        m = sp.random(*shape, density=density, random_state=rng,
                      format="csr", dtype=onp.float32)
        m.sort_indices()
        from mxnet_tpu.ndarray.sparse import CSRNDArray
        return CSRNDArray(m.data, m.indptr, m.indices, shape), m

    @pytest.mark.parametrize("opname", ["add", "subtract", "multiply"])
    @pytest.mark.parametrize("density", [0.0, 0.05, 0.4])
    def test_parity_vs_scipy(self, opname, density):
        import scipy.sparse as sp
        from mxnet_tpu.ndarray import sparse as mxsp
        seed = ({"add": 1, "subtract": 2, "multiply": 3}[opname] * 1000
                + int(density * 100))
        rng = onp.random.RandomState(seed)
        a, sa = self._rand_csr(rng, (13, 17), density)
        b, sb = self._rand_csr(rng, (13, 17), density * 0.7)
        out = getattr(mxsp, opname)(a, b)
        ref = {"add": lambda: sa + sb,
               "subtract": lambda: sa - sb,
               "multiply": lambda: sa.multiply(sb).tocsr()}[opname]()
        ref.sort_indices()
        ref.eliminate_zeros()
        got = sp.csr_matrix(
            (onp.asarray(out.data.asnumpy(), onp.float32),
             onp.asarray(out.indices.asnumpy()),
             onp.asarray(out.indptr.asnumpy())), shape=out.shape)
        onp.testing.assert_allclose(got.toarray(), ref.toarray(),
                                    rtol=1e-5, atol=1e-6)

    def test_cancellation_prunes_explicit_zeros(self):
        """subtract(a, a) must return an EMPTY pattern (nnz 0), matching
        the scipy/reference csr binop pruning — explicit zeros from
        cancellation are not kept."""
        from mxnet_tpu.ndarray import sparse as mxsp
        rng = onp.random.RandomState(11)
        a, _ = self._rand_csr(rng, (7, 9), 0.3)
        out = mxsp.subtract(a, a)
        assert out.data.shape[0] == 0
        assert int(out.indptr.asnumpy()[-1]) == 0
        onp.testing.assert_allclose(out.tostype("default").asnumpy(), 0.0)

    def test_union_kernel_jits(self):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ndarray.sparse import _csr_union_device
        ka = jnp.asarray([1, 5, 9], jnp.int32)
        va = jnp.asarray([1.0, 2.0, 3.0], jnp.float32)
        kb = jnp.asarray([5, 7], jnp.int32)
        vb = jnp.asarray([10.0, 20.0], jnp.float32)
        f = jax.jit(lambda *a: _csr_union_device(*a, mode="sum"))
        keys, vals, valid = f(ka, va, kb, vb)
        assert keys.shape == (5,) and vals.shape == (5,)
        assert int(valid.sum()) == 4
        onp.testing.assert_array_equal(onp.asarray(keys[:4]), [1, 5, 7, 9])
        onp.testing.assert_allclose(onp.asarray(vals[:4]),
                                    [1.0, 12.0, 20.0, 3.0])
        g = jax.jit(lambda *a: _csr_union_device(*a, mode="prod"))
        keys, vals, valid = g(ka, va, kb, vb)
        assert int(valid.sum()) == 1
        assert int(keys[0]) == 5 and float(vals[0]) == 20.0

    def test_sparse_ops_never_touch_the_dense_mirror(self):
        """dot and elemwise on CSR operands must not materialize the
        dense cache (the r3 'lazy dense mirror' stays for generic dense
        interop only)."""
        from mxnet_tpu.ndarray import sparse as mxsp
        rng = onp.random.RandomState(3)
        a, _ = self._rand_csr(rng, (9, 11), 0.3)
        b, _ = self._rand_csr(rng, (9, 11), 0.3)
        rhs = mx.nd.array(rng.rand(11, 4).astype("float32"))
        mxsp.add(a, b)
        mxsp.multiply(a, b)
        mxsp.dot(a, rhs)
        assert a._dense_cache is None and b._dense_cache is None

    def test_rs_union_device_jittable(self):
        """The row_sparse union kernel is a pure static-shape jax
        function (VERDICT r4 item 5): jit it directly, check keys,
        union semantics (multiply keeps the union pattern with zero
        rows outside the intersection), and the packed layout."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ndarray.sparse import _rs_union_device
        ka = jnp.asarray([1, 5], jnp.int32)
        va = jnp.asarray([[1., 2.], [3., 4.]])
        kb = jnp.asarray([5, 9], jnp.int32)
        vb = jnp.asarray([[10., 10.], [7., 8.]])
        f = jax.jit(lambda *a: _rs_union_device(*a, opname="add"))
        keys, vals, valid = f(ka, va, kb, vb)
        assert keys.shape == (4,) and vals.shape == (4, 2)
        assert int(valid.sum()) == 3
        onp.testing.assert_array_equal(onp.asarray(keys[:3]), [1, 5, 9])
        onp.testing.assert_allclose(onp.asarray(vals[:3]),
                                    [[1, 2], [13, 14], [7, 8]])
        g = jax.jit(lambda *a: _rs_union_device(*a, opname="multiply"))
        keys, vals, valid = g(ka, va, kb, vb)
        assert int(valid.sum()) == 3  # union pattern, not intersection
        onp.testing.assert_allclose(onp.asarray(vals[:3]),
                                    [[0, 0], [30, 40], [0, 0]])

    def test_rs_ops_never_touch_the_dense_mirror(self):
        """row_sparse elemwise and sparse_retain must not materialize
        the dense cache (r4 item 5 extends the csr-only regression)."""
        from mxnet_tpu.ndarray import sparse as mxsp
        rng = onp.random.RandomState(4)
        da = onp.zeros((10, 3), "float32")
        db = onp.zeros((10, 3), "float32")
        da[[1, 4, 7]] = rng.rand(3, 3)
        db[[4, 8]] = rng.rand(2, 3)
        a = mx.nd.array(da).tostype("row_sparse")
        b = mx.nd.array(db).tostype("row_sparse")
        a._dense_cache = None
        b._dense_cache = None
        s = mxsp.add(a, b)
        m = mxsp.multiply(a, b)
        r = mxsp.sparse_retain(a, mx.nd.array(
            onp.asarray([1, 7], "float32")))
        assert a._dense_cache is None and b._dense_cache is None
        onp.testing.assert_allclose(onp.asarray(s.asnumpy()), da + db,
                                    rtol=1e-6)
        onp.testing.assert_allclose(onp.asarray(m.asnumpy()), da * db,
                                    rtol=1e-6)
        onp.testing.assert_array_equal(onp.asarray(r.indices.asnumpy()),
                                       [1, 7])


class TestSparseScalarDtypeGate:
    """Scalar mul/div storage-preservation is gated to floating dtypes
    and nonzero divisors — int sparse must promote like the dense op
    instead of truncating the scale factor to 0 (ADVICE.md item)."""

    def _int_rs(self):
        d = onp.zeros((4, 5), "int32")
        d[1] = [1, 2, 0, 4, 5]
        d[3] = [0, 0, 3, 0, 0]
        return d, mx.nd.array(d.astype("float32")).tostype(
            "row_sparse"), sparse.RowSparseNDArray(
                onp.asarray([[1, 2, 0, 4, 5], [0, 0, 3, 0, 0]], "int32"),
                onp.asarray([1, 3]), (4, 5))

    def test_int_rowsparse_div_promotes(self):
        import jax.numpy as jnp
        d, _, rs = self._int_rs()
        out = rs / 2
        # dense semantics: int / 2 -> float, 0.5 not truncated to 0
        onp.testing.assert_allclose(onp.asarray(out.asnumpy()), d / 2,
                                    rtol=1e-6)
        assert jnp.issubdtype(jnp.dtype(out.dtype), jnp.floating)

    def test_int_rowsparse_mul_matches_dense(self):
        d, _, rs = self._int_rs()
        # the dense scalar op casts the scalar to the array dtype
        # (reference NDArray scalar semantics) — int sparse must agree
        # with the dense result instead of scaling through _scale
        dense = mx.nd.array(d) * 0.5
        onp.testing.assert_allclose(onp.asarray((rs * 0.5).asnumpy()),
                                    onp.asarray(dense.asnumpy()),
                                    rtol=1e-6)
        dense3 = mx.nd.array(d) * 3
        onp.testing.assert_allclose(onp.asarray((rs * 3).asnumpy()),
                                    onp.asarray(dense3.asnumpy()),
                                    rtol=1e-6)

    def test_float_rowsparse_scalar_keeps_storage(self):
        _, f, _ = self._int_rs()
        out = f / 2
        assert out.stype == "row_sparse"
        onp.testing.assert_allclose(
            onp.asarray(out.asnumpy())[1], [0.5, 1, 0, 2, 2.5], rtol=1e-6)
        out2 = f * 3.0
        assert out2.stype == "row_sparse"

    def test_nonfinite_scalar_goes_dense(self):
        _, f, _ = self._int_rs()
        # 0 * inf = nan at UNSTORED positions — only the dense op can
        # represent that, so inf/nan scalars must bypass _scale
        out = f * float("inf")
        a = onp.asarray(out.asnumpy())
        assert onp.isnan(a[0]).all()      # unstored row: 0 * inf
        assert onp.isinf(a[1][0])         # stored value: 1 * inf
        out2 = f / float("nan")
        assert onp.isnan(onp.asarray(out2.asnumpy())).all()

    def test_float_div_by_zero_goes_dense(self):
        _, f, _ = self._int_rs()
        out = f / 0
        # dense semantics: unstored zeros become 0/0 = nan (the sparse
        # _scale path could only scale stored values)
        a = onp.asarray(out.asnumpy())
        assert onp.isnan(a[0]).all()
        assert onp.isinf(a[1][0])

    def test_int_csr_div_promotes(self):
        d = onp.zeros((3, 4), "int32")
        d[0, 1] = 6
        d[2, 3] = 9
        mat = sp.csr_matrix(d)
        a = sparse.csr_matrix((onp.asarray(mat.data, "int32"),
                               mat.indices, mat.indptr), shape=(3, 4))
        out = a / 4
        onp.testing.assert_allclose(onp.asarray(out.asnumpy()), d / 4,
                                    rtol=1e-6)
