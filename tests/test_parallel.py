"""Collectives + SPMD sharding tests on the 8-device virtual CPU mesh
(mirrors the reference's multi-process-on-localhost nightly kvstore tests,
SURVEY.md §7 test strategy)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.parallel import P


def test_make_mesh_shapes():
    m = parallel.make_mesh()
    assert m.devices.size == 8
    m2 = parallel.make_mesh({"dp": 2, "tp": -1})
    assert dict(zip(m2.axis_names, m2.devices.shape)) == {"dp": 2, "tp": 4}
    with pytest.raises(mx.MXNetError):
        parallel.make_mesh({"dp": 3})


def test_use_mesh_context():
    m = parallel.make_mesh({"dp": 4, "tp": 2})
    assert parallel.current_mesh() is None
    with parallel.use_mesh(m):
        assert parallel.current_mesh() is m
        assert parallel.default_mesh() is m
    assert parallel.current_mesh() is None


def test_all_reduce_sum_mean():
    x = mx.nd.array(onp.arange(16, dtype="float32").reshape(8, 2))
    red = parallel.all_reduce(x, axis="dp", op="sum")
    # each shard is (1,2); sum over 8 shards
    expect = onp.arange(16, dtype="float32").reshape(8, 2).sum(0)
    onp.testing.assert_allclose(red.asnumpy(), expect[None, :], rtol=1e-6)
    mean = parallel.all_reduce(x, axis="dp", op="mean")
    onp.testing.assert_allclose(mean.asnumpy(), expect[None, :] / 8,
                                rtol=1e-6)


def test_all_gather_roundtrip():
    x = mx.nd.array(onp.arange(8, dtype="float32").reshape(8, 1))
    g = parallel.all_gather(x, axis="dp")
    assert g.shape == (8, 1)
    onp.testing.assert_allclose(g.asnumpy(), x.asnumpy())


def test_reduce_scatter():
    x = mx.nd.array(onp.ones((8, 4), dtype="float32"))
    r = parallel.reduce_scatter(x, axis="dp", op="sum")
    assert r.shape == (8, 4)
    onp.testing.assert_allclose(r.asnumpy(), 8 * onp.ones((8, 4)), rtol=1e-6)


def test_broadcast_root():
    x = mx.nd.array(onp.arange(8, dtype="float32").reshape(8, 1))
    b = parallel.broadcast(x, axis="dp", root=3)
    onp.testing.assert_allclose(b.asnumpy(), 3 * onp.ones((1, 1)))


def test_ring_pass_rotates():
    m = parallel.make_mesh({"sp": 8})
    x = mx.nd.array(onp.arange(8, dtype="float32").reshape(8, 1))
    y = parallel.ring_pass(x, mesh=m, axis="sp", shift=1)
    # shard i receives shard (i-1 mod 8)'s value
    expect = onp.roll(onp.arange(8, dtype="float32"), 1).reshape(8, 1)
    onp.testing.assert_allclose(y.asnumpy(), expect)


def test_sharding_rules_fit():
    m = parallel.make_mesh({"dp": 2, "tp": 4})
    rules = parallel.ShardingRules([
        (r".*weight", P("tp", None)),
        (r".*bias", P("tp")),
    ])
    assert tuple(rules.spec_for("dense0.weight", (8, 16), m)) == ("tp", None)
    # 6 not divisible by tp=4 -> fall back to replicated on that dim
    assert tuple(rules.spec_for("dense0.weight", (6, 16), m)) == (None, None)
    assert tuple(rules.spec_for("dense0.bias", (8,), m)) == ("tp",)
    assert tuple(rules.spec_for("other.gamma", (8,), m)) == ()


def _make_net():
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    return net


def test_spmd_trainer_dp_trains():
    from mxnet_tpu import gluon
    mx.random.seed(0)
    net = _make_net()
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh({"dp": 8})
    tr = parallel.SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              "adam", {"learning_rate": 0.05}, mesh=mesh)
    onp.random.seed(0)
    X = onp.random.randn(64, 16).astype("float32")
    W = onp.random.randn(16, 8).astype("float32")
    y = (X @ W).argmax(1)
    losses = [float(tr.step(mx.nd.array(X), mx.nd.array(y)).asnumpy().item())
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses


def test_spmd_trainer_tp_matches_replicated():
    """Same seed, TP-sharded vs replicated params: losses must agree (the
    sharding is a layout, not a math change)."""
    from mxnet_tpu import gluon

    def run(rules):
        mx.random.seed(1)
        net = _make_net()
        net.initialize(mx.init.Xavier())
        mesh = parallel.make_mesh({"dp": 2, "tp": 4})
        tr = parallel.SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=mesh, rules=rules)
        onp.random.seed(1)
        X = onp.random.randn(16, 16).astype("float32")
        y = onp.random.randint(0, 8, size=16)
        return [tr.step(mx.nd.array(X), mx.nd.array(y)).asnumpy().item()
                for _ in range(5)]

    tp_rules = parallel.ShardingRules([(r".*weight", P("tp", None))])
    base = run(None)
    tp = run(tp_rules)
    onp.testing.assert_allclose(base, tp, rtol=2e-5)


def test_spmd_trainer_nadam_multi_step():
    """Nadam's momentum schedule lives in per-param state, not on self —
    step 2 must not see a leaked tracer."""
    from mxnet_tpu import gluon
    mx.random.seed(0)
    net = _make_net()
    net.initialize()
    tr = parallel.SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              "nadam", {"learning_rate": 0.01},
                              mesh=parallel.make_mesh({"dp": 8}))
    X = onp.random.randn(16, 16).astype("float32")
    y = onp.random.randint(0, 8, size=16)
    for _ in range(3):
        loss = tr.step(mx.nd.array(X), mx.nd.array(y))
    assert onp.isfinite(loss.asnumpy()).all()


def test_spmd_trainer_honors_instance_rescale():
    from mxnet_tpu import gluon, optimizer
    mx.random.seed(0)
    net = _make_net()
    net.initialize()
    opt = optimizer.SGD(learning_rate=0.5, rescale_grad=0.0)
    tr = parallel.SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), opt,
                              mesh=parallel.make_mesh({"dp": 8}))
    X = onp.random.randn(16, 16).astype("float32")
    y = onp.random.randint(0, 8, size=16)
    net(mx.nd.array(X))  # materialize deferred shapes
    w_before = net[0].weight.data().asnumpy().copy()
    tr.step(mx.nd.array(X), mx.nd.array(y))
    onp.testing.assert_allclose(net[0].weight.data().asnumpy(), w_before)


def test_fit_spec_truncates_rank():
    m = parallel.make_mesh({"dp": 2, "tp": 4})
    rules = parallel.ShardingRules([(r".*dense.*", P("tp", None))])
    # rank-1 bias matched by a rank-2 spec: spec must truncate, not error
    assert tuple(rules.spec_for("dense0.bias", (8,), m)) in ((None,), ("tp",))
    from mxnet_tpu.gluon import nn
    net = nn.Dense(8, in_units=16)
    net.initialize()
    parallel.shard_block(net, m, rules)  # must not raise


def test_broadcast_bad_root_raises():
    x = mx.nd.array(onp.arange(8, dtype="float32").reshape(8, 1))
    with pytest.raises(ValueError):
        parallel.broadcast(x, axis="dp", root=8)


def test_spmd_trainer_batchnorm_aux_updates():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8), nn.BatchNorm(in_channels=16),
            nn.Dense(4))
    net.initialize()
    mesh = parallel.make_mesh({"dp": 8})
    tr = parallel.SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              "sgd", {"learning_rate": 0.1}, mesh=mesh)
    X = onp.random.randn(16, 8).astype("float32")
    y = onp.random.randint(0, 4, size=16)
    bn = net[1]
    before = bn.running_mean.data().asnumpy().copy()
    for _ in range(3):
        tr.step(mx.nd.array(X), mx.nd.array(y))
    after = bn.running_mean.data().asnumpy()
    assert not onp.allclose(before, after), "running stats never updated"


def test_run_steps_matches_sequential():
    """N scanned steps inside one jit == N sequential step() calls."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel

    mesh = parallel.make_mesh({"dp": 8})
    rng = onp.random.RandomState(0)
    xs = rng.rand(4, 8, 5).astype(onp.float32)
    ys = rng.rand(4, 8, 3).astype(onp.float32)

    def fresh():
        mx.random.seed(1)
        n = gluon.nn.Dense(3)
        n.initialize(mx.init.Xavier())
        return n, parallel.SPMDTrainer(n, gluon.loss.L2Loss(), "sgd",
                                       {"learning_rate": 0.1}, mesh=mesh)

    na, ta = fresh()
    for i in range(4):
        ta.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    nb, tb = fresh()
    tb.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    losses = tb.run_steps(mx.nd.array(xs[1:]), mx.nd.array(ys[1:]))
    assert losses.shape == (3,)
    assert tb._t == 4
    wa = list(na.collect_params().values())[0].data().asnumpy()
    wb = list(nb.collect_params().values())[0].data().asnumpy()
    onp.testing.assert_allclose(wa, wb, rtol=1e-5, atol=1e-6)


def test_gpipe_matches_sequential():
    """GPipe over the pp axis == sequential stage application, forward AND
    gradient (the schedule is differentiable end-to-end)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.pipeline import gpipe_apply, stack_stage_params

    mesh = parallel.make_mesh({"pp": 8})
    rng = onp.random.RandomState(0)
    S, D, B = 8, 8, 16

    def stage_fn(p, h):
        return h + jnp.tanh(h @ p["w"]) @ p["v"]

    stage_params = [
        dict(w=jnp.asarray(rng.randn(D, D).astype(onp.float32)) * 0.3,
             v=jnp.asarray(rng.randn(D, D).astype(onp.float32)) * 0.3)
        for _ in range(S)]
    stacked = stack_stage_params(stage_params)
    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))

    out = gpipe_apply(stage_fn, stacked, x, mesh=mesh, microbatches=4)
    ref = x
    for p in stage_params:
        ref = stage_fn(p, ref)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)

    def loss(sp):
        return (gpipe_apply(stage_fn, sp, x, mesh=mesh,
                            microbatches=4) ** 2).sum()

    def ref_loss(sp):
        h = x
        for i in range(S):
            h = stage_fn(jax.tree.map(lambda a: a[i], sp), h)
        return (h ** 2).sum()

    g1 = jax.grad(loss)(stacked)
    g2 = jax.grad(ref_loss)(stacked)
    for k in ("w", "v"):
        onp.testing.assert_allclose(onp.asarray(g1[k]), onp.asarray(g2[k]),
                                    rtol=5e-4, atol=5e-5)


def test_gpipe_dp_tp_pp_composition():
    """3-axis mesh: tp-sharded stage weights + dp-sharded microbatches
    inside the GPipe trunk match the sequential reference (fwd + grad),
    and two SGD steps descend (the __graft_entry__ dryrun contract)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax import lax
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.mesh import P
    from mxnet_tpu.parallel.pipeline import gpipe_apply

    mesh = parallel.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rng = onp.random.RandomState(5)
    S, D, B = 2, 8, 8
    ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.4
    x = jnp.asarray(rng.randn(B, D), jnp.float32)

    def stage(p, h):
        part = h @ p["w"]
        full = lax.all_gather(part, "tp", axis=-1, tiled=True)
        return h + jnp.tanh(full)

    def pp_loss(w):
        out = gpipe_apply(stage, {"w": w}, x, mesh=mesh, microbatches=S,
                          param_specs={"w": P("pp", None, "tp")},
                          batch_axis="dp")
        return (out ** 2).sum()

    def ref_loss(w):
        h = x
        for i in range(S):
            h = h + jnp.tanh(h @ w[i])
        return (h ** 2).sum()

    losses = []
    for _ in range(2):
        v, g = jax.value_and_grad(pp_loss)(ws)
        rv, rg = jax.value_and_grad(ref_loss)(ws)
        onp.testing.assert_allclose(float(v), float(rv), rtol=1e-5)
        onp.testing.assert_allclose(onp.asarray(g), onp.asarray(rg),
                                    rtol=1e-4, atol=1e-5)
        losses.append(float(v))
        ws = ws - 0.02 * g
    assert losses[1] < losses[0]


def test_gpipe_shape_guard():
    import jax.numpy as jnp
    import numpy as onp
    import pytest
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.pipeline import gpipe_apply, stack_stage_params

    mesh = parallel.make_mesh({"pp": 8})
    params = stack_stage_params(
        [dict(w=jnp.ones((4, 6))) for _ in range(8)])
    with pytest.raises(ValueError, match="ring-invariant"):
        gpipe_apply(lambda p, h: h @ p["w"], params,
                    jnp.ones((16, 4)), mesh=mesh)
