"""Tools + opperf tests (reference tools/ and benchmark/opperf coverage;
SURVEY.md L10, §6)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _run(args, **kw):
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, cwd="/root/repo", env=_ENV, **kw)


@pytest.fixture
def image_tree(tmp_path):
    from PIL import Image
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            arr = (onp.random.rand(20, 20, 3) * 255).astype(onp.uint8)
            Image.fromarray(arr).save(str(d / f"{i}.jpg"))
    return str(root)


class TestIm2Rec:
    def test_list_mode(self, image_tree, tmp_path):
        prefix = str(tmp_path / "d")
        r = _run(["tools/im2rec.py", prefix, image_tree, "--recursive",
                  "--list"])
        assert r.returncode == 0, r.stderr
        lines = open(prefix + ".lst").read().strip().splitlines()
        assert len(lines) == 6
        labels = {l.split("\t")[1] for l in lines}
        assert labels == {"0", "1"}

    def test_pack_and_read_back(self, image_tree, tmp_path):
        prefix = str(tmp_path / "d")
        r = _run(["tools/im2rec.py", prefix, image_tree, "--recursive",
                  "--resize", "16"])
        assert r.returncode == 0, r.stderr
        from mxnet_tpu.gluon.data.vision import ImageRecordDataset
        ds = ImageRecordDataset(prefix + ".rec")
        assert len(ds) == 6
        img, label = ds[0]
        assert min(img.shape[:2]) == 16
        assert label in (0.0, 1.0)


class TestParseLog:
    def test_parses_metrics(self, tmp_path):
        log = tmp_path / "t.log"
        log.write_text(
            "INFO:root:Epoch[0] Train-accuracy=0.5\n"
            "INFO:root:Epoch[0] Time cost=10.1\n"
            "INFO:root:Epoch[1] Train-accuracy=0.8\n"
            "INFO:root:Epoch[1] Validation-accuracy=0.75\n")
        r = _run(["tools/parse_log.py", str(log), "--format", "csv"])
        assert r.returncode == 0
        assert "train-accuracy" in r.stdout
        assert "0.75" in r.stdout

    def test_empty_log_errors(self, tmp_path):
        log = tmp_path / "e.log"
        log.write_text("nothing here\n")
        assert _run(["tools/parse_log.py", str(log)]).returncode == 1


class TestDiagnose:
    def test_runs(self):
        r = _run(["tools/diagnose.py"])
        assert r.returncode == 0
        assert "mxnet_tpu" in r.stdout
        assert "features" in r.stdout


class TestBandwidth:
    def test_kvstore_bandwidth(self):
        r = _run(["tools/bandwidth/measure.py", "--sizes", "65536",
                  "--repeats", "2"], timeout=180)
        assert r.returncode == 0, r.stderr[-500:]
        assert "GB/s" in r.stdout


class TestOpperf:
    def test_subset_runs(self):
        r = _run(["benchmark/opperf/opperf.py", "--ops", "dot", "relu",
                  "--runs", "2"], timeout=240)
        assert r.returncode == 0, r.stderr[-500:]
        assert "dot" in r.stdout and "relu" in r.stdout

    def test_python_api(self):
        from benchmark.opperf.opperf import run_op_benchmark
        res = run_op_benchmark(["sigmoid"], warmup=1, runs=2)
        assert res[0]["op"] == "sigmoid"
        assert "jit_ms" in res[0]


class TestRTC:
    def test_pallas_module_kernel(self):
        import mxnet_tpu as mx

        def addmul(x_ref, y_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0 + y_ref[...]

        mod = mx.rtc.PallasModule({"addmul": addmul})
        k = mod.get_kernel("addmul")
        x = mx.nd.array(onp.arange(8, dtype=onp.float32).reshape(2, 4))
        out = k([x, mx.nd.ones((2, 4))])
        onp.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2 + 1)

    def test_unknown_kernel_and_cuda_gate(self):
        import mxnet_tpu as mx
        from mxnet_tpu.base import MXNetError
        mod = mx.rtc.PallasModule({"k": lambda x_ref, o_ref: None})
        with pytest.raises(MXNetError):
            mod.get_kernel("missing")
        with pytest.raises(MXNetError):
            mx.rtc.CudaModule("source")


class TestSymbolicCheckers:
    def test_check_symbolic_forward_backward(self):
        import mxnet_tpu as mx
        from mxnet_tpu.test_utils import (check_symbolic_forward,
                                          check_symbolic_backward)
        x = onp.random.rand(3, 4).astype(onp.float32) - 0.5
        s = mx.sym.relu(mx.sym.var("x"))
        check_symbolic_forward(s, [x], [onp.maximum(x, 0)])
        check_symbolic_backward(s, [x], [onp.ones_like(x)],
                                [(x > 0).astype(onp.float32)])


class TestProfiler:
    def test_aggregate_stats_capture_and_pause(self, tmp_path):
        import mxnet_tpu as mx
        mx.profiler.set_config(filename=str(tmp_path / "prof.json"),
                               aggregate_stats=True)
        mx.profiler.start()
        a = mx.nd.array(onp.ones((8, 8), onp.float32))
        _ = mx.nd.dot(a, a)
        mx.profiler.pause()
        _ = a + 1  # excluded section
        mx.profiler.resume()
        _ = mx.nd.dot(a, a)
        mx.profiler.stop()
        table = mx.profiler.dumps()
        assert "dot" in table
        mx.profiler.dump()
        import json
        trace = json.load(open(str(tmp_path / "prof.json")))
        assert any(ev["name"] == "dot" for ev in trace["traceEvents"])
