"""Tools + opperf tests (reference tools/ and benchmark/opperf coverage;
SURVEY.md L10, §6)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _run(args, **kw):
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, cwd="/root/repo", env=_ENV, **kw)


@pytest.fixture
def image_tree(tmp_path):
    from PIL import Image
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            arr = (onp.random.rand(20, 20, 3) * 255).astype(onp.uint8)
            Image.fromarray(arr).save(str(d / f"{i}.jpg"))
    return str(root)


class TestIm2Rec:
    def test_list_mode(self, image_tree, tmp_path):
        prefix = str(tmp_path / "d")
        r = _run(["tools/im2rec.py", prefix, image_tree, "--recursive",
                  "--list"])
        assert r.returncode == 0, r.stderr
        lines = open(prefix + ".lst").read().strip().splitlines()
        assert len(lines) == 6
        labels = {l.split("\t")[1] for l in lines}
        assert labels == {"0", "1"}

    def test_pack_and_read_back(self, image_tree, tmp_path):
        prefix = str(tmp_path / "d")
        r = _run(["tools/im2rec.py", prefix, image_tree, "--recursive",
                  "--resize", "16"])
        assert r.returncode == 0, r.stderr
        from mxnet_tpu.gluon.data.vision import ImageRecordDataset
        ds = ImageRecordDataset(prefix + ".rec")
        assert len(ds) == 6
        img, label = ds[0]
        assert min(img.shape[:2]) == 16
        assert label in (0.0, 1.0)


class TestParseLog:
    def test_parses_metrics(self, tmp_path):
        log = tmp_path / "t.log"
        log.write_text(
            "INFO:root:Epoch[0] Train-accuracy=0.5\n"
            "INFO:root:Epoch[0] Time cost=10.1\n"
            "INFO:root:Epoch[1] Train-accuracy=0.8\n"
            "INFO:root:Epoch[1] Validation-accuracy=0.75\n")
        r = _run(["tools/parse_log.py", str(log), "--format", "csv"])
        assert r.returncode == 0
        assert "train-accuracy" in r.stdout
        assert "0.75" in r.stdout

    def test_empty_log_errors(self, tmp_path):
        log = tmp_path / "e.log"
        log.write_text("nothing here\n")
        assert _run(["tools/parse_log.py", str(log)]).returncode == 1


class TestDiagnose:
    def test_runs(self):
        r = _run(["tools/diagnose.py"])
        assert r.returncode == 0
        assert "mxnet_tpu" in r.stdout
        assert "features" in r.stdout


class TestBandwidth:
    def test_kvstore_bandwidth(self):
        r = _run(["tools/bandwidth/measure.py", "--sizes", "65536",
                  "--repeats", "2"], timeout=180)
        assert r.returncode == 0, r.stderr[-500:]
        assert "GB/s" in r.stdout


class TestOpperf:
    def test_subset_runs(self):
        r = _run(["benchmark/opperf/opperf.py", "--ops", "dot", "relu",
                  "--runs", "2"], timeout=240)
        assert r.returncode == 0, r.stderr[-500:]
        assert "dot" in r.stdout and "relu" in r.stdout

    def test_python_api(self):
        from benchmark.opperf.opperf import run_op_benchmark
        res = run_op_benchmark(["sigmoid"], warmup=1, runs=2)
        assert res[0]["op"] == "sigmoid"
        assert "jit_ms" in res[0]
