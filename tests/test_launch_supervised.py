"""Supervised launch chaos gauntlet (ISSUE 13): ``tools/launch.py`` as
a real supervisor — a dead or wedged rank produces a clean nonzero
exit on ALL ranks within the timeout, never a hang.

Acceptance bar (a): killing one of 3 launched ranks tears the job down
with a diagnostic naming the failed rank; the supervisor forwards the
first failing rank's exit code (128+signal for signal deaths) and no
sibling survives.  The fast tier-1 arms use a no-import script (exit
code forwarding) and the fault-injected SIGKILL (the ISSUE's smoke);
the heartbeat-silence matrix arm is slow.
"""
import os
import subprocess
import sys
import time

import pytest

_LAUNCH = [sys.executable, "tools/launch.py"]


def _run(args, timeout):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    env.pop("MXNET_FAULT_INJECT", None)
    t0 = time.monotonic()
    r = subprocess.run(args, capture_output=True, text=True,
                       cwd="/root/repo", env=env, timeout=timeout)
    return r, time.monotonic() - t0


class TestSupervisedLaunch:
    def test_failing_rank_exit_code_forwarded_fast(self, tmp_path):
        """No-mxnet script: rank 1 exits 7; the siblings (parked in a
        long sleep) are killed, the supervisor exits 7 — the first
        failing rank's code, not a swallowed generic 1 — and the whole
        teardown is fast (satellite: _kill_all hardening)."""
        script = tmp_path / "rank_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "print('RANK%s_UP' % rank, flush=True)\n"
            "if rank == '1':\n"
            "    time.sleep(0.3)\n"
            "    sys.exit(7)\n"
            "time.sleep(120)\n"
            "print('RANK%s_DONE' % rank, flush=True)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--kill-grace", "1",
                                sys.executable, str(script)],
                     timeout=60)
        assert r.returncode == 7, (r.returncode, r.stderr[-800:])
        assert "rank 1" in r.stderr and "exited with code 7" in r.stderr
        assert "RANK0_UP" in r.stdout and "RANK2_UP" in r.stdout
        assert "RANK0_DONE" not in r.stdout     # killed, not finished
        assert dt < 30, f"teardown took {dt:.1f}s"

    def test_fault_injected_kill_tears_job_down(self, tmp_path):
        """THE tier-1 chaos smoke: 3 ranks beating via the library
        heartbeat, rank 1 fault-injected to die with SIGKILL mid-run
        (MXNET_FAULT_INJECT=launch.heartbeat:kill:2).  The supervisor
        must exit 137 (128+SIGKILL) with a diagnostic naming rank 1,
        and no rank may hang."""
        script = tmp_path / "beat_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "if rank == '1':\n"
            "    os.environ['MXNET_FAULT_INJECT'] = "
            "'launch.heartbeat:kill:2'\n"
            "from mxnet_tpu.parallel.heartbeat import start_heartbeat\n"
            "start_heartbeat()\n"
            "print('RANK%s_BEATING' % rank, flush=True)\n"
            "time.sleep(120)\n"
            "print('RANK%s_DONE' % rank, flush=True)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--heartbeat-interval",
                                "0.2", "--heartbeat-timeout", "60",
                                "--kill-grace", "2",
                                sys.executable, str(script)],
                     timeout=240)
        assert r.returncode == 137, (r.returncode, r.stderr[-800:])
        assert "rank 1" in r.stderr
        assert "signal 9" in r.stderr
        assert "RANK1_BEATING" in r.stdout      # it was up, then died
        assert "_DONE" not in r.stdout          # nobody ran to the end
        assert dt < 180, f"no-hang bar: {dt:.1f}s"

    @pytest.mark.slow
    def test_heartbeat_silence_detected(self, tmp_path):
        """Full-matrix arm: a rank that stops beating (fault-injected
        hang in the beat loop) without dying is declared wedged after
        --heartbeat-timeout and the job tears down nonzero — the
        'silent rank' half of dead-worker detection."""
        script = tmp_path / "wedge_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "if rank == '2':\n"
            "    os.environ['MXNET_FAULT_INJECT'] = "
            "'launch.heartbeat:hang:3:120'\n"
            "from mxnet_tpu.parallel.heartbeat import start_heartbeat\n"
            "start_heartbeat()\n"
            "print('RANK%s_BEATING' % rank, flush=True)\n"
            "time.sleep(120)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--heartbeat-interval",
                                "0.2", "--heartbeat-timeout", "3",
                                "--kill-grace", "1",
                                sys.executable, str(script)],
                     timeout=240)
        assert r.returncode != 0
        assert "rank 2" in r.stderr
        assert "heartbeat silent" in r.stderr
        assert dt < 180, f"no-hang bar: {dt:.1f}s"

    def test_interval_incompatible_with_timeout_rejected(self):
        """Post-review regression: an interval the timeout cannot
        tolerate (healthy rank would be declared silent) is a CLI
        error up front, not a job-killing misconfiguration."""
        r, _dt = _run(_LAUNCH + ["-n", "1", "--heartbeat-interval",
                                 "120", "--heartbeat-timeout", "60",
                                 "python", "-c", "pass"], timeout=30)
        assert r.returncode != 0
        assert "must exceed" in r.stderr

    def test_clean_three_rank_run_still_exits_zero(self, tmp_path):
        """Supervision must not break the happy path: 3 ranks exiting
        zero -> supervisor exits zero with all output passed through."""
        script = tmp_path / "ok_prog.py"
        script.write_text(
            "import os\n"
            "print('RANK%s_OK' % os.environ['MXNET_WORKER_ID'],"
            " flush=True)\n")
        r, _dt = _run(_LAUNCH + ["-n", "3", sys.executable,
                                 str(script)], timeout=60)
        assert r.returncode == 0, r.stderr[-500:]
        for i in range(3):
            assert f"RANK{i}_OK" in r.stdout
