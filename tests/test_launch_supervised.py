"""Supervised launch chaos gauntlet (ISSUES 13 + 15): ``tools/launch.py``
as a real supervisor — a dead or wedged rank produces a clean nonzero
exit on ALL ranks within the timeout (never a hang), and with
``--restarts`` the job RECOVERS: the pod is torn down, re-spawned, and
every rank auto-resumes from the newest complete checkpoint, bit-exact.

Acceptance bars: (ISSUE 13) killing one of 3 launched ranks tears the
job down with a diagnostic naming the failed rank and the first failing
rank's exit code forwarded.  (ISSUE 15 chaos parity pin) a training run
SIGKILLed mid-run — once mid-checkpoint-save and once
mid-accumulation-window — and restarted via ``--restarts`` produces
final params/optimizer states numerically identical to an uninterrupted
run; plus the 3-rank restart smoke (rank 1 fault-killed, one restart,
run completes, params equal uninterrupted).  The heartbeat-silence
matrix arms are slow.
"""
import json
import os
import subprocess
import sys
import time

import numpy as onp
import pytest

_LAUNCH = [sys.executable, "tools/launch.py"]
_RESUME_PROG = os.path.join("tests", "fixtures", "resume_train.py")


def _run(args, timeout, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    env.pop("MXNET_FAULT_INJECT", None)
    env.pop("MXNET_CHECKPOINT_DIR", None)
    env.pop("MXNET_RESTART_COUNT", None)
    if extra_env:
        env.update(extra_env)
    t0 = time.monotonic()
    r = subprocess.run(args, capture_output=True, text=True,
                       cwd="/root/repo", env=env, timeout=timeout)
    return r, time.monotonic() - t0


def _assert_npz_equal(path_a, path_b):
    a, b = onp.load(path_a), onp.load(path_b)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        onp.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.fixture(scope="module")
def uninterrupted_out(tmp_path_factory):
    """The uninterrupted-run truth every parity arm compares against:
    one direct (no supervisor, no faults) run of the resume_train
    fixture with its default arguments."""
    base = tmp_path_factory.mktemp("baseline")
    out = str(base / "out.npz")
    r, _ = _run([sys.executable, _RESUME_PROG, "--dir",
                 str(base / "ck"), "--out", out], timeout=180)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    return out


class TestSupervisedLaunch:
    def test_failing_rank_exit_code_forwarded_fast(self, tmp_path):
        """No-mxnet script: rank 1 exits 7; the siblings (parked in a
        long sleep) are killed, the supervisor exits 7 — the first
        failing rank's code, not a swallowed generic 1 — and the whole
        teardown is fast (satellite: _kill_all hardening)."""
        script = tmp_path / "rank_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "print('RANK%s_UP' % rank, flush=True)\n"
            "if rank == '1':\n"
            "    time.sleep(0.3)\n"
            "    sys.exit(7)\n"
            "time.sleep(120)\n"
            "print('RANK%s_DONE' % rank, flush=True)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--kill-grace", "1",
                                sys.executable, str(script)],
                     timeout=60)
        assert r.returncode == 7, (r.returncode, r.stderr[-800:])
        assert "rank 1" in r.stderr and "exited with code 7" in r.stderr
        assert "RANK0_UP" in r.stdout and "RANK2_UP" in r.stdout
        assert "RANK0_DONE" not in r.stdout     # killed, not finished
        assert dt < 30, f"teardown took {dt:.1f}s"

    def test_fault_injected_kill_tears_job_down(self, tmp_path):
        """THE tier-1 chaos smoke: 3 ranks beating via the library
        heartbeat, rank 1 fault-injected to die with SIGKILL mid-run
        (MXNET_FAULT_INJECT=launch.heartbeat:kill:2).  The supervisor
        must exit 137 (128+SIGKILL) with a diagnostic naming rank 1,
        and no rank may hang."""
        script = tmp_path / "beat_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "if rank == '1':\n"
            "    os.environ['MXNET_FAULT_INJECT'] = "
            "'launch.heartbeat:kill:2'\n"
            "from mxnet_tpu.parallel.heartbeat import start_heartbeat\n"
            "start_heartbeat()\n"
            "print('RANK%s_BEATING' % rank, flush=True)\n"
            "time.sleep(120)\n"
            "print('RANK%s_DONE' % rank, flush=True)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--heartbeat-interval",
                                "0.2", "--heartbeat-timeout", "60",
                                "--kill-grace", "2",
                                sys.executable, str(script)],
                     timeout=240)
        assert r.returncode == 137, (r.returncode, r.stderr[-800:])
        assert "rank 1" in r.stderr
        assert "signal 9" in r.stderr
        assert "RANK1_BEATING" in r.stdout      # it was up, then died
        assert "_DONE" not in r.stdout          # nobody ran to the end
        assert dt < 180, f"no-hang bar: {dt:.1f}s"

    @pytest.mark.slow
    def test_heartbeat_silence_detected(self, tmp_path):
        """Full-matrix arm: a rank that stops beating (fault-injected
        hang in the beat loop) without dying is declared wedged after
        --heartbeat-timeout and the job tears down nonzero — the
        'silent rank' half of dead-worker detection."""
        script = tmp_path / "wedge_prog.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "rank = os.environ['MXNET_WORKER_ID']\n"
            "if rank == '2':\n"
            "    os.environ['MXNET_FAULT_INJECT'] = "
            "'launch.heartbeat:hang:3:120'\n"
            "from mxnet_tpu.parallel.heartbeat import start_heartbeat\n"
            "start_heartbeat()\n"
            "print('RANK%s_BEATING' % rank, flush=True)\n"
            "time.sleep(120)\n")
        r, dt = _run(_LAUNCH + ["-n", "3", "--heartbeat-interval",
                                "0.2", "--heartbeat-timeout", "3",
                                "--kill-grace", "1",
                                sys.executable, str(script)],
                     timeout=240)
        assert r.returncode != 0
        assert "rank 2" in r.stderr
        assert "heartbeat silent" in r.stderr
        assert dt < 180, f"no-hang bar: {dt:.1f}s"

    def test_interval_incompatible_with_timeout_rejected(self):
        """Post-review regression: an interval the timeout cannot
        tolerate (healthy rank would be declared silent) is a CLI
        error up front, not a job-killing misconfiguration."""
        r, _dt = _run(_LAUNCH + ["-n", "1", "--heartbeat-interval",
                                 "120", "--heartbeat-timeout", "60",
                                 "python", "-c", "pass"], timeout=30)
        assert r.returncode != 0
        assert "must exceed" in r.stderr

    def test_restarts_rejected_in_ssh_mode(self, tmp_path):
        hosts = tmp_path / "hosts"
        hosts.write_text("localhost\n")
        r, _ = _run(_LAUNCH + ["-n", "1", "--launcher", "ssh", "-H",
                               str(hosts), "--restarts", "1",
                               "python", "-c", "pass"], timeout=30)
        assert r.returncode != 0
        assert "local mode only" in r.stderr

    def test_clean_three_rank_run_still_exits_zero(self, tmp_path):
        """Supervision must not break the happy path: 3 ranks exiting
        zero -> supervisor exits zero with all output passed through."""
        script = tmp_path / "ok_prog.py"
        script.write_text(
            "import os\n"
            "print('RANK%s_OK' % os.environ['MXNET_WORKER_ID'],"
            " flush=True)\n")
        r, _dt = _run(_LAUNCH + ["-n", "3", sys.executable,
                                 str(script)], timeout=60)
        assert r.returncode == 0, r.stderr[-500:]
        for i in range(3):
            assert f"RANK{i}_OK" in r.stdout


class TestSupervisedRestart:
    """ISSUE 15: the recovery half — ``--restarts`` turns worker_dead
    into a pod restart with checkpoint auto-resume."""

    def test_chaos_parity_two_kills_bit_exact(self, tmp_path,
                                              uninterrupted_out):
        """THE chaos parity pin: one supervised run SIGKILLed twice —
        generation 0 mid-checkpoint-save (``checkpoint.save:kill:4``
        fires after the temp write, before the commit rename) and
        generation 1 mid-accumulation-window (``data.next:kill:3`` with
        update_interval=2 kills after an odd step) — then restarted by
        the supervisor each time.  The run must complete with exit 0,
        resume from the newest COMPLETE checkpoint each time (the
        interrupted save is swept with a checkpoint_corrupt event), and
        the final params + optimizer states must be numerically
        IDENTICAL to the uninterrupted run."""
        out = str(tmp_path / "out.npz")
        rec = str(tmp_path / "rec.jsonl")
        r, dt = _run(
            _LAUNCH + ["-n", "1", "--restarts", "2",
                       "--restart-backoff", "0.1", "--kill-grace", "1",
                       "--checkpoint-dir", str(tmp_path / "ck"),
                       sys.executable, _RESUME_PROG, "--out", out,
                       "--fault", "0=checkpoint.save:kill:4",
                       "--fault", "1=data.next:kill:3"],
            timeout=300, extra_env={"MXNET_TELEMETRY_JSONL": rec})
        assert r.returncode == 0, (r.returncode, r.stderr[-1200:])
        assert "restarting the pod" in r.stderr
        assert r.stderr.count("died_signal") >= 2
        _assert_npz_equal(uninterrupted_out, out)
        # the recording carries the whole recovery story
        events = [json.loads(ln) for ln in open(rec) if ln.strip()]
        kinds = [e.get("kind") for e in events]
        assert kinds.count("pod_restart") == 2
        assert "checkpoint_corrupt" in kinds   # the aborted tmp save
        assert "checkpoint_saved" in kinds
        # and telemetry_report renders/parses it (restarts section)
        rr, _ = _run([sys.executable, "tools/telemetry_report.py", rec,
                      "--json"], timeout=60)
        assert rr.returncode == 0, rr.stderr[-500:]
        summary = json.loads(rr.stdout)
        assert summary["restarts"][0]["restarts"] == 2
        assert dt < 240, f"no-hang bar: {dt:.1f}s"

    def test_three_rank_restart_smoke(self, tmp_path,
                                      uninterrupted_out):
        """Satellite: 3-rank pod, rank 1 fault-killed mid-run, ONE
        supervised restart, the whole run completes, and the final
        params equal an uninterrupted run (every rank trains the same
        deterministic program and resumes from its own per-rank
        checkpoint dir)."""
        outs = [str(tmp_path / f"out{r}.npz") for r in range(3)]
        r, dt = _run(
            _LAUNCH + ["-n", "3", "--restarts", "1",
                       "--restart-backoff", "0.1",
                       "--heartbeat-interval", "0.2",
                       "--heartbeat-timeout", "60",
                       "--kill-grace", "1",
                       "--checkpoint-dir", str(tmp_path / "ck"),
                       sys.executable, _RESUME_PROG,
                       "--out", str(tmp_path / "outRANK.npz"),
                       "--out-per-rank",
                       "--fault", "0=launch.heartbeat:kill:3",
                       "--fault-rank", "1"],
            timeout=300)
        assert r.returncode == 0, (r.returncode, r.stderr[-1200:])
        assert "rank 1" in r.stderr and "restarting the pod" in r.stderr
        for out in outs:
            assert os.path.exists(out), (out, r.stdout[-800:])
        # rank 1 (the killed one) — and its siblings, torn down by the
        # supervisor mid-flight — all land bit-exact on the truth
        for out in outs:
            _assert_npz_equal(uninterrupted_out, out)
        assert dt < 240, f"no-hang bar: {dt:.1f}s"

    def test_restart_budget_exhausted_per_distinct_failure(
            self, tmp_path):
        """A rank flapping the SAME way exhausts its (rank, why) budget
        and the job fails with that rank's code — restart storms are
        bounded."""
        script = tmp_path / "always7.py"
        script.write_text("import sys; sys.exit(7)\n")
        r, dt = _run(_LAUNCH + ["-n", "1", "--restarts", "1",
                                "--restart-backoff", "0.1",
                                sys.executable, str(script)],
                     timeout=60)
        assert r.returncode == 7
        assert "restarting the pod" in r.stderr          # one restart
        assert "restart budget exhausted" in r.stderr    # then stop
        assert dt < 30

    @pytest.mark.slow
    def test_heartbeat_silent_rank_restarts_and_completes(
            self, tmp_path):
        """Matrix arm: a rank whose heartbeat goes SILENT (fault-hung
        beat loop, process alive) is declared wedged, the pod is torn
        down and restarted once, and the longer run completes clean —
        heartbeat-silence and restart composed end to end."""
        out = str(tmp_path / "outRANK.npz")
        r, dt = _run(
            _LAUNCH + ["-n", "3", "--restarts", "1",
                       "--restart-backoff", "0.1",
                       "--heartbeat-interval", "0.2",
                       "--heartbeat-timeout", "2",
                       "--kill-grace", "1",
                       "--checkpoint-dir", str(tmp_path / "ck"),
                       sys.executable, _RESUME_PROG,
                       "--steps", "400", "--out", out,
                       "--out-per-rank",
                       "--fault", "0=launch.heartbeat:hang:2:600",
                       "--fault-rank", "2"],
            timeout=420)
        assert r.returncode == 0, (r.returncode, r.stderr[-1200:])
        assert "heartbeat silent" in r.stderr
        assert "restarting the pod" in r.stderr
        for rank in range(3):
            assert os.path.exists(str(tmp_path / f"out{rank}.npz"))
        assert dt < 360, f"no-hang bar: {dt:.1f}s"
