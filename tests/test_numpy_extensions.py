"""mx.np breadth extensions (round-3): golden tests vs host numpy."""
import numpy as onp
import pytest

import mxnet_tpu as mx
np = mx.np


def _r(*s):
    return onp.random.RandomState(0).randn(*s).astype("float32")


class TestAliases:
    def test_numpy2_names(self):
        x = _r(5)
        onp.testing.assert_allclose(np.acos(np.array(x * 0.1)).asnumpy(),
                                    onp.arccos(x * 0.1), rtol=1e-5)
        onp.testing.assert_allclose(
            np.atan2(np.array(x), np.array(x + 1)).asnumpy(),
            onp.arctan2(x, x + 1), rtol=1e-5)
        onp.testing.assert_allclose(
            np.pow(np.array(abs(x)), np.array(2.0)).asnumpy(),
            onp.abs(x) ** 2, rtol=1e-5)
        onp.testing.assert_allclose(
            np.permute_dims(np.array(_r(2, 3, 4)), (2, 0, 1)).shape,
            (4, 2, 3))

    def test_concat(self):
        a, b = _r(2, 3), _r(1, 3)
        out = np.concat([np.array(a), np.array(b)], axis=0)
        onp.testing.assert_allclose(out.asnumpy(),
                                    onp.concatenate([a, b]), rtol=1e-6)


class TestStructured:
    def test_cov_vander_trapezoid(self):
        x = _r(3, 8)
        onp.testing.assert_allclose(np.cov(np.array(x)).asnumpy(),
                                    onp.cov(x), rtol=1e-4, atol=1e-5)
        v = _r(4)
        onp.testing.assert_allclose(np.vander(np.array(v)).asnumpy(),
                                    onp.vander(v), rtol=1e-4)
        y = _r(9)
        onp.testing.assert_allclose(
            float(np.trapezoid(np.array(y)).asnumpy()),
            onp.trapezoid(y) if hasattr(onp, "trapezoid")
            else onp.trapz(y), rtol=1e-5)

    def test_partition_lexsort(self):
        x = _r(10)
        out = np.partition(np.array(x), 4).asnumpy()
        assert (out[:4] <= out[4]).all() and (out[5:] >= out[4]).all()
        a = onp.asarray([1, 1, 2, 2], "float32")
        b = onp.asarray([3.0, 1.0, 2.0, 0.0], "float32")
        idx = np.lexsort([np.array(b), np.array(a)]).asnumpy()
        onp.testing.assert_array_equal(idx, onp.lexsort([b, a]))

    def test_select_choose_compress(self):
        x = _r(6)
        out = np.select([np.array(x > 0), np.array(x <= 0)],
                        [np.array(x), np.array(-x)])
        onp.testing.assert_allclose(out.asnumpy(), onp.abs(x), rtol=1e-6)
        idx = onp.asarray([0, 1, 0], "int32")
        out = np.choose(np.array(idx),
                        [np.array(_r(3)), np.array(_r(3) + 10)])
        assert out.shape == (3,)
        out = np.compress(onp.asarray([True, False, True]),
                          np.array(_r(3, 2)), axis=0)
        assert out.shape == (2, 2)

    def test_put_along_axis_fill_diagonal(self):
        a = np.array(onp.zeros((3, 3), "float32"))
        idx = np.array(onp.asarray([[0], [1], [2]], "int64"))
        vals = np.array(onp.ones((3, 1), "float32"))
        out = np.put_along_axis(a, idx, vals, 1).asnumpy()
        onp.testing.assert_allclose(out, onp.eye(3), rtol=1e-6)
        out = np.fill_diagonal(a, 5.0).asnumpy()
        onp.testing.assert_allclose(out, 5 * onp.eye(3), rtol=1e-6)

    def test_divmod_modf_frexp(self):
        x = onp.asarray([5.5, -2.25], "float32")
        q, r = np.divmod(np.array(x), np.array(2.0))
        onp.testing.assert_allclose(q.asnumpy(), [2, -2])
        onp.testing.assert_allclose(r.asnumpy(), [1.5, 1.75])
        frac, whole = np.modf(np.array(x))
        onp.testing.assert_allclose(frac.asnumpy(), [0.5, -0.25])
        m, e = np.frexp(np.array(onp.asarray([8.0], "float32")))
        assert float(m.asnumpy()) == 0.5 and int(e.asnumpy()) == 4

    def test_unwrap_apply_along_axis(self):
        ph = onp.asarray([0, 1, 2, -2.5, -1.0], "float32") * onp.pi
        onp.testing.assert_allclose(np.unwrap(np.array(ph)).asnumpy(),
                                    onp.unwrap(ph), rtol=1e-5)
        import jax.numpy as jnp
        out = np.apply_along_axis(lambda r: r.sum(), 1,
                                  np.array(_r(3, 4)))
        assert out.shape == (3,)

    def test_block_geomspace(self):
        a = np.array(onp.ones((2, 2), "float32"))
        out = np.block([[a, a], [a, a]])
        assert out.shape == (4, 4)
        g = np.geomspace(1, 1000, 4).asnumpy()
        onp.testing.assert_allclose(g, [1, 10, 100, 1000], rtol=1e-4)


class TestSetOps:
    def test_isin_and_friends(self):
        a = onp.asarray([1, 2, 3, 4], "int32")
        b = onp.asarray([2, 4, 6], "int32")
        onp.testing.assert_array_equal(
            np.isin(np.array(a), np.array(b)).asnumpy(),
            [False, True, False, True])
        onp.testing.assert_array_equal(
            np.intersect1d(np.array(a), np.array(b)).asnumpy(), [2, 4])
        onp.testing.assert_array_equal(
            np.union1d(np.array(a), np.array(b)).asnumpy(),
            [1, 2, 3, 4, 6])
        onp.testing.assert_array_equal(
            np.setdiff1d(np.array(a), np.array(b)).asnumpy(), [1, 3])
        onp.testing.assert_array_equal(
            np.setxor1d(np.array(a), np.array(b)).asnumpy(), [1, 3, 6])

    def test_unique_family(self):
        a = onp.asarray([3, 1, 3, 2, 1], "int32")
        onp.testing.assert_array_equal(
            np.unique_values(np.array(a)).asnumpy(), [1, 2, 3])
        vals, counts = np.unique_counts(np.array(a))
        onp.testing.assert_array_equal(counts.asnumpy(), [2, 1, 2])


class TestIntrospection:
    def test_dtype_helpers(self):
        assert np.finfo("float32").eps == onp.finfo("float32").eps
        assert np.iinfo("int32").max == 2**31 - 1
        assert np.issubdtype(onp.float32, onp.floating)
        assert np.promote_types("float32", "float64") == onp.float64
        assert np.broadcast_shapes((2, 1), (1, 3)) == (2, 3)
        assert np.isscalar(3.0) and not np.isscalar([3.0])

    def test_isreal_obj(self):
        x = np.array(_r(3))
        assert np.isrealobj(x) and not np.iscomplexobj(x)
        onp.testing.assert_array_equal(np.isreal(x).asnumpy(),
                                       [True, True, True])

    def test_array_equiv_astype(self):
        a = np.array(onp.ones((2, 2), "float32"))
        assert np.array_equiv(a, np.array(onp.ones((2, 2), "float32")))
        assert np.astype(a, "int32").asnumpy().dtype == onp.int32
