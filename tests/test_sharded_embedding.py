"""Sharded embedding tables at scale (SURVEY.md §3.3 "Sparse / large
embedding DP" row; VERDICT r1 missing item 5): the reference's
``row_sparse`` embedding + ``row_sparse_pull(row_ids)`` maps to a
GSPMD row-sharded dense table + gather — demonstrated here on the
8-device mesh with training parity against the replicated run."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.parallel import P, ShardingRules


VOCAB, DIM = 64 * 1024, 32


class _EmbedNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = gluon.nn.Embedding(VOCAB, DIM)
            self.head = gluon.nn.Dense(4, flatten=False, in_units=DIM)

    def hybrid_forward(self, F, x):
        return self.head(self.embed(x))


def _mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    return parallel.make_mesh({"dp": 1, "tp": 8})


def _rules():
    return ShardingRules([(r".*embedding\d*_weight", P("tp", None))])


class TestShardedEmbedding:
    def test_table_is_row_sharded_across_devices(self):
        mesh = _mesh()
        mx.random.seed(0)
        net = _EmbedNet()
        net.initialize(mx.init.Normal(0.02))
        parallel.shard_block(net, mesh, _rules())
        w = net.embed.weight._data._data
        shards = w.addressable_shards
        assert len(shards) == 8
        # each device holds 1/8 of the rows — the EP memory win
        assert shards[0].data.shape == (VOCAB // 8, DIM)
        ids = {s.device.id for s in shards}
        assert len(ids) == 8

    def test_training_parity_with_replicated(self):
        mesh = _mesh()
        rng = onp.random.RandomState(0)
        toks = rng.randint(0, VOCAB, (4, 8, 16))
        labs = rng.randint(0, 4, (4, 8, 16)).astype(onp.float32)

        def run(rules):
            mx.random.seed(0)
            net = _EmbedNet()
            net.initialize(mx.init.Normal(0.02))
            tr = parallel.SPMDTrainer(
                net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                {"learning_rate": 0.5}, mesh=mesh, rules=rules)
            losses = tr.run_steps(mx.nd.array(toks), mx.nd.array(labs))
            return (onp.asarray(losses.asnumpy()),
                    net.embed.weight.data().asnumpy())

        l_sharded, w_sharded = run(_rules())
        l_repl, w_repl = run(None)
        onp.testing.assert_allclose(l_sharded, l_repl, rtol=1e-5,
                                    atol=1e-6)
        onp.testing.assert_allclose(w_sharded, w_repl, rtol=1e-4,
                                    atol=1e-6)
        # training touched only the gathered rows (sparse-update reality)
        touched = onp.unique(toks)
        untouched = onp.setdiff1d(onp.arange(512), touched)[:16]
        mx.random.seed(0)
        ref = _EmbedNet()
        ref.initialize(mx.init.Normal(0.02))
        w0 = ref.embed.weight.data().asnumpy()
        onp.testing.assert_allclose(w_sharded[untouched], w0[untouched],
                                    rtol=1e-6)

    def test_row_pull_gather_on_sharded_table(self):
        """row_sparse_pull(row_ids) analog: gather specific rows from the
        sharded table without materializing it."""
        mesh = _mesh()
        mx.random.seed(0)
        net = _EmbedNet()
        net.initialize(mx.init.Normal(0.02))
        parallel.shard_block(net, mesh, _rules())
        full = net.embed.weight.data().asnumpy()
        row_ids = onp.array([0, 13, 8191, VOCAB - 1])
        rows = mx.nd.take(net.embed.weight.data(),
                          mx.nd.array(row_ids.astype(onp.int32)))
        onp.testing.assert_allclose(rows.asnumpy(), full[row_ids],
                                    rtol=1e-6)

    def test_kvstore_row_sparse_pull_api(self):
        """The legacy kvstore row_sparse_pull surface works against the
        same table semantics (reference PullRowSparse)."""
        kv = mx.kv.create("device")
        table = mx.nd.array(onp.random.RandomState(0)
                            .rand(64, 4).astype(onp.float32))
        kv.init("emb", table)
        out = mx.nd.zeros((64, 4))
        kv.row_sparse_pull("emb", out=out,
                           row_ids=mx.nd.array(onp.array([3, 9])))
        got = out.asnumpy()
        onp.testing.assert_allclose(got[3], table.asnumpy()[3], rtol=1e-6)
        assert (got[4] == 0).all()  # un-pulled rows stay zero
