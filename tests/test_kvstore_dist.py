"""Distributed kvstore machinery tests: gradient compression, dist kinds,
launcher protocol (reference tests/nightly/dist_sync_kvstore.py coverage;
SURVEY.md §3.1 KVStore row, §4.4).

Real multi-process DCN runs need multiple hosts; here we verify the
single-process degradation (dist == local semantics) and the compression
math, mirroring the reference's localhost nightly pattern.
"""
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore import create
from mxnet_tpu.kvstore.compression import GradientCompression


class TestGradientCompression:
    def test_2bit_quantization_levels(self):
        gc = GradientCompression(threshold=0.5)
        g = onp.array([0.7, -0.7, 0.1, -0.1, 0.5], onp.float32)
        q = onp.asarray(gc.compress("k", mx.nd.array(g)._data))
        onp.testing.assert_allclose(q, [0.5, -0.5, 0.0, 0.0, 0.5])

    def test_error_feedback_accumulates(self):
        """Small gradients must not be lost — the residual carries them
        until they cross the threshold (reference error-feedback)."""
        gc = GradientCompression(threshold=0.5)
        g = mx.nd.array(onp.full(4, 0.2, onp.float32))._data
        total = onp.zeros(4, onp.float32)
        for _ in range(10):
            total += onp.asarray(gc.compress("k", g))
        # 10 * 0.2 = 2.0 sent in units of 0.5 → exactly 4 pulses worth ± one
        onp.testing.assert_allclose(total, onp.full(4, 2.0), atol=0.5)

    def test_1bit_signs(self):
        gc = GradientCompression(type="1bit", threshold=0.25)
        q = onp.asarray(gc.compress(
            "k", mx.nd.array(onp.array([3.0, -3.0], onp.float32))._data))
        onp.testing.assert_allclose(q, [0.25, -0.25])

    def test_bad_type_rejected(self):
        with pytest.raises(MXNetError):
            GradientCompression(type="4bit")


class TestDistKVStore:
    def test_dist_sync_single_process_is_local(self):
        kv = create("dist_sync")
        assert kv.num_workers == 1
        kv.init(0, mx.nd.array(onp.zeros(3, onp.float32)))
        out = mx.nd.zeros(3)
        kv.pushpull(0, [mx.nd.ones(3), mx.nd.ones(3)], out=out)
        onp.testing.assert_allclose(out.asnumpy(), onp.full(3, 2.0))

    def test_compression_in_store(self):
        kv = create("dist_sync")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.init("w", mx.nd.zeros(3))
        out = mx.nd.zeros(3)
        kv.pushpull("w", mx.nd.array(onp.array([0.9, -0.9, 0.1],
                                               onp.float32)), out=out)
        onp.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0])

    def test_optimizer_on_server_semantics(self):
        kv = create("dist_sync")
        kv.init(0, mx.nd.ones(2))
        opt = mx.optimizer.create("sgd", learning_rate=0.5)
        kv.set_optimizer(opt)
        kv.push(0, mx.nd.ones(2))  # w <- w - 0.5*1
        out = mx.nd.zeros(2)
        kv.pull(0, out)
        onp.testing.assert_allclose(out.asnumpy(), [0.5, 0.5])


class TestLauncher:
    def test_dry_run_env_protocol(self):
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "3", "--dry-run",
             "python", "train.py"],
            capture_output=True, text=True, cwd="/root/repo")
        lines = [l for l in out.stdout.splitlines() if l.startswith("[rank")]
        assert len(lines) == 3
        assert "MXNET_NUM_WORKERS=3" in lines[0]
        assert "MXNET_WORKER_ID=2" in lines[2]
        assert "MXNET_COORDINATOR=127.0.0.1:" in lines[0]
        assert "DMLC_ROLE=worker" in lines[0]

    def test_local_launch_runs_processes(self):
        code = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "2", "--launcher",
             "local", sys.executable, "-c",
             "import os; assert os.environ['MXNET_NUM_WORKERS']=='2'; "
             "print('RANK%s' % os.environ['MXNET_WORKER_ID'], flush=True)"],
            capture_output=True, text=True, cwd="/root/repo")
        assert code.returncode == 0, code.stderr
        assert "RANK0" in code.stdout and "RANK1" in code.stdout

    def test_missing_command_errors(self):
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "1"],
            capture_output=True, text=True, cwd="/root/repo")
        assert out.returncode != 0

    def test_real_two_process_allreduce(self, tmp_path):
        """The reference's nightly localhost multi-process pattern
        (SURVEY.md §4 test strategy): two processes join via the launcher
        and pushpull must sum across them."""
        script = tmp_path / "dist_prog.py"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import mxnet_tpu as mx\n"
            "from mxnet_tpu.parallel import init_distributed\n"
            "init_distributed()\n"
            "import jax, numpy as onp\n"
            "rank = jax.process_index()\n"
            "kv = mx.kv.create('dist_sync')\n"
            "kv.init(0, mx.nd.zeros(4))\n"
            "out = mx.nd.zeros(4)\n"
            "kv.pushpull(0, mx.nd.array(onp.full(4, float(rank + 1),\n"
            "                                    onp.float32)), out=out)\n"
            "assert float(out.asnumpy()[0]) == 3.0, out.asnumpy()\n"
            "kv.barrier()\n"
            "print('RANK%d_OK' % rank, flush=True)\n")
        import os
        env = dict(os.environ, PYTHONPATH="/root/repo")
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "2", "--launcher",
             "local", sys.executable, str(script)],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=180)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "RANK0_OK" in out.stdout and "RANK1_OK" in out.stdout

    def test_real_two_process_spmd_train_step(self, tmp_path):
        """A GSPMD train step over a TWO-PROCESS mesh (DCN axis on
        localhost, SURVEY.md §7 hard-part 7 / VERDICT r2 item 5): the dp
        axis spans processes, grads are reduced by the compiler across
        them, both ranks must see the identical finite loss."""
        script = tmp_path / "spmd_prog.py"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import mxnet_tpu as mx\n"
            "from mxnet_tpu.parallel import init_distributed\n"
            "init_distributed()\n"
            "import jax, numpy as onp\n"
            "from mxnet_tpu import gluon, parallel\n"
            "assert jax.process_count() == 2, jax.process_count()\n"
            "assert len(jax.devices()) == 2  # 1 local x 2 procs\n"
            "mx.random.seed(0)\n"
            "net = gluon.nn.HybridSequential()\n"
            "net.add(gluon.nn.Dense(16, activation='relu', in_units=8))\n"
            "net.add(gluon.nn.Dense(4, in_units=16))\n"
            "net.initialize(mx.init.Xavier())\n"
            "mesh = parallel.make_mesh({'dp': 2})\n"
            "tr = parallel.SPMDTrainer(net,\n"
            "    gluon.loss.SoftmaxCrossEntropyLoss(), 'sgd',\n"
            "    {'learning_rate': 0.1}, mesh=mesh)\n"
            "rng = onp.random.RandomState(0)\n"
            "x = rng.randn(8, 8).astype('float32')\n"
            "y = rng.randint(0, 4, 8).astype('float32')\n"
            "losses = [float(onp.asarray(\n"
            "    tr.step(mx.nd.array(x), mx.nd.array(y)).asnumpy())\n"
            "    .reshape(())) for _ in range(3)]\n"
            "assert all(onp.isfinite(l) for l in losses), losses\n"
            "assert losses[-1] < losses[0], losses  # actually training\n"
            "print('RANK%d_SPMD_OK loss=%.5f' % (jax.process_index(),\n"
            "                                    losses[-1]), flush=True)\n")
        import os
        env = dict(os.environ, PYTHONPATH="/root/repo")
        env.pop("XLA_FLAGS", None)
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "2", "--launcher",
             "local", sys.executable, str(script)],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=300)
        assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
        assert "RANK0_SPMD_OK" in out.stdout and \
            "RANK1_SPMD_OK" in out.stdout
        import re
        vals = {m.group(1) for m in
                re.finditer(r"SPMD_OK loss=([\d.]+)", out.stdout)}
        assert len(vals) == 1, f"ranks disagree: {vals}"

    def test_real_three_process_nightly_shape(self, tmp_path):
        """The reference's nightly harness shape (SURVEY.md §7,
        ``tests/nightly/dist_sync_kvstore.py``): THREE workers in one run
        asserting (a) sync semantics — every worker computes the identical
        allreduced value and a second wave sees the first wave's state,
        (b) 2-bit compression with error feedback ACROSS processes —
        sub-threshold gradients are not lost, they drain through the
        residual over repeated pushes on every rank, and (c) row_sparse
        pulls of a server-updated weight return exactly the touched rows
        on all ranks.  One harness, three workers, like the reference
        (its 3 server processes collapse into the XLA collective — the
        'server' is the compiled AllReduce; PARITY.md KVStore row)."""
        script = tmp_path / "nightly_prog.py"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import mxnet_tpu as mx\n"
            "from mxnet_tpu.parallel import init_distributed\n"
            "init_distributed()\n"
            "import jax, numpy as onp\n"
            "rank = jax.process_index()\n"
            "N = jax.process_count()\n"
            "assert N == 3, N\n"
            "kv = mx.kv.create('dist_sync')\n"
            "assert kv.num_workers == 3\n"
            "# --- (a) sync semantics: two dependent pushpull waves ----\n"
            "kv.init('w', mx.nd.zeros((4, 3)))\n"
            "kv.pushpull('w', mx.nd.full((4, 3), float(rank + 1)))\n"
            "got = mx.nd.zeros((4, 3))\n"
            "kv.pull('w', out=got)\n"
            "onp.testing.assert_allclose(got.asnumpy(),\n"
            "                            onp.full((4, 3), 6.0))\n"
            "kv.barrier()\n"
            "# second wave ACCUMULATES onto the stored key (push with no\n"
            "# updater adds): 6.0 from wave 1 + allreduced ones = 9.0 —\n"
            "# passes only if wave-1 store state is visible to wave 2\n"
            "kv.push('w', mx.nd.ones((4, 3)))\n"
            "kv.pull('w', out=got)\n"
            "onp.testing.assert_allclose(got.asnumpy(),\n"
            "                            onp.full((4, 3), 9.0))\n"
            "# --- (b) 2-bit compression + error feedback x-process ----\n"
            "kvc = mx.kv.create('dist_sync')\n"
            "kvc.set_gradient_compression({'type': '2bit',\n"
            "                              'threshold': 0.5})\n"
            "kvc.init('g', mx.nd.zeros(6))\n"
            "# rank-dependent sub-threshold grads: 0.2*(rank+1) each push.\n"
            "# Per push each rank wires 0 or +-0.5 pulses; over 10 pushes\n"
            "# the residual drains so every rank's total approaches\n"
            "# 10*0.2*(rank+1), summed across ranks = 12.0 (+- one 0.5\n"
            "# pulse per rank still stuck in residuals)\n"
            "tot = onp.zeros(6, onp.float32)\n"
            "o = mx.nd.zeros(6)\n"
            "for _ in range(10):\n"
            "    kvc.pushpull('g', mx.nd.full((6,), 0.2 * (rank + 1)),\n"
            "                 out=o)\n"
            "    tot += o.asnumpy()\n"
            "onp.testing.assert_allclose(tot, onp.full(6, 12.0), atol=1.5)\n"
            "kvc.barrier()\n"
            "# --- (c) row_sparse pull of a server-updated weight ------\n"
            "kvs = mx.kv.create('dist_sync')\n"
            "kvs.init('emb', mx.nd.zeros((8, 4)))\n"
            "upd = onp.zeros((8, 4), onp.float32)\n"
            "upd[2] = rank + 1.0\n"
            "upd[5] = 10.0 * (rank + 1)\n"
            "kvs.pushpull('emb', mx.nd.array(upd))\n"
            "rout = mx.nd.zeros((8, 4))\n"
            "kvs.row_sparse_pull('emb', out=rout,\n"
            "                    row_ids=mx.nd.array(\n"
            "                        onp.array([2, 5], onp.int64)))\n"
            "want = onp.zeros((8, 4), onp.float32)\n"
            "want[2] = 6.0\n"
            "want[5] = 60.0\n"
            "onp.testing.assert_allclose(rout.asnumpy(), want)\n"
            "# untouched rows must come back ZERO even though the dense\n"
            "# store also holds them (touched-rows-only contract)\n"
            "full = mx.nd.zeros((8, 4))\n"
            "kvs.pull('emb', out=full)\n"
            "assert float(abs(full.asnumpy()).sum()) == \\\n"
            "    float(abs(want).sum())\n"
            "kvs.barrier()\n"
            "print('RANK%d_NIGHTLY_OK' % rank, flush=True)\n")
        import os
        env = dict(os.environ, PYTHONPATH="/root/repo")
        env.pop("XLA_FLAGS", None)
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "3", "--launcher",
             "local", sys.executable, str(script)],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=300)
        assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
        for r in range(3):
            assert f"RANK{r}_NIGHTLY_OK" in out.stdout, out.stdout[-500:]

    def test_two_process_bucketed_pushpull(self, tmp_path):
        """A key-list pushpull on a dist store must coalesce into one
        AllReduce per dtype (bucketing) and still sum correctly across
        processes — including mixed dtypes and an fp misaligned tail."""
        script = tmp_path / "bucket_prog.py"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import mxnet_tpu as mx\n"
            "from mxnet_tpu.parallel import init_distributed\n"
            "init_distributed()\n"
            "import jax, numpy as onp\n"
            "rank = jax.process_index()\n"
            "kv = mx.kv.create('dist_sync')\n"
            "keys = ['a', 'b', 'c']\n"
            "shapes = [(3,), (2, 2), (5,)]\n"
            "dts = ['float32', 'float16', 'float32']\n"
            "for k, s, dt in zip(keys, shapes, dts):\n"
            "    kv.init(k, mx.nd.zeros(s, dtype=dt))\n"
            "vals = [mx.nd.array(onp.full(s, float(rank + 1), dt))\n"
            "        for s, dt in zip(shapes, dts)]\n"
            "outs = [mx.nd.zeros(s, dtype=dt)\n"
            "        for s, dt in zip(shapes, dts)]\n"
            "kv.pushpull(keys, vals, out=outs)\n"
            "for s, dt, o in zip(shapes, dts, outs):\n"
            "    assert str(o.dtype) == dt, (dt, o.dtype)\n"
            "    onp.testing.assert_allclose(\n"
            "        o.asnumpy().astype('float32'), onp.full(s, 3.0))\n"
            "kv.barrier()\n"
            "print('RANK%d_BUCKET_OK' % rank, flush=True)\n")
        import os
        env = dict(os.environ, PYTHONPATH="/root/repo")
        env.pop("XLA_FLAGS", None)
        out = subprocess.run(
            [sys.executable, "tools/launch.py", "-n", "2", "--launcher",
             "local", sys.executable, str(script)],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=300)
        assert out.returncode == 0, (out.stdout[-800:], out.stderr[-2000:])
        assert "RANK0_BUCKET_OK" in out.stdout
        assert "RANK1_BUCKET_OK" in out.stdout
