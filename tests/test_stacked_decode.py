"""Stacked-layer scan decode (models/decoding.py stacked_token +
ops/decode_fused.stack_decode_weights): ONE lax.scan over the layer axis
must reproduce the per-layer unrolled step token-for-token (greedy AND
sampled, GPT and Llama/GQA), collapse the compiled step's HLO op count
under the ROADMAP ceiling, and keep the whole token loop on one
executable.  The perf claims live in benchmark/decode_bench.py and
BASELINE.md."""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx


def _gpt(layers=2, units=32, heads=4, hidden=64, vocab=97, init=0.02,
         max_length=64):
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=vocab, max_length=max_length,
                        num_layers=layers, units=units, num_heads=heads,
                        hidden_size=hidden))
    net.initialize(mx.init.Normal(init))
    return net


def _llama():
    from mxnet_tpu.models import llama_tiny
    mx.random.seed(0)
    net, cfg = llama_tiny()
    net.initialize(mx.init.Normal(0.02))
    return net, cfg


class TestStackedParity:
    def test_gpt_greedy_matches_unrolled_and_full_recompute(self):
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(0).randint(0, 97, (2, 5))
        full = net.generate(prompt, max_new_tokens=12, temperature=0.0)
        st = kv_generate(net, prompt, max_new_tokens=12, temperature=0.0,
                         stacked="on")
        un = kv_generate(net, prompt, max_new_tokens=12, temperature=0.0,
                         stacked="off")
        onp.testing.assert_array_equal(st, un)
        onp.testing.assert_array_equal(st, full)

    def test_gpt_sampled_parity(self):
        """Sampled decode draws through the identical fold_in/categorical
        keys, so stacked and unrolled must emit the same stream."""
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(1).randint(0, 97, (2, 4))
        kw = dict(max_new_tokens=8, temperature=0.7, top_k=5, seed=3)
        onp.testing.assert_array_equal(
            kv_generate(net, prompt, stacked="on", **kw),
            kv_generate(net, prompt, stacked="off", **kw))

    def test_gpt_scan_prefill_parity(self):
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(2).randint(0, 97, (1, 6))
        for kw in (dict(temperature=0.0),
                   dict(temperature=0.8, top_k=4, seed=7)):
            onp.testing.assert_array_equal(
                kv_generate(net, prompt, max_new_tokens=7,
                            prefill="scan", stacked="on", **kw),
                kv_generate(net, prompt, max_new_tokens=7,
                            prefill="scan", stacked="off", **kw))

    def test_llama_gqa_greedy_and_sampled_parity(self):
        """Llama family through the stack: RMSNorm, per-step RoPE,
        grouped-query KV cache (llama_tiny is GQA: KV < H), SwiGLU."""
        from mxnet_tpu.models import kv_generate
        net, cfg = _llama()
        assert cfg.num_kv_heads < cfg.num_heads
        prompt = onp.random.RandomState(6).randint(0, cfg.vocab_size,
                                                   (2, 4))
        full = net.generate(prompt, max_new_tokens=10, temperature=0.0)
        st = kv_generate(net, prompt, max_new_tokens=10, temperature=0.0,
                         stacked="on")
        un = kv_generate(net, prompt, max_new_tokens=10, temperature=0.0,
                         stacked="off")
        onp.testing.assert_array_equal(st, un)
        onp.testing.assert_array_equal(st, full)
        kw = dict(max_new_tokens=6, temperature=0.9, top_k=7, seed=11)
        onp.testing.assert_array_equal(
            kv_generate(net, prompt, stacked="on", **kw),
            kv_generate(net, prompt, stacked="off", **kw))

    def test_weight_update_invalidates_stack(self):
        """The stacked arrays must restack after a weight rebind (the
        pinned-source discipline shared with the Pallas pack and q8
        caches) — and the already-compiled program must pick up the new
        values through its traced weight operands."""
        from mxnet_tpu.models import kv_generate
        net = _gpt(init=0.15)
        prompt = onp.random.RandomState(3).randint(0, 97, (1, 4))
        out1 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, stacked="on")
        w = net.blocks[0].attn.qkv.weight
        w.set_data(mx.nd.from_jax(-w.data()._data))
        out2 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, stacked="on")
        ref2 = kv_generate(net, prompt, max_new_tokens=4,
                           temperature=0.0, stacked="off")
        onp.testing.assert_array_equal(out2, ref2)
        assert (out1 != out2).any()


class TestStackedGating:
    def test_default_mode_is_stacked(self):
        from mxnet_tpu.models import decode_mode
        net = _gpt()
        assert decode_mode(net) == "stacked"
        lnet, _ = _llama()
        assert decode_mode(lnet) == "stacked"

    def test_env_hatch_restores_unrolled(self, monkeypatch):
        from mxnet_tpu.base import MXNetError
        from mxnet_tpu.models import decode_mode, kv_generate
        net = _gpt()
        monkeypatch.setenv("MXNET_STACKED_DECODE", "0")
        assert decode_mode(net) == "unrolled"
        prompt = onp.random.RandomState(4).randint(0, 97, (1, 4))
        out = kv_generate(net, prompt, max_new_tokens=3, temperature=0.0)
        key_modes = {k[-1] for k in net._kv_decode_cache}
        assert key_modes == {"unrolled"}
        # an explicit stacked='on' conflicts with the kill switch
        with pytest.raises(MXNetError, match="MXNET_STACKED_DECODE"):
            kv_generate(net, prompt, max_new_tokens=3, temperature=0.0,
                        stacked="on")
        # hatch off again: same prompt now compiles the stacked program
        monkeypatch.delenv("MXNET_STACKED_DECODE")
        ref = kv_generate(net, prompt, max_new_tokens=3, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_int8_runs_stacked_where_supported(self):
        """The q8 stream rides the stacked scan by default (ROADMAP PR 5
        remainder); the unrolled fallback still covers it when the stack
        gate rejects the model."""
        from mxnet_tpu.models import decode_mode
        net = _gpt()
        assert decode_mode(net, weights="int8") == "stacked"
        assert decode_mode(net, weights="int8", stacked="off") \
            == "unrolled"
        net.blocks[1].ln1._eps = 1e-3          # non-uniform stack
        assert decode_mode(net, weights="int8") == "unrolled"

    def test_fused_requires_explicit_opt_in(self):
        """VERDICT r5: fused='auto' must NOT select the unmeasured
        Pallas megakernel — 'auto' resolves to stacked/unrolled, and
        'on' raises where the TPU gate rejects the config (always on
        CPU without interpret mode)."""
        from mxnet_tpu.base import MXNetError
        from mxnet_tpu.models import decode_mode
        net = _gpt()
        assert decode_mode(net, fused="auto") == "stacked"
        with pytest.raises(MXNetError, match="fused"):
            decode_mode(net, fused="on")
        with pytest.raises(ValueError, match="stacked"):
            decode_mode(net, stacked="sideways")

    def test_invalid_args_raise_even_with_zero_new_tokens(self):
        """Argument validation runs ahead of the max_new_tokens<=0 early
        return (post-review regression: a typo must fail fast in 0-token
        smoke calls, as it did before the engine refactor)."""
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.zeros((1, 4), onp.int32)
        for bad in (dict(weights="int4"), dict(prefill="batch"),
                    dict(fused="always"), dict(stacked="sideways")):
            with pytest.raises(ValueError):
                kv_generate(net, prompt, max_new_tokens=0, **bad)

    def test_nonstandard_ffn_variant_decodes_unrolled(self):
        """A GPT-family variant whose FFN lacks the fc1/act structure
        must keep decoding through the unrolled generality fallback
        (post-review regression: the engine's act-type probe must not
        crash on it — one_token calls the whole ffn Block and never
        needs fc1)."""
        from mxnet_tpu.gluon.block import HybridBlock
        from mxnet_tpu.gluon.nn.basic_layers import Dense
        from mxnet_tpu.models import decode_mode, kv_generate

        class _WeirdFFN(HybridBlock):
            def __init__(self, units, hidden, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.a = Dense(hidden, flatten=False, in_units=units,
                                   activation="tanh", prefix="a_")
                    self.b = Dense(units, flatten=False, in_units=hidden,
                                   prefix="b_")

            def hybrid_forward(self, F, x):
                return self.b(self.a(x))

        net = _gpt()
        for i, blk in enumerate(net.blocks):
            blk.ffn = _WeirdFFN(32, 64, prefix=f"wf{i}_")
        net.initialize(mx.init.Normal(0.02))
        assert decode_mode(net) == "unrolled"
        prompt = onp.random.RandomState(8).randint(0, 97, (1, 4))
        out = kv_generate(net, prompt, max_new_tokens=5, temperature=0.0)
        ref = net.generate(prompt, max_new_tokens=5, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_non_uniform_stack_falls_back(self):
        """A layer stack with differing norm eps cannot share one scan
        body — the gate must reject it and kv_generate must fall back to
        the unrolled path (which derives math from the model's own
        sublayers) with correct output."""
        from mxnet_tpu.models import decode_mode, kv_generate
        from mxnet_tpu.ops.decode_fused import stacked_decode_supported
        net = _gpt()
        net.blocks[1].ln1._eps = 1e-3
        assert not stacked_decode_supported(net)
        assert decode_mode(net) == "unrolled"
        prompt = onp.random.RandomState(5).randint(0, 97, (1, 4))
        out = kv_generate(net, prompt, max_new_tokens=4, temperature=0.0)
        ref = net.generate(prompt, max_new_tokens=4, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_stack_export_shapes(self):
        """stacked_decode_weights: every slot is (NL, ...) with the
        per-layer array's shape behind it; GQA k/v rows are KV*D wide."""
        net, cfg = _llama()
        sw = net.stacked_decode_weights()
        NL = cfg.num_layers
        d = cfg.units // cfg.num_heads
        assert sw["q_w"].shape == (NL, cfg.units, cfg.units)
        assert sw["k_w"].shape == (NL, cfg.num_kv_heads * d, cfg.units)
        assert sw["rms1_g"].shape == (NL, cfg.units)
        gnet = _gpt(layers=3, units=32, hidden=64)
        gsw = gnet.stacked_decode_weights()
        assert gsw["qkv_w"].shape == (3, 96, 32)
        assert gsw["fc1_b"].shape == (3, 64)


class TestInt8StackedParity:
    """The q8 weight stream through the stacked scan (stacked codes ride
    the xs through q8_matvec) must match the per-layer unrolled q8 path
    token-for-token — same codes, same kernel, same cast order."""

    def test_gpt_int8_stacked_matches_unrolled(self):
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(10).randint(0, 97, (2, 5))
        kw = dict(max_new_tokens=10, temperature=0.0, weights="int8")
        onp.testing.assert_array_equal(
            kv_generate(net, prompt, stacked="on", **kw),
            kv_generate(net, prompt, stacked="off", **kw))
        kw = dict(max_new_tokens=6, temperature=0.8, top_k=5, seed=13,
                  weights="int8")
        onp.testing.assert_array_equal(
            kv_generate(net, prompt, stacked="on", **kw),
            kv_generate(net, prompt, stacked="off", **kw))

    def test_llama_gqa_int8_stacked_matches_unrolled(self):
        from mxnet_tpu.models import kv_generate
        net, cfg = _llama()
        assert cfg.num_kv_heads < cfg.num_heads
        prompt = onp.random.RandomState(11).randint(0, cfg.vocab_size,
                                                    (2, 4))
        kw = dict(max_new_tokens=8, temperature=0.0, weights="int8")
        onp.testing.assert_array_equal(
            kv_generate(net, prompt, stacked="on", **kw),
            kv_generate(net, prompt, stacked="off", **kw))

    def test_int8_stack_requantizes_on_rebind(self):
        """A weight rebind must invalidate the stacked q8 codes (the
        pinned-source discipline shared with the per-layer q8 cache)."""
        from mxnet_tpu.models import kv_generate
        net = _gpt(init=0.15)
        prompt = onp.random.RandomState(12).randint(0, 97, (1, 4))
        kw = dict(max_new_tokens=4, temperature=0.0, weights="int8")
        out1 = kv_generate(net, prompt, stacked="on", **kw)
        w = net.blocks[0].attn.qkv.weight
        w.set_data(mx.nd.from_jax(-w.data()._data))
        out2 = kv_generate(net, prompt, stacked="on", **kw)
        ref2 = kv_generate(net, prompt, stacked="off", **kw)
        onp.testing.assert_array_equal(out2, ref2)
        assert (out1 != out2).any()

    def test_int8_op_count_collapse_and_layer_invariance(self):
        """The int8 stacked step carries one layer-body of HLO too:
        deepening the stack must not grow the op count, and the stacked
        count stays under the unrolled one."""
        from mxnet_tpu import profiler_xla
        from mxnet_tpu.models import decode_step_program
        counts = {}
        for layers in (2, 4):
            net = _gpt(layers=layers)
            for smode in ("on", "off"):
                fn, args = decode_step_program(net, batch=1, total=16,
                                               weights="int8",
                                               stacked=smode)
                counts[(smode, layers)] = profiler_xla.hlo_op_count(
                    fn, *args)
        assert counts[("on", 4)] == counts[("on", 2)]
        assert counts[("off", 4)] > counts[("off", 2)]
        assert counts[("on", 2)] < counts[("off", 2)]


class TestOpCountCeiling:
    def test_tiny_geometry_collapse(self):
        """Stacked step carries ~one layer-body of HLO: deepening the
        stack must NOT grow the op count (the unrolled step grows
        linearly)."""
        from mxnet_tpu import profiler_xla
        from mxnet_tpu.models import decode_step_program
        counts = {}
        for layers in (2, 4):
            net = _gpt(layers=layers)
            fn, args = decode_step_program(net, batch=1, total=16)
            counts[("stacked", layers)] = profiler_xla.hlo_op_count(
                fn, *args)
            fn, args = decode_step_program(net, batch=1, total=16,
                                           stacked="off")
            counts[("unrolled", layers)] = profiler_xla.hlo_op_count(
                fn, *args)
        assert counts[("stacked", 4)] == counts[("stacked", 2)]
        assert counts[("unrolled", 4)] > counts[("unrolled", 2)]
        assert counts[("stacked", 2)] < counts[("unrolled", 2)]

    def test_gpt2_small_geometry_under_ceiling(self):
        """The acceptance bar: GPT-2-small geometry (12L/768U/12H/3072F)
        compiled stacked decode step stays ≤ 60 HLO ops on CPU (vs ~230
        executed device ops measured for the unrolled scan step in the
        r4 TPU profile; the unrolled step lowers to ~450 static ops on
        CPU), with greedy outputs token-identical to the unrolled
        path."""
        from mxnet_tpu import profiler_xla
        from mxnet_tpu.models import decode_step_program, kv_generate
        net = _gpt(layers=12, units=768, heads=12, hidden=3072,
                   vocab=2048, init=0.05)
        fn, args = decode_step_program(net, batch=1, total=48)
        n = profiler_xla.hlo_op_count(fn, *args)
        assert n <= 60, f"stacked decode step op count {n} > ceiling 60"
        prompt = onp.random.RandomState(0).randint(0, 2048, (1, 4))
        st = kv_generate(net, prompt, max_new_tokens=6, temperature=0.0,
                         stacked="on")
        un = kv_generate(net, prompt, max_new_tokens=6, temperature=0.0,
                         stacked="off")
        onp.testing.assert_array_equal(st, un)


class TestRetraceGuard:
    def test_one_executable_across_token_loop(self):
        """The whole decode (prefill + every token) is ONE jit program:
        repeated calls with the same signature reuse one cache entry and
        one compiled executable — no per-token dispatch, no retrace."""
        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(7).randint(0, 97, (1, 5))
        kv_generate(net, prompt, max_new_tokens=8, temperature=0.0)
        kv_generate(net, prompt, max_new_tokens=8, temperature=0.0)
        cache = net._kv_decode_cache
        assert len(cache) == 1
        (jitted,) = cache.values()
        assert jitted._cache_size() == 1
        # a weight edit must NOT retrace (weights ride as traced args)
        w = net.blocks[0].attn.qkv.weight
        w.set_data(mx.nd.from_jax(-w.data()._data))
        kv_generate(net, prompt, max_new_tokens=8, temperature=0.0)
        assert len(cache) == 1 and jitted._cache_size() == 1


class TestNoWeightPinning:
    def test_rebound_weights_are_freed(self):
        """Train/serve interleave must not leak weight copies: the
        cached decode program's closure (which outlives each call) must
        not pin the first call's weight arrays after a rebind
        (post-review regression — the engine now hands its operand refs
        to the caller and drops them)."""
        import gc
        import weakref

        from mxnet_tpu.models import kv_generate
        net = _gpt()
        prompt = onp.random.RandomState(9).randint(0, 97, (1, 4))
        kv_generate(net, prompt, max_new_tokens=3, temperature=0.0)
        old = net.blocks[0].attn.qkv.weight.data()._data
        ref = weakref.ref(old)
        w = net.blocks[0].attn.qkv.weight
        w.set_data(mx.nd.from_jax(-old))
        del old
        kv_generate(net, prompt, max_new_tokens=3, temperature=0.0)
        gc.collect()
        assert ref() is None, \
            "first-call weight array still pinned after rebind"


class TestStepOpCountSideEffects:
    def test_step_hlo_op_count_does_not_advance_global_rng(self):
        """step_hlo_op_count is a compile-only diagnostic: inserting it
        between training steps must not change the global PRNG stream
        (post-review regression — it previously consumed
        random.next_key())."""
        from mxnet_tpu import gluon, parallel
        from mxnet_tpu import random as mxr
        from mxnet_tpu.gluon import nn
        import jax

        mx.random.seed(0)
        net = nn.Dense(4, in_units=4, flatten=False)
        net.initialize(mx.init.Xavier())
        tr = parallel.SPMDTrainer(
            net, gluon.loss.L2Loss(), "sgd", {"learning_rate": 0.1},
            mesh=parallel.make_mesh({"dp": len(jax.devices())}))
        x = mx.nd.array(onp.random.RandomState(0).rand(8, 4)
                        .astype("float32"))
        y = mx.nd.array(onp.random.RandomState(1).rand(8, 4)
                        .astype("float32"))
        mx.random.seed(7)
        ref = onp.asarray(mxr.next_key())
        mx.random.seed(7)
        assert tr.step_hlo_op_count(x, y) > 0
        got = onp.asarray(mxr.next_key())
        onp.testing.assert_array_equal(got, ref)


class TestDecodeBenchSmoke:
    def test_decode_bench_smoke(self):
        """benchmark/decode_bench.py --smoke: unrolled vs stacked arms +
        ops/step column on a tiny geometry (the tier-1 gate — asserts
        parity and the op-count collapse internally)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "benchmark/decode_bench.py", "--smoke"],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=570)
        assert r.returncode == 0, r.stderr[-2000:]
        assert '"mode": "stacked"' in r.stdout
        assert '"ops_per_step"' in r.stdout
        assert "parity OK" in r.stdout
