"""mx.np / mx.npx namespaces (reference test model:
tests/python/unittest/test_numpy_op.py — NumPy-golden checks)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np
npx = mx.npx


def _chk(mx_val, np_val, rtol=1e-5, atol=1e-6):
    onp.testing.assert_allclose(mx_val.asnumpy(), np_val, rtol=rtol,
                                atol=atol)


class TestCreation:
    def test_basic(self):
        assert np.zeros((2, 3)).shape == (2, 3)
        assert np.ones(4).asnumpy().sum() == 4
        assert np.full((2,), 7.0).asnumpy().tolist() == [7.0, 7.0]
        assert np.arange(5).asnumpy().tolist() == [0, 1, 2, 3, 4]
        assert np.eye(3).asnumpy().trace() == 3
        _chk(np.linspace(0, 1, 5), onp.linspace(0, 1, 5))

    def test_float64_downcast(self):
        # reference: python floats become float32
        a = np.array([1.5, 2.5])
        assert a.dtype == onp.float32

    def test_like(self):
        x = np.array([[1.0, 2], [3, 4]])
        assert np.zeros_like(x).asnumpy().sum() == 0
        assert np.ones_like(x).asnumpy().sum() == 4
        assert type(np.zeros_like(x)) is np.ndarray


class TestUfuncs:
    def test_unary_golden(self):
        x = onp.random.RandomState(0).rand(3, 4).astype(onp.float32) + 0.1
        mx_x = np.array(x)
        for name in ["exp", "log", "sqrt", "square", "sin", "cos", "tanh",
                     "floor", "ceil", "abs", "sign", "log1p", "expm1"]:
            _chk(getattr(np, name)(mx_x), getattr(onp, name)(x), rtol=1e-4)

    def test_binary_golden(self):
        r = onp.random.RandomState(1)
        a, b = r.rand(2, 3).astype(onp.float32), \
            r.rand(2, 3).astype(onp.float32)
        ma, mb = np.array(a), np.array(b)
        for name in ["add", "subtract", "multiply", "divide", "maximum",
                     "minimum", "power", "arctan2", "hypot"]:
            _chk(getattr(np, name)(ma, mb), getattr(onp, name)(a, b),
                 rtol=1e-4)

    def test_scalar_broadcast(self):
        x = np.array([1.0, 2.0])
        assert np.add(x, 1).asnumpy().tolist() == [2.0, 3.0]
        assert (x + 1).asnumpy().tolist() == [2.0, 3.0]
        assert (2 * x).asnumpy().tolist() == [2.0, 4.0]
        assert type(x + 1) is np.ndarray

    def test_comparisons(self):
        x = np.array([1.0, 2.0, 3.0])
        assert np.less(x, 2).asnumpy().tolist() == [True, False, False]
        assert np.equal(x, 2).asnumpy().tolist() == [False, True, False]


class TestReductions:
    def test_golden(self):
        x = onp.random.RandomState(2).rand(3, 4, 5).astype(onp.float32)
        m = np.array(x)
        _chk(np.sum(m), x.sum(), rtol=1e-4)
        _chk(np.sum(m, axis=1), x.sum(axis=1), rtol=1e-4)
        _chk(np.mean(m, axis=(0, 2)), x.mean(axis=(0, 2)), rtol=1e-4)
        _chk(np.max(m, axis=0, keepdims=True), x.max(0, keepdims=True))
        _chk(np.std(m), x.std(), rtol=1e-3)
        _chk(np.var(m, ddof=1), x.var(ddof=1), rtol=1e-3)
        _chk(np.prod(m, axis=2), x.prod(axis=2), rtol=1e-3)
        _chk(np.cumsum(m, axis=1), x.cumsum(axis=1), rtol=1e-4)
        assert int(np.argmax(m).asnumpy()) == x.argmax()

    def test_bool_reductions(self):
        x = np.array([[1.0, 0.0], [1.0, 1.0]])
        assert bool(np.all(x).asnumpy()) is False
        assert bool(np.any(x).asnumpy()) is True
        assert int(np.count_nonzero(x).asnumpy()) == 3


class TestManipulation:
    def test_shapes(self):
        x = np.arange(24)
        r = np.reshape(x, (2, 3, 4))
        assert r.shape == (2, 3, 4)
        assert np.transpose(r).shape == (4, 3, 2)
        assert np.transpose(r, (1, 0, 2)).shape == (3, 2, 4)
        assert np.swapaxes(r, 0, 2).shape == (4, 3, 2)
        assert np.expand_dims(r, 0).shape == (1, 2, 3, 4)
        assert np.squeeze(np.expand_dims(r, 0)).shape == (2, 3, 4)
        assert np.broadcast_to(np.ones((1, 4)), (3, 4)).shape == (3, 4)

    def test_joins(self):
        a, b = np.ones((2, 3)), np.zeros((2, 3))
        assert np.concatenate([a, b], axis=0).shape == (4, 3)
        assert np.stack([a, b], axis=1).shape == (2, 2, 3)
        assert np.vstack([a, b]).shape == (4, 3)
        assert np.hstack([a, b]).shape == (2, 6)
        s = np.split(np.arange(12).reshape(3, 4), 2, axis=1)
        assert len(s) == 2 and s[0].shape == (3, 2)

    def test_index_ops(self):
        x = np.array([3.0, 1.0, 2.0])
        assert np.sort(x).asnumpy().tolist() == [1.0, 2.0, 3.0]
        assert np.argsort(x).asnumpy().tolist() == [1, 2, 0]
        assert np.take(x, np.array([0, 2])).asnumpy().tolist() == [3.0, 2.0]
        u = np.unique(np.array([1, 1, 2, 3, 3]))
        assert u.asnumpy().tolist() == [1, 2, 3]
        nz = np.nonzero(np.array([0, 1, 0, 2]))
        assert nz[0].asnumpy().tolist() == [1, 3]

    def test_indexing_returns_np(self):
        x = np.arange(10).reshape(2, 5)
        assert type(x[0]) is np.ndarray
        assert type(x[:, 1:3]) is np.ndarray
        assert x[1, 4].item() == 9
        mask = x > 6
        assert x[mask].asnumpy().tolist() == [7, 8, 9]


class TestLinalgEinsum:
    def test_products(self):
        r = onp.random.RandomState(3)
        a = r.rand(3, 4).astype(onp.float32)
        b = r.rand(4, 5).astype(onp.float32)
        _chk(np.dot(np.array(a), np.array(b)), a @ b, rtol=1e-4)
        _chk(np.matmul(np.array(a), np.array(b)), a @ b, rtol=1e-4)
        _chk(np.einsum("ij,jk->ik", np.array(a), np.array(b)), a @ b,
             rtol=1e-4)
        _chk(np.tensordot(np.array(a), np.array(b), axes=([1], [0])),
             onp.tensordot(a, b, axes=([1], [0])), rtol=1e-4)

    def test_linalg(self):
        r = onp.random.RandomState(4)
        a = r.rand(4, 4).astype(onp.float32)
        spd = a @ a.T + 4 * onp.eye(4, dtype=onp.float32)
        m = np.array(spd)
        _chk(np.linalg.inv(m), onp.linalg.inv(spd), rtol=1e-2, atol=1e-3)
        _chk(np.linalg.cholesky(m), onp.linalg.cholesky(spd), rtol=1e-3,
             atol=1e-4)
        _chk(np.linalg.norm(m), onp.linalg.norm(spd), rtol=1e-4)
        w, v = np.linalg.eigh(m)
        _chk(w, onp.linalg.eigh(spd)[0], rtol=1e-3, atol=1e-3)
        _chk(np.linalg.det(m), onp.linalg.det(spd), rtol=1e-3)
        x = np.linalg.solve(m, np.ones((4,)))
        _chk(x, onp.linalg.solve(spd, onp.ones(4)), rtol=1e-3, atol=1e-4)


class TestAutogradThroughNp:
    def test_backward(self):
        a = np.array([1.0, 2.0, 3.0])
        a.attach_grad()
        with autograd.record():
            loss = np.sum(np.square(a) * 3.0)
        loss.backward()
        assert a.grad.asnumpy().tolist() == [6.0, 12.0, 18.0]

    def test_einsum_grad(self):
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        a.attach_grad()
        with autograd.record():
            loss = np.einsum("ij->", np.exp(a))
        loss.backward()
        _chk(a.grad, onp.exp(a.asnumpy()), rtol=1e-5)

    def test_mixed_nd_np(self):
        x = np.array([1.0, 2.0])
        nd_x = x.as_nd_ndarray()
        assert type(nd_x) is mx.nd.NDArray
        back = nd_x.as_np_ndarray()
        assert type(back) is np.ndarray


class TestRandom:
    def test_shapes_and_seed(self):
        mx.random.seed(42)
        a = np.random.normal(0, 1, (3, 4))
        mx.random.seed(42)
        b = np.random.normal(0, 1, (3, 4))
        onp.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
        assert np.random.uniform(size=(5,)).shape == (5,)
        assert np.random.randint(0, 10, (2, 3)).shape == (2, 3)
        c = np.random.choice(5, size=(10,))
        assert c.shape == (10,) and int(c.asnumpy().max()) < 5
        p = np.random.permutation(6)
        assert sorted(p.asnumpy().tolist()) == [0, 1, 2, 3, 4, 5]


class TestNpx:
    def test_nn_ops(self):
        x = np.array([[-1.0, 2.0, 0.5]])
        assert npx.relu(x).asnumpy().tolist() == [[0.0, 2.0, 0.5]]
        s = npx.softmax(x)
        assert abs(s.asnumpy().sum() - 1) < 1e-5
        assert type(s) is np.ndarray
        _chk(npx.sigmoid(np.array([0.0])), onp.array([0.5]))
        ls = npx.log_softmax(x)
        _chk(np.exp(ls), s, rtol=1e-5)

    def test_one_hot_pick_topk(self):
        idx = np.array([0, 2])
        oh = npx.one_hot(idx, 3)
        assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]
        data = np.array([[0.1, 0.9], [0.8, 0.2]])
        assert npx.topk(data, k=1).asnumpy().reshape(-1).tolist() == [1, 0]

    def test_set_np(self):
        npx.set_np()
        assert npx.is_np_array()
        npx.reset_np()
        assert not npx.is_np_array()

    def test_layer_norm(self):
        x = np.random.normal(0, 1, (2, 8))
        g, b = np.ones((8,)), np.zeros((8,))
        y = npx.layer_norm(x, g, b)
        m = y.asnumpy().mean(axis=-1)
        onp.testing.assert_allclose(m, onp.zeros(2), atol=1e-5)


class TestUtil:
    def test_environment(self):
        import os
        from mxnet_tpu.util import environment
        with environment("MXNET_TEST_VAR", "1"):
            assert os.environ["MXNET_TEST_VAR"] == "1"
        assert "MXNET_TEST_VAR" not in os.environ

    def test_features(self):
        import mxnet_tpu.runtime as rt
        f = rt.Features()
        assert f.is_enabled("XLA")
        assert f.is_enabled("SPMD")
        with pytest.raises(RuntimeError):
            f.is_enabled("NOT_A_FEATURE")


class TestCoverageAdditions:
    def test_common_numpy_surface_present(self):
        import mxnet_tpu as mx
        names = ("corrcoef deg2rad diag_indices diagflat dsplit empty_like "
                 "nanargmax nanargmin nancumprod nancumsum nanpercentile "
                 "nanstd nanvar put rad2deg resize row_stack signbit trapz "
                 "tri triu_indices").split()
        missing = [n for n in names if not hasattr(mx.np, n)]
        assert not missing, missing

    def test_values_match_numpy(self):
        import numpy as onp
        import mxnet_tpu as mx
        np = mx.np
        onp.testing.assert_allclose(
            np.trapz(np.array([1., 2., 3.])).asnumpy(), 4.0)
        onp.testing.assert_allclose(
            np.nanstd(np.array([1., onp.nan, 3.])).asnumpy(),
            onp.nanstd([1, onp.nan, 3]), rtol=1e-6)
        a = np.array([0., 0., 0., 0.])
        np.put(a, [0, 2], [9., 8.])
        onp.testing.assert_allclose(a.asnumpy(), [9., 0., 8., 0.])
        r, _ = np.triu_indices(3)
        onp.testing.assert_array_equal(r.asnumpy(), onp.triu_indices(3)[0])


class TestNpxControlFlow:
    def test_masked_softmax(self):
        import mxnet_tpu as mx
        x = mx.np.array([[1., 2., 3.]])
        m = mx.np.array([[1, 1, 0]])
        out = mx.npx.masked_softmax(x, m)
        assert abs(float(onp.asarray(out.asnumpy()).sum()) - 1.0) < 1e-5
        assert float(out.asnumpy()[0, 2]) == 0.0
        ls = mx.npx.masked_log_softmax(x, m)
        onp.testing.assert_allclose(
            onp.exp(ls.asnumpy()[0, :2]).sum(), 1.0, rtol=1e-5)

    def test_foreach_scan(self):
        import mxnet_tpu as mx
        data = mx.np.array(onp.ones((4, 2), onp.float32))
        outs, final = mx.npx.foreach(lambda x, s: (x + s, x + s), data,
                                     mx.np.zeros((2,)))
        onp.testing.assert_allclose(final.asnumpy(), [4., 4.])
        onp.testing.assert_allclose(outs.asnumpy()[:, 0], [1., 2., 3., 4.])

    def test_while_loop_and_cond(self):
        import mxnet_tpu as mx
        out = mx.npx.while_loop(lambda vs: vs[0] < 5,
                                lambda vs: [vs[0] + 1],
                                [mx.np.array(0)], max_iterations=10)
        assert int(onp.asarray(out[0].asnumpy())) == 5
        r = mx.npx.cond(mx.np.array(True), lambda vs: [vs[0] * 2],
                        lambda vs: [vs[0] * 3], [mx.np.array(4.0)])
        assert float(onp.asarray(r[0].asnumpy())) == 8.0

    def test_index_update_add(self):
        import mxnet_tpu as mx
        a = mx.np.zeros((3, 3))
        b = mx.npx.index_update(a, (mx.np.array([0]), mx.np.array([1])),
                                mx.np.array([5.0]))
        c = mx.npx.index_add(b, (mx.np.array([0]), mx.np.array([1])),
                             mx.np.array([2.0]))
        assert float(c.asnumpy()[0, 1]) == 7.0

    def test_engine_facade(self):
        import mxnet_tpu as mx
        assert mx.engine.engine_type() in ("NaiveEngine",
                                           "ThreadedEnginePerDevice")
        prev = mx.engine.set_bulk_size(4)
        with mx.engine.bulk(32):
            pass
        mx.engine.set_bulk_size(prev)
        mx.engine.wait_all()


class TestRandomDistributions:
    def test_new_distributions_shapes_and_support(self):
        import mxnet_tpu as mx
        r = mx.np.random
        mx.random.seed(0)
        cases = [("pareto", (3.0,), lambda v: (v >= 0).all()),
                 ("power", (5.0,), lambda v: ((v >= 0) & (v <= 1)).all()),
                 ("rayleigh", (2.0,), lambda v: (v >= 0).all()),
                 ("weibull", (1.5,), lambda v: (v >= 0).all()),
                 ("geometric", (0.3,), lambda v: (v >= 1).all()),
                 ("negative_binomial", (5, 0.5), lambda v: (v >= 0).all()),
                 ("f", (5, 7), lambda v: (v > 0).all())]
        for name, args, check in cases:
            v = getattr(r, name)(*args, size=(500,)).asnumpy()
            assert v.shape == (500,), name
            assert check(v), name

    def test_moments(self):
        import mxnet_tpu as mx
        r = mx.np.random
        mx.random.seed(3)
        onp.testing.assert_allclose(
            r.rayleigh(2.0, size=(20000,)).asnumpy().mean(),
            2.0 * onp.sqrt(onp.pi / 2), rtol=0.05)
        onp.testing.assert_allclose(
            r.geometric(0.25, size=(20000,)).asnumpy().mean(), 4.0,
            rtol=0.05)
