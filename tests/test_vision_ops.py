"""Vision op tests: UpSampling, BilinearResize2D, ROIAlign/ROIPooling,
box_nms, GridGenerator/BilinearSampler (reference
src/operator/{nn,contrib}; SURVEY.md §3.1 operator corpus)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


class TestUpsample:
    def test_nearest_matches_torch(self):
        import torch
        x = mx.nd.array(onp.arange(16, dtype=onp.float32).reshape(1, 1, 4, 4))
        up = mx.nd.UpSampling(x, scale=2, sample_type="nearest")
        ref = torch.nn.functional.interpolate(
            torch.tensor(x.asnumpy()), scale_factor=2, mode="nearest").numpy()
        onp.testing.assert_allclose(up.asnumpy(), ref)

    def test_bilinear_shapes(self):
        x = mx.nd.ones((2, 3, 4, 4))
        assert mx.nd.UpSampling(x, scale=3,
                                sample_type="bilinear").shape == (2, 3, 12, 12)
        assert mx.nd.BilinearResize2D(x, height=7,
                                      width=5).shape == (2, 3, 7, 5)


class TestROI:
    def test_roialign_constant_region(self):
        img = mx.nd.array(onp.full((1, 2, 16, 16), 5.0, onp.float32))
        rois = mx.nd.array(onp.array([[0, 2, 2, 10, 10]], onp.float32))
        out = mx.nd.ROIAlign(img, rois, pooled_size=(4, 4))
        assert out.shape == (1, 2, 4, 4)
        onp.testing.assert_allclose(out.asnumpy(), 5.0)

    def test_roialign_gradient_flows(self):
        img = mx.nd.array(onp.random.rand(1, 2, 8, 8).astype(onp.float32))
        rois = mx.nd.array(onp.array([[0, 1, 1, 6, 6]], onp.float32))
        img.attach_grad()
        with autograd.record():
            out = mx.nd.ROIAlign(img, rois, pooled_size=(2, 2))
        out.backward(mx.nd.ones(out.shape))
        assert float(onp.asarray(img.grad.abs().sum().asnumpy())) > 0

    def test_roipooling_max(self):
        img_np = onp.zeros((1, 1, 8, 8), onp.float32)
        img_np[0, 0, 3, 3] = 9.0
        rois = mx.nd.array(onp.array([[0, 0, 0, 7, 7]], onp.float32))
        out = mx.nd.ROIPooling(mx.nd.array(img_np), rois, pooled_size=(2, 2))
        assert float(out.asnumpy().max()) == 9.0

    def test_batch_index_selects_image(self):
        data = onp.stack([onp.full((1, 4, 4), 1.0), onp.full((1, 4, 4), 2.0)])
        rois = mx.nd.array(onp.array([[1, 0, 0, 3, 3]], onp.float32))
        out = mx.nd.ROIAlign(mx.nd.array(data.astype(onp.float32)), rois,
                             pooled_size=(2, 2))
        onp.testing.assert_allclose(out.asnumpy(), 2.0)


class TestBoxNMS:
    def test_suppresses_overlap(self):
        boxes = onp.array([[0, 0.9, 0, 0, 10, 10],
                           [0, 0.8, 1, 1, 11, 11],
                           [1, 0.7, 20, 20, 30, 30]], onp.float32)
        out = mx.nd.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                            coord_start=2, score_index=1, id_index=0,
                            force_suppress=True)
        onp.testing.assert_allclose(out.asnumpy()[:, 1], [0.9, -1.0, 0.7])

    def test_per_class_no_suppression(self):
        # overlapping boxes of DIFFERENT class ids both survive
        boxes = onp.array([[0, 0.9, 0, 0, 10, 10],
                           [1, 0.8, 1, 1, 11, 11]], onp.float32)
        out = mx.nd.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                            coord_start=2, score_index=1, id_index=0)
        onp.testing.assert_allclose(out.asnumpy()[:, 1], [0.9, 0.8])

    def test_valid_thresh(self):
        boxes = onp.array([[0, 0.05, 0, 0, 5, 5]], onp.float32)
        out = mx.nd.box_nms(mx.nd.array(boxes), valid_thresh=0.1,
                            coord_start=2, score_index=1, id_index=0)
        assert float(out.asnumpy()[0, 1]) == -1.0


class TestSpatialTransformer:
    def test_identity_transform(self):
        theta = mx.nd.array(onp.array([[1, 0, 0, 0, 1, 0]], onp.float32))
        grid = mx.nd.GridGenerator(theta, transform_type="affine",
                                   target_shape=(4, 4))
        x = mx.nd.array(onp.random.rand(1, 1, 4, 4).astype(onp.float32))
        out = mx.nd.BilinearSampler(x, grid)
        onp.testing.assert_allclose(out.asnumpy(), x.asnumpy(), atol=1e-5)

    def test_translation_shifts(self):
        # x-shift of a delta image moves the bright pixel
        theta = mx.nd.array(onp.array([[1, 0, 0.5, 0, 1, 0]], onp.float32))
        grid = mx.nd.GridGenerator(theta, transform_type="affine",
                                   target_shape=(1, 5))
        x = onp.zeros((1, 1, 1, 5), onp.float32)
        x[0, 0, 0, 4] = 1.0
        out = mx.nd.BilinearSampler(mx.nd.array(x), grid)
        assert float(out.asnumpy()[0, 0, 0, 3]) > 0.9


class TestActivations:
    def test_values(self):
        x = mx.nd.array(onp.array([-1.0, 0.0, 2.0], onp.float32))
        onp.testing.assert_allclose(
            mx.nd.hard_sigmoid(x).asnumpy(),
            onp.clip(0.2 * x.asnumpy() + 0.5, 0, 1), rtol=1e-6)
        onp.testing.assert_allclose(
            mx.nd.log_sigmoid(x).asnumpy(),
            onp.log(1 / (1 + onp.exp(-x.asnumpy()))), rtol=1e-5)
        import torch
        onp.testing.assert_allclose(
            mx.nd.mish(x).asnumpy(),
            torch.nn.functional.mish(torch.tensor(x.asnumpy())).numpy(),
            rtol=1e-5)


class TestMultiBox:
    def test_prior_grid(self):
        pri = mx.nd.MultiBoxPrior(mx.nd.ones((1, 3, 2, 2)),
                                  sizes=(0.5, 0.25), ratios=(1, 2))
        assert pri.shape == (1, 12, 4)
        a = pri.asnumpy()[0]
        cx = (a[:, 0] + a[:, 2]) / 2
        assert abs(cx[0] - 0.25) < 1e-6

    def test_target_matching(self):
        anchors = mx.nd.array(onp.array([[[0.1, 0.1, 0.4, 0.4],
                                          [0.6, 0.6, 0.9, 0.9]]],
                                        onp.float32))
        labels = mx.nd.array(onp.array([[[0, 0.1, 0.1, 0.42, 0.42],
                                         [-1, 0, 0, 0, 0]]], onp.float32))
        loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(anchors, labels,
                                                   mx.nd.zeros((1, 2, 2)))
        assert float(cls_t.asnumpy()[0, 0]) == 1.0  # matched -> class+1
        assert float(cls_t.asnumpy()[0, 1]) == 0.0  # background
        assert float(loc_m.asnumpy()[0, :4].sum()) == 4.0

    def test_detection_decodes_anchors_at_zero_offset(self):
        anchors = mx.nd.array(onp.array([[[0.1, 0.1, 0.4, 0.4],
                                          [0.6, 0.6, 0.9, 0.9]]],
                                        onp.float32))
        cls_prob = mx.nd.array(onp.array([[[0.1, 0.2], [0.9, 0.8]]],
                                         onp.float32))
        det = mx.nd.MultiBoxDetection(cls_prob, mx.nd.zeros((1, 8)), anchors)
        d = det.asnumpy()[0]
        keep = d[d[:, 1] > 0]
        onp.testing.assert_allclose(keep[0, 2:], [0.1, 0.1, 0.4, 0.4],
                                    atol=1e-5)


class TestFFTDlpack:
    def test_fft_roundtrip(self):
        x = mx.nd.array(onp.random.rand(2, 8).astype(onp.float32))
        f = mx.nd.fft(x)
        assert f.shape == (2, 16)
        rec = mx.nd.ifft(f) / 8
        onp.testing.assert_allclose(rec.asnumpy(), x.asnumpy(), rtol=1e-5,
                                    atol=1e-6)

    def test_dlpack_torch_roundtrip(self):
        import torch
        x = mx.nd.array(onp.arange(6, dtype=onp.float32).reshape(2, 3))
        t = torch.from_dlpack(x)
        onp.testing.assert_array_equal(t.numpy(), x.asnumpy())
        back = mx.nd.from_dlpack(torch.arange(4, dtype=torch.float32))
        onp.testing.assert_array_equal(back.asnumpy(), [0, 1, 2, 3])


class TestProposal:
    def test_rpn_proposals(self):
        rng = onp.random.RandomState(0)
        N, H, W, A = 1, 4, 4, 2
        cls_prob = mx.nd.array(rng.rand(N, 2 * A, H, W).astype(onp.float32))
        bbox_pred = mx.nd.array(
            (rng.rand(N, 4 * A, H, W).astype(onp.float32) - 0.5) * 0.1)
        im_info = mx.nd.array(onp.array([[64, 64, 1.0]], onp.float32))
        rois = mx.nd.Proposal(cls_prob, bbox_pred, im_info, scales=(1, 2),
                              ratios=(1.0,), feature_stride=16,
                              rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8,
                              rpn_min_size=4)
        r = rois.asnumpy()
        assert r.shape == (8, 5)
        assert (r[:, 0] == 0).all()           # batch index
        assert (r[:, 1] >= 0).all() and (r[:, 3] <= 63.01).all()  # clipped
        assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()

    def test_output_score(self):
        rng = onp.random.RandomState(1)
        cls_prob = mx.nd.array(rng.rand(1, 4, 4, 4).astype(onp.float32))
        bbox_pred = mx.nd.array(onp.zeros((1, 8, 4, 4), onp.float32))
        im_info = mx.nd.array(onp.array([[64, 64, 1.0]], onp.float32))
        rois, scores = mx.nd.Proposal(cls_prob, bbox_pred, im_info,
                                      scales=(1, 2), ratios=(1.0,),
                                      output_score=True,
                                      rpn_post_nms_top_n=5, rpn_min_size=4)
        assert scores.shape == (5, 1)


class TestDeformableConv:
    def test_zero_offset_equals_plain_conv(self):
        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.rand(2, 4, 8, 8).astype(onp.float32))
        w = mx.nd.array(rng.rand(6, 4, 3, 3).astype(onp.float32) * 0.1)
        off = mx.nd.zeros((2, 18, 8, 8))
        out = mx.nd.DeformableConvolution(x, off, w, kernel=(3, 3),
                                          pad=(1, 1), num_filter=6,
                                          no_bias=True)
        ref = mx.nd.Convolution(x, w, kernel=(3, 3), pad=(1, 1),
                                num_filter=6, no_bias=True)
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-4,
                                    atol=1e-5)

    def test_gradients_flow_to_all_inputs(self):
        rng = onp.random.RandomState(1)
        x = mx.nd.array(rng.rand(1, 2, 6, 6).astype(onp.float32))
        w = mx.nd.array(rng.rand(3, 2, 3, 3).astype(onp.float32) * 0.1)
        off = mx.nd.array(rng.rand(1, 18, 6, 6).astype(onp.float32) * 0.5)
        for t in (x, w, off):
            t.attach_grad()
        with autograd.record():
            o = mx.nd.DeformableConvolution(x, off, w, kernel=(3, 3),
                                            pad=(1, 1), num_filter=3,
                                            no_bias=True)
        o.backward(mx.nd.ones(o.shape))
        for t in (x, w, off):
            assert float(onp.asarray(t.grad.abs().sum().asnumpy())) > 0


class TestCorrelation:
    def test_zero_displacement_is_mean_of_squares(self):
        rng = onp.random.RandomState(0)
        a = rng.rand(1, 3, 6, 6).astype(onp.float32)
        out = mx.nd.Correlation(mx.nd.array(a), mx.nd.array(a),
                                max_displacement=1)
        # reference crops a border of max_displacement + kernel_radius = 1
        assert out.shape == (1, 9, 4, 4)
        onp.testing.assert_allclose(out.asnumpy()[0, 4],
                                    (a[0] ** 2).mean(0)[1:-1, 1:-1],
                                    rtol=1e-5)

    def test_displacement_alignment(self):
        rng = onp.random.RandomState(1)
        a = rng.rand(1, 2, 6, 6).astype(onp.float32)
        b = onp.roll(a, -1, axis=3)
        out = mx.nd.Correlation(mx.nd.array(a), mx.nd.array(b),
                                max_displacement=1)
        # channel 3 = (dy=0, dx=-1); cropped grid covers orig coords 1..4
        onp.testing.assert_allclose(out.asnumpy()[0, 3],
                                    ((a[0] ** 2).mean(0))[1:-1, 1:-1],
                                    rtol=1e-5)
