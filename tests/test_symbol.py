"""Symbol module tests (reference tests/python/unittest/test_symbol.py
coverage; SURVEY.md §3.2 "symbol module", §5.4b export formats)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.base import MXNetError


def _mlp():
    data = mx.sym.var("data")
    w1, b1 = mx.sym.var("w1"), mx.sym.var("b1")
    fc = mx.sym.FullyConnected(data, w1, b1, num_hidden=4, name="fc1")
    return mx.sym.Activation(fc, act_type="relu", name="relu1")


class TestSymbolCompose:
    def test_arguments_topo_order(self):
        s = _mlp()
        assert s.list_arguments() == ["data", "w1", "b1"]

    def test_infer_shape(self):
        s = _mlp()
        args, outs, aux = s.infer_shape(data=(5, 8), w1=(4, 8), b1=(4,))
        assert outs == [(5, 4)]
        assert aux == []

    def test_infer_shape_missing_raises(self):
        with pytest.raises(MXNetError):
            _mlp().infer_shape(data=(5, 8))

    def test_infer_type(self):
        s = _mlp()
        args, outs, _ = s.infer_type()
        assert outs[0] == onp.dtype("float32")

    def test_composition_substitutes_variable(self):
        first = _mlp()
        head = mx.sym.FullyConnected(mx.sym.var("x2"), mx.sym.var("w2"),
                                     None, num_hidden=2, no_bias=True)
        comp = head(x2=first)
        names = comp.list_arguments()
        assert "data" in names and "x2" not in names

    def test_group_and_index(self):
        a = _mlp()
        b = a + 2.0
        grp = mx.sym.Group([a, b])
        assert len(grp) == 2
        assert grp[0].list_arguments() == a.list_arguments()

    def test_scalar_arithmetic(self):
        s = mx.sym.var("x") * 2.0 + 1.0
        out = s.eval(x=mx.nd.array([1.0, 2.0]))[0]
        onp.testing.assert_allclose(out.asnumpy(), [3.0, 5.0])

    def test_operator_overloads(self):
        x = mx.sym.var("x")
        y = mx.sym.var("y")
        out = ((x + y) * x / y - x).eval(x=mx.nd.array([4.0]),
                                         y=mx.nd.array([2.0]))[0]
        onp.testing.assert_allclose(out.asnumpy(), [8.0])


class TestSymbolSerialization:
    def test_json_roundtrip_eval(self, tmp_path):
        s = _mlp()
        x = onp.random.rand(2, 8).astype(onp.float32)
        W = onp.random.rand(4, 8).astype(onp.float32)
        b = onp.random.rand(4).astype(onp.float32)
        ref = s.eval(data=mx.nd.array(x), w1=mx.nd.array(W),
                     b1=mx.nd.array(b))[0]
        fname = str(tmp_path / "sym.json")
        s.save(fname)
        s2 = mx.sym.load(fname)
        assert s2.list_arguments() == s.list_arguments()
        out = s2.eval(data=mx.nd.array(x), w1=mx.nd.array(W),
                      b1=mx.nd.array(b))[0]
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-6)


class TestExecutor:
    def test_forward_backward(self):
        s = _mlp()
        ex = s.simple_bind(grad_req="write", data=(2, 8), w1=(4, 8), b1=(4,))
        x = onp.random.rand(2, 8).astype(onp.float32)
        W = onp.random.rand(4, 8).astype(onp.float32)
        ex.forward(is_train=True, data=x, w1=W, b1=onp.zeros(4, onp.float32))
        ex.backward(mx.nd.ones((2, 4)))
        gw = ex.grad_dict["w1"]
        assert gw.shape == (4, 8)
        # relu active everywhere (positive inputs) → dW = out_grad^T @ x
        onp.testing.assert_allclose(gw.asnumpy(),
                                    onp.ones((2, 4)).T @ x, rtol=1e-4)

    def test_bind_missing_arg_raises(self):
        with pytest.raises(MXNetError):
            _mlp().bind(args={"data": mx.nd.zeros((2, 8))})


class TestExportImports:
    def test_dense_roundtrip(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
        net.initialize()
        x = mx.nd.array(onp.random.rand(3, 20).astype(onp.float32))
        ref = net(x)
        prefix = str(tmp_path / "mlp")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                        prefix + "-0000.params")
        out = blk(x)
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                    rtol=1e-5, atol=1e-5)

    def test_conv_bn_roundtrip(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, 3, padding=1),
                gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"),
                gluon.nn.Flatten(),
                gluon.nn.Dense(5))
        net.initialize()
        x = mx.nd.array(onp.random.rand(2, 3, 8, 8).astype(onp.float32))
        net(x)  # one pass to settle shapes
        ref = net(x)
        prefix = str(tmp_path / "convnet")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                        prefix + "-0000.params")
        out = blk(x)
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                    rtol=1e-4, atol=1e-4)

    def test_exported_hybridized_matches(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(6))
        net.initialize()
        net.hybridize()
        x = mx.nd.array(onp.random.rand(2, 4).astype(onp.float32))
        ref = net(x)
        prefix = str(tmp_path / "h")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                        prefix + "-0000.params")
        onp.testing.assert_allclose(blk(x).asnumpy(), ref.asnumpy(),
                                    rtol=1e-5, atol=1e-5)

    def test_export_before_forward_raises(self, tmp_path):
        net = gluon.nn.Dense(3)
        net.initialize()
        with pytest.raises(MXNetError):
            net.export(str(tmp_path / "x"))

    def test_symbolblock_trains(self, tmp_path):
        net = gluon.nn.Dense(4)
        net.initialize()
        x = mx.nd.array(onp.random.rand(2, 3).astype(onp.float32))
        net(x)
        prefix = str(tmp_path / "t")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                        prefix + "-0000.params")
        with autograd.record():
            out = blk(x)
            loss = (out * out).sum()
        loss.backward()
        grads = [p.grad() for p in blk.collect_params().values()
                 if p.grad_req != "null"]
        assert any(float(g.abs().sum().asnumpy()) > 0 for g in grads)


class TestCapture:
    def test_capture_records_ops(self):
        from mxnet_tpu.symbol.symbol import capture
        x = mx.nd.array(onp.random.rand(2, 3).astype(onp.float32))
        with capture() as cap:
            cap.mark_variable("x", x)
            y = mx.nd.relu(x)
            z = y + y
        sym = cap.symbol_for([z])
        assert sym.list_arguments() == ["x"]
        out = sym.eval(x=x)[0]
        onp.testing.assert_allclose(
            out.asnumpy(), 2 * onp.maximum(x.asnumpy(), 0), rtol=1e-6)


class TestMultiOutput:
    def test_split_heads_and_composition_index(self):
        x = mx.sym.var("x")
        parts = mx.sym.split(x, num_outputs=2, axis=0)
        assert len(parts) == 2
        net = mx.sym.relu(mx.sym.var("h"))
        comp = net(h=parts[1])  # must wire to output 1, not output 0
        res = comp.eval(x=mx.nd.array(onp.array([[-1., 2.], [3., -4.]],
                                                onp.float32)))[0]
        onp.testing.assert_allclose(res.asnumpy(), [[3., 0.]])

    def test_group_eval(self):
        x = mx.sym.var("x")
        parts = mx.sym.split(x, num_outputs=2, axis=0)
        outs = parts.eval(x=mx.nd.ones((4, 3)))
        assert len(outs) == 2 and outs[0].shape == (2, 3)


class TestNameAttrScopes:
    def test_prefix_names(self):
        with mx.name.Prefix("scope_"):
            s = mx.sym.relu(mx.sym.var("x"))
        assert s.name.startswith("scope_relu")

    def test_attr_scope_rides_and_filters(self):
        with mx.AttrScope(ctx_group="dev1"):
            t = mx.sym.relu(mx.sym.var("y"))
        assert t.attr("__ctx_group__") == "dev1"
        out = t.eval(y=mx.nd.array([-1.0, 3.0]))[0]
        assert out.asnumpy().tolist() == [0.0, 3.0]

    def test_attr_scope_nesting_merges(self):
        with mx.AttrScope(a="1"):
            with mx.AttrScope(b="2"):
                u = mx.sym.relu(mx.sym.var("z"))
        assert u.attr("__a__") == "1" and u.attr("__b__") == "2"


class TestPredictor:
    """Standalone inference runner — the c_predict_api answer
    (mxnet_tpu/predictor.py, SURVEY.md §3.1 C API row)."""

    def _export_mlp(self, tmp_path):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
        net.initialize()
        x = mx.nd.array(onp.random.rand(3, 20).astype(onp.float32))
        ref = net(x)
        prefix = str(tmp_path / "pred")
        net.export(prefix)
        return prefix, x, ref

    def test_predict_api_surface(self, tmp_path):
        from mxnet_tpu.predictor import Predictor
        prefix, x, ref = self._export_mlp(tmp_path)
        pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                         {"data": (3, 20)})
        # MXPredSetInput / Forward / GetOutput shape
        pred.set_input("data", x.asnumpy())
        pred.run()
        out = pred.get_output(0)
        onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                    rtol=1e-5, atol=1e-5)
        # one-call convenience
        out2 = pred.forward(data=x.asnumpy())[0]
        onp.testing.assert_allclose(out2.asnumpy(), ref.asnumpy(),
                                    rtol=1e-5, atol=1e-5)

    def test_compiled_artifact_roundtrip(self, tmp_path):
        """jax.export AOT artifact: serialize, reload, run without the
        model's Python code."""
        from mxnet_tpu.predictor import Predictor
        prefix, x, ref = self._export_mlp(tmp_path)
        pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                         {"data": (3, 20)})
        artifact = str(tmp_path / "model.jaxexport")
        pred.export_compiled(artifact)
        run = Predictor.load_compiled(artifact)
        out = run(x.asnumpy())[0]
        onp.testing.assert_allclose(onp.asarray(out), ref.asnumpy(),
                                    rtol=1e-5, atol=1e-5)


class TestCaptureRandomOps:
    def test_registered_random_ops_capture_and_replay(self):
        """mx.random scalar draws now route through REGISTERED ops with
        static attrs, so symbol capture records a replayable node (the
        r3 collision fix: the old ad-hoc Op closures captured broken
        graphs)."""
        from mxnet_tpu.symbol.symbol import capture
        mx.random.seed(3)
        with capture() as cap:
            y = mx.random.uniform(2.0, 5.0, shape=(64,))
            z = mx.nd.relu(y)
        sym = cap.symbol_for([z])
        assert sym.list_arguments() == []  # attrs-only: no dangling inputs
        out = sym.eval()[0].asnumpy()
        assert out.shape == (64,)
        # replay draws FRESH randomness but the recorded attrs (the
        # 2..5 range) must be respected
        assert out.min() >= 2.0 and out.max() < 5.0
