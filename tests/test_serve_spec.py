"""Speculative draft-and-verify decoding (ISSUE 17,
``mxnet_tpu/serve/draft.py`` + ``PoolPrograms.verify_fn``).

THE acceptance bar: a greedy served stream under speculation is
token-for-token identical to ``kv_generate`` — speculation changes the
dispatch schedule, never the tokens.  Around it: the verify-ladder
compile bound (``len(spec_sizes) x len(pool_sizes)`` programs, zero
retraces under draft-length churn), the draft ledger
(``accepted + rejected == proposed``, re-derived by ``--check-serve``),
prefix-cache co-residency (the hit slot's first step is plain — the
ramp), the ``serve.verify`` chaos site, and the env knobs
(``MXNET_SERVE_SPEC`` / ``_DEPTH`` / ``_SIZES``).
"""
import os
import subprocess
import sys
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import faults


def _gpt(layers=2, units=32, heads=4, hidden=64, vocab=97,
         max_length=64):
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=vocab, max_length=max_length,
                        num_layers=layers, units=units, num_heads=heads,
                        hidden_size=hidden))
    net.initialize(mx.init.Normal(0.02))
    return net


def _prompt(seed, n, vocab=97):
    return onp.random.RandomState(seed).randint(0, vocab, (n,))


def _drain(server):
    while server.pump():
        pass


def _ref(net, prompt, n, **kw):
    from mxnet_tpu.models import kv_generate
    kw.setdefault("temperature", 0.0)
    return list(kv_generate(net, prompt[None], max_new_tokens=n,
                            **kw)[0, prompt.size:])


@pytest.fixture(scope="module")
def net():
    return _gpt()


@pytest.fixture(scope="module")
def server(net):
    """Shared greedy 2-slot SPECULATIVE pool, pump-driven; every test
    drains it back to idle."""
    from mxnet_tpu.serve import DecodeServer
    srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                       spec=True, autostart=False)
    yield srv
    srv.close(drain=False)


# --------------------------------------------------------------------- #
# parity
# --------------------------------------------------------------------- #

class TestSpecParity:
    def test_coresident_streams_match_kv_generate(self, net, server):
        """Two ragged co-resident requests under speculation are
        bit-identical to the offline greedy decode, the ledger
        balances, and speculation actually happened (verify dispatches
        and accepted drafts are nonzero on this self-similar
        workload)."""
        server.reset_counters()
        p1, p2 = _prompt(300, 5), _prompt(301, 3)
        s1 = server.submit(p1, max_new_tokens=24)
        s2 = server.submit(p2, max_new_tokens=20)
        _drain(server)
        assert s1.tokens(5) == _ref(net, p1, 24)
        assert s2.tokens(5) == _ref(net, p2, 20)
        c = dict(server.counters)
        assert c["verify_dispatches"] > 0
        assert c["draft_accepted"] > 0
        assert c["draft_accepted"] + c["draft_rejected"] \
            == c["draft_proposed"]
        # the per-stream ledger sums to the server totals
        assert s1.draft_accepted + s2.draft_accepted \
            == c["draft_accepted"]
        assert s1.draft_rejected + s2.draft_rejected \
            == c["draft_rejected"]
        for s in (s1, s2):
            assert 0.0 <= s.accept_rate <= 1.0
        st = server.stats()
        assert st["spec"] is True
        assert st["draft_accept_rate"] == pytest.approx(
            c["draft_accepted"] / max(c["draft_proposed"], 1))

    def test_tokens_per_dispatch_beats_plain(self, net, server):
        """The point of the ISSUE: fewer advancing dispatches than
        tokens.  On the self-similar greedy stream the ledger
        multiplier total/(total - accepted) clears 1.5."""
        server.reset_counters()
        p = _prompt(302, 4)
        s = server.submit(p, max_new_tokens=32)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 32)
        total = len(s.tokens(5))
        tpd = total / max(total - s.draft_accepted, 1)
        assert tpd > 1.5, (tpd, s.draft_accepted, s.draft_rejected)

    def test_eos_retirement_exact_under_spec(self, net):
        """EOS inside an accepted burst retires at the right position:
        the acceptance clamp cuts the advance at first_eos + 1, so the
        stream equals the offline EOS-truncated decode."""
        from mxnet_tpu.serve import DecodeServer
        p = _prompt(303, 4)
        ref = _ref(net, p, 16)
        eos = ref[7]                     # retire mid-stream
        want = ref[:ref.index(eos) + 1]
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           eos_id=eos, spec=True, autostart=False)
        telemetry.clear_events()
        s = srv.submit(p, max_new_tokens=16)
        _drain(srv)
        assert s.tokens(5) == want
        assert any(e.get("request_id") == s.request_id
                   and e.get("reason") == "eos"
                   for e in telemetry.events("serve_request"))
        srv.close()

    def test_short_budget_never_overruns(self, net, server):
        """max_new smaller than the speculation depth: the budget
        clamp wins, the stream stops exactly at max_new tokens."""
        p = _prompt(304, 6)
        telemetry.clear_events()
        s = server.submit(p, max_new_tokens=2)
        _drain(server)
        assert s.tokens(5) == _ref(net, p, 2)
        assert any(e.get("request_id") == s.request_id
                   and e.get("reason") == "max_len"
                   for e in telemetry.events("serve_request"))

    def test_sampled_server_takes_plain_path(self, net):
        """temperature > 0 disables speculation (rejection sampling is
        out of scope): zero verify dispatches, sampled parity exact."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           temperature=0.8, top_k=5, spec=True,
                           autostart=False)
        assert srv.spec_enabled is False
        p = _prompt(305, 4)
        s = srv.submit(p, max_new_tokens=8, seed=9)
        _drain(srv)
        assert s.tokens(5) == _ref(net, p, 8, temperature=0.8, top_k=5,
                                   seed=9)
        assert srv.counters["verify_dispatches"] == 0
        assert srv.stats()["spec"] is False
        srv.close()

    def test_rejecting_drafter_still_exact(self, net):
        """A drafter that is always wrong costs nothing but its verify
        columns: every draft rejects, every verify still advances one
        plain-step token, parity holds."""
        from mxnet_tpu.serve import DecodeServer, Drafter

        class WrongDrafter(Drafter):
            def propose(self, history, k):
                return [96] * k          # never the greedy argmax

        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           spec=True, drafter=WrongDrafter(),
                           autostart=False)
        p = _prompt(306, 4)
        ref = _ref(net, p, 12)
        assert 96 not in ref             # the premise of WrongDrafter
        s = srv.submit(p, max_new_tokens=12)
        _drain(srv)
        assert s.tokens(5) == ref
        assert s.draft_accepted == 0 and s.draft_rejected > 0
        assert s.accept_rate == 0.0
        srv.close()


# --------------------------------------------------------------------- #
# the drafter
# --------------------------------------------------------------------- #

class TestNGramDrafter:
    def test_longest_suffix_most_recent_match(self):
        from mxnet_tpu.serve import NGramDrafter
        d = NGramDrafter()
        # suffix [1,2,3] matched at position 0 -> propose what followed
        assert d.propose([1, 2, 3, 4, 1, 2, 3], 2) == [4, 1]
        # two matches: the MOST RECENT earlier occurrence wins
        assert d.propose([1, 2, 5, 1, 2, 6, 1, 2], 1) == [6]

    def test_no_repeat_proposes_nothing(self):
        from mxnet_tpu.serve import NGramDrafter
        d = NGramDrafter()
        assert d.propose([1, 2, 3, 4, 5], 4) == []
        assert d.propose([7], 4) == []
        assert d.propose([1, 2, 1, 2], 0) == []

    def test_window_bounds_the_scan(self):
        from mxnet_tpu.serve import NGramDrafter
        d = NGramDrafter(window=4)
        # the only match for suffix [1,2] is outside the 4-token window
        assert d.propose([1, 2, 9, 8, 7, 1, 2], 2) == []

    def test_bad_config_raises(self):
        from mxnet_tpu.serve import NGramDrafter
        with pytest.raises(ValueError):
            NGramDrafter(min_match=0)
        with pytest.raises(ValueError):
            NGramDrafter(min_match=3, max_match=2)


# --------------------------------------------------------------------- #
# bucketed verify ladder
# --------------------------------------------------------------------- #

class TestSpecBuckets:
    def test_verify_compiles_bounded_zero_retraces(self, net):
        """Draft-length churn is operand VALUES: verify programs are
        pinned to the k ladder x pool sizes, each compiled once, and a
        second wave of different draft lengths retraces nothing."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=True, autostart=False)
        label = srv.telemetry_label
        telemetry.clear_events()
        for wave in range(3):            # varied histories and budgets
            ss = [srv.submit(_prompt(320 + 10 * wave + i, 3 + i),
                             max_new_tokens=10 + 7 * i)
                  for i in range(2)]
            _drain(srv)
            for s in ss:
                s.tokens(5)
        comp = [e for e in telemetry.events("compile")
                if e.get("site") == "serve.verify"
                and e.get("server") == label]
        bound = len(srv.spec_sizes) * len(srv.pool_sizes)
        assert 0 < len(comp) <= bound, (len(comp), bound)
        assert not any(e.get("retrace") for e in comp)
        assert len({e["k_bucket"] for e in comp}) == len(comp)
        # the engine cache agrees: one program per used bucket, each
        # with exactly one traced signature
        assert len(srv._progs._verifies) == len(comp)
        for fn in srv._progs._verifies.values():
            assert fn._cache_size() == 1
        srv.close()

    def test_verify_bucket_validation(self, net):
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           spec=True, autostart=False)
        with pytest.raises(MXNetError, match=">= 1"):
            srv._progs.verify_fn(0)
        srv.close()

    def test_env_knobs(self, net, monkeypatch):
        from mxnet_tpu.serve import DecodeServer
        monkeypatch.setenv("MXNET_SERVE_SPEC", "0")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        assert srv.spec_enabled is False
        srv.close()
        monkeypatch.delenv("MXNET_SERVE_SPEC")

        monkeypatch.setenv("MXNET_SERVE_SPEC_DEPTH", "2")
        monkeypatch.setenv("MXNET_SERVE_SPEC_SIZES", "1,2")
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           autostart=False)
        assert srv.spec_enabled and srv.spec_depth == 2
        assert srv.spec_sizes == (1, 2)
        srv.close()

        monkeypatch.setenv("MXNET_SERVE_SPEC_DEPTH", "eight")
        with pytest.raises(MXNetError, match="MXNET_SERVE_SPEC_DEPTH"):
            DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                         autostart=False)

    def test_kwarg_validation(self, net):
        from mxnet_tpu.serve import DecodeServer
        with pytest.raises(MXNetError, match="spec_depth"):
            DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                         spec_depth=-1, autostart=False)
        with pytest.raises(MXNetError, match="spec_sizes"):
            DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                         spec_sizes=(4, 2), autostart=False)
        # depth clamps to the largest pinned verify width
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           spec_depth=64, spec_sizes=(1, 2, 4),
                           autostart=False)
        assert srv.spec_depth == 4
        srv.close()


# --------------------------------------------------------------------- #
# prefix cache co-residency
# --------------------------------------------------------------------- #

class TestSpecPrefixCache:
    def test_cow_hit_and_speculation_coresident_parity(self, net):
        """ISSUE 17 regression pin: a COW prefix hit and a speculating
        slot co-resident in one pool.  The hit slot's first decode step
        recomputes the final prompt position (its stream has no tokens
        for the drafter yet — the ramp), speculation joins only after,
        and BOTH streams stay bit-identical to the offline decode."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=True, autostart=False)
        p_hit = _prompt(340, 32)         # two full pages -> cacheable
        warm = srv.submit(p_hit, max_new_tokens=4)
        _drain(srv)
        assert warm.tokens(5) == _ref(net, p_hit, 4)
        srv.reset_counters()
        # the hit and a fresh speculating request share the pool
        s_hit = srv.submit(p_hit, max_new_tokens=12)
        s_new = srv.submit(_prompt(341, 5), max_new_tokens=16)
        _drain(srv)
        assert s_hit.tokens(5) == _ref(net, p_hit, 12)
        assert s_new.tokens(5) == _ref(net, _prompt(341, 5), 16)
        c = dict(srv.counters)
        assert c["prefix_hits"] == 1
        assert c["draft_accepted"] + c["draft_rejected"] \
            == c["draft_proposed"]
        srv.close()


# --------------------------------------------------------------------- #
# int8 quantized pool composition (ISSUE 18)
# --------------------------------------------------------------------- #

class TestInt8SpecComposition:
    def test_cow_chunk_and_spec_coresident_on_int8_pool(self, net):
        """ISSUE 18 satellite: every serving feature on ONE int8 pool —
        a COW prefix hit (zero admit dispatches), a chunked long-prompt
        prefill, and speculative verify, co-resident.  The draft ledger
        stays exact, tokens/dispatch clears the speculation bar, and
        both streams hold the pinned greedy agreement vs the f32
        reference (int8 is the repo's first lossy serving mode — the
        bar is PARITY.md's agreement tolerance, not bit-identity)."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           prefill_buckets=(8,), spec=True,
                           kv_dtype="int8", autostart=False)
        assert srv.stats()["kv_dtype"] == "int8"
        p_hit = _prompt(360, 32)         # two full pages -> cacheable
        p_long = _prompt(361, 21)        # > bucket 8 -> 3 chunk passes
        warm = srv.submit(p_hit, max_new_tokens=4)
        _drain(srv)
        assert len(warm.tokens(5)) == 4
        srv.reset_counters()
        s_hit = srv.submit(p_hit, max_new_tokens=16)
        s_long = srv.submit(p_long, max_new_tokens=20)
        _drain(srv)
        c = dict(srv.counters)
        # the hit admitted through the cache, the long prompt through
        # chunked prefill: no batched-admit dispatch ran at all
        assert c["prefix_hits"] == 1
        assert c["admit_dispatches"] == 0
        assert c["chunk_dispatches"] == 3
        # draft ledger exact, speculation live on the quantized pool
        assert c["verify_dispatches"] > 0
        assert c["draft_accepted"] > 0
        assert c["draft_accepted"] + c["draft_rejected"] \
            == c["draft_proposed"]
        total = len(s_hit.tokens(5)) + len(s_long.tokens(5))
        assert total == 36
        tpd = total / max(total - c["draft_accepted"], 1)
        assert tpd > 1.5, (tpd, c)
        # pinned greedy agreement vs the f32 offline decode (PARITY.md)
        for s, p, n in ((s_hit, p_hit, 16), (s_long, p_long, 20)):
            got, ref = s.tokens(5), _ref(net, p, n)
            agree = sum(int(a == b) for a, b in zip(got, ref)) / n
            assert agree >= 0.9, (agree, got, ref)
        srv.close()


# --------------------------------------------------------------------- #
# chaos: the serve.verify fault site
# --------------------------------------------------------------------- #

class TestSpecChaos:
    def test_verify_fault_fails_streams_cleanly(self, net, monkeypatch):
        """An injected failure on the FIRST speculative verify dispatch
        fails every in-flight stream with the underlying error and
        later submit()s raise cleanly — same contract as serve.step."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=True, autostart=False)
        p1, p2 = _prompt(350, 4), _prompt(351, 5)
        s1 = srv.submit(p1, max_new_tokens=12)
        s2 = srv.submit(p2, max_new_tokens=12)
        monkeypatch.setenv("MXNET_FAULT_INJECT", "serve.verify:raise:1")
        faults.reset_faults()
        srv.start()
        with pytest.raises(MXNetError, match="injected fault"):
            s1.tokens(30)
        with pytest.raises(MXNetError, match="injected fault"):
            s2.tokens(30)
        with pytest.raises(MXNetError, match="server failed"):
            srv.submit(p1, max_new_tokens=2)

    def test_cancel_mid_burst_coresident_exact(self, net):
        """cancel() between speculative bursts frees the slot at the
        next drain; the co-resident stream is token-identical and the
        slot is reusable."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(2,),
                           spec=True, autostart=False)
        pA, pB = _prompt(352, 5), _prompt(353, 4)
        sA = srv.submit(pA, max_new_tokens=20)
        sB = srv.submit(pB, max_new_tokens=20)
        for _ in range(3):               # mid-flight, bursts in the air
            srv.pump()
        assert not sB.done
        assert sB.cancel() is True
        _drain(srv)
        refB = _ref(net, pB, 20)
        assert sA.tokens(5) == _ref(net, pA, 20)     # co-resident exact
        got = sB.tokens(5)
        assert len(got) < 20 and got == refB[:len(got)]
        assert sB.cancelled
        pC = _prompt(354, 3)
        sC = srv.submit(pC, max_new_tokens=6)        # slot reusable
        _drain(srv)
        assert sC.tokens(5) == _ref(net, pC, 6)
        srv.close()

    def test_watchdog_mid_burst_fails_consumers(self, net):
        """A pump wedged mid-speculative-burst past step_timeout fires
        the watchdog: consumers get its error instead of blocking."""
        from mxnet_tpu.serve import DecodeServer
        srv = DecodeServer(net, max_total_len=64, pool_sizes=(1,),
                           spec=True, step_timeout=0.25,
                           autostart=False)
        # warm pump-driven first: a first-request compile would trip
        # the 0.25s timeout before any wedge is simulated
        w = srv.submit(_prompt(355, 4), max_new_tokens=8)
        _drain(srv)
        assert w.tokens(5) == _ref(net, _prompt(355, 4), 8)
        telemetry.clear_events()
        real_pump = srv.pump

        def wedged_pump():
            time.sleep(1.2)
            return real_pump()

        srv.pump = wedged_pump
        s = srv.submit(_prompt(356, 4), max_new_tokens=8)
        srv.start()
        with pytest.raises(MXNetError, match="watchdog"):
            s.tokens(30)
        assert any(e.get("server") == srv.telemetry_label
                   for e in telemetry.events("watchdog_fired"))


# --------------------------------------------------------------------- #
# the recording-side ledger (telemetry_report --check-serve)
# --------------------------------------------------------------------- #

class TestCheckServeLedger:
    def _base(self):
        return [{"kind": "serve_config", "server": "s", "sync_mode": 0,
                 "pool_sizes": [2], "admit_sizes": [1, 2],
                 "prefill_buckets": [8], "spec_sizes": [1, 2, 4]}]

    def test_balanced_ledger_passes(self):
        from tools.telemetry_report import check_serve
        evs = self._base() + [
            {"kind": "serve_spec", "server": "s", "k_bucket": 4,
             "proposed": 6, "accepted": 4, "rejected": 2},
            {"kind": "serve_stats", "server": "s", "steps": 3,
             "counters": {"step_dispatches": 3, "draft_proposed": 6,
                          "draft_accepted": 4, "draft_rejected": 2}},
        ]
        assert check_serve(evs) == []

    def test_unbalanced_events_fail(self):
        from tools.telemetry_report import check_serve
        evs = self._base() + [
            {"kind": "serve_spec", "server": "s", "k_bucket": 4,
             "proposed": 6, "accepted": 4, "rejected": 1},
        ]
        assert any("serve_spec" in f for f in check_serve(evs))

    def test_unbalanced_counters_fail(self):
        from tools.telemetry_report import check_serve
        evs = self._base() + [
            {"kind": "serve_stats", "server": "s",
             "counters": {"draft_proposed": 6, "draft_accepted": 5,
                          "draft_rejected": 2}},
        ]
        assert any("serve_stats counters" in f for f in check_serve(evs))

    def test_verify_ladder_overflow_fails(self):
        from tools.telemetry_report import check_serve
        evs = self._base() + [
            {"kind": "compile", "site": "serve.verify", "server": "s",
             "pool": 2, "k_bucket": k} for k in range(1, 5)
        ]
        # spec ladder bound = 3 sizes x 1 pool = 3 < 4 compiles
        assert any("verify compiles" in f for f in check_serve(evs))

    def test_verify_retrace_fails(self):
        from tools.telemetry_report import check_serve
        evs = self._base() + [
            {"kind": "compile", "site": "serve.verify", "server": "s",
             "pool": 2, "k_bucket": 2},
            {"kind": "compile", "site": "serve.verify", "server": "s",
             "pool": 2, "k_bucket": 2},
        ]
        assert any("retrace" in f for f in check_serve(evs))

    def test_pre_spec_recording_skips(self):
        """A recording from before speculation (no spec fields) passes
        every ledger check untouched."""
        from tools.telemetry_report import check_serve
        evs = [{"kind": "serve_config", "server": "s", "sync_mode": 0,
                "pool_sizes": [2], "admit_sizes": [1],
                "prefill_buckets": [8]},
               {"kind": "serve_stats", "server": "s", "steps": 2,
                "counters": {"step_dispatches": 2}}]
        assert check_serve(evs) == []


# --------------------------------------------------------------------- #
# the sweep runner
# --------------------------------------------------------------------- #

class TestTpuSweep:
    def test_dry_run_plans_both_benches(self):
        r = subprocess.run(
            [sys.executable, "benchmark/tpu_sweep.py", "--dry-run",
             "--smoke"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "decode_bench.py" in r.stdout
        assert "serve_bench.py" in r.stdout
        assert "dist_bench.py" in r.stdout
        assert "MXNET_TELEMETRY_JSONL=" in r.stdout
        assert "dry run: 0 of 3 benches executed" in r.stdout
