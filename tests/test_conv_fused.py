"""Fused 1x1-conv backward (ops/conv_fused.py): the Pallas dgrad+wgrad
single-pass kernel must match XLA autodiff exactly in interpret mode,
gate itself off unsupported shapes, and stay wired into the
``Convolution`` op's NHWC branch (VERDICT r4 item 1 escalation —
BASELINE.md ResNet section has the perf story)."""
import os

import numpy as onp
import pytest

os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # per-test (not module-level): other modules delete this env var in
    # their teardown, and _interpret() reads it at call time.  The
    # fused conv backward is an opt-in artifact (measured-negative,
    # BASELINE.md) — these tests opt in to keep the kernel green.
    monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    monkeypatch.setenv("MXNET_FUSED_CONV_BWD", "1")


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.ops.conv_fused import (  # noqa: E402
    _conv1x1_fwd_math, _pick_tile, conv1x1_nhwc, fused_bwd_supported)


@pytest.mark.parametrize("shape", [(2, 8, 8, 64, 256), (1, 4, 4, 128, 32),
                                   (4, 8, 8, 256, 64)])
def test_fused_bwd_matches_autodiff(shape):
    n, h, w_, ci, co = shape
    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, h, w_, ci), jnp.float32)
    w = jnp.asarray(rng.randn(co, ci, 1, 1) * 0.05, jnp.float32)
    assert fused_bwd_supported(x.shape, w.shape, (1, 1), (1, 1), 1)
    y1 = conv1x1_nhwc(x, w)
    y2 = _conv1x1_fwd_math(x, w)
    onp.testing.assert_allclose(y1, y2, rtol=1e-5)
    dy = jnp.asarray(rng.randn(*y1.shape), jnp.float32)
    dx1, dw1 = jax.vjp(conv1x1_nhwc, x, w)[1](dy)
    dx2, dw2 = jax.vjp(_conv1x1_fwd_math, x, w)[1](dy)
    onp.testing.assert_allclose(dx1, dx2, rtol=2e-4, atol=1e-4)
    onp.testing.assert_allclose(dw1, dw2, rtol=2e-4, atol=1e-3)
    assert dw1.dtype == w.dtype


def test_bf16_grads_close():
    rng = onp.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 8, 64), jnp.bfloat16)
    w = jnp.asarray(rng.randn(128, 64, 1, 1) * 0.05, jnp.bfloat16)
    dy = jnp.ones((2, 8, 8, 128), jnp.bfloat16)
    dx1, dw1 = jax.vjp(conv1x1_nhwc, x, w)[1](dy)
    dx2, dw2 = jax.vjp(_conv1x1_fwd_math, x, w)[1](dy)
    onp.testing.assert_allclose(onp.asarray(dx1, onp.float32),
                                onp.asarray(dx2, onp.float32),
                                rtol=2e-2, atol=1e-2)
    # kernel accumulates dW in f32 — at least as accurate as XLA's bf16
    onp.testing.assert_allclose(onp.asarray(dw1, onp.float32),
                                onp.asarray(dw2, onp.float32),
                                rtol=2e-2, atol=2e-1)


def test_untileable_shape_falls_back():
    # P = 2*7*7 = 98 has no tile; the vjp must silently use XLA
    rng = onp.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 7, 7, 256), jnp.float32)
    w = jnp.asarray(rng.randn(64, 256, 1, 1) * 0.05, jnp.float32)
    assert _pick_tile(98, 256, 64) == 0
    assert not fused_bwd_supported(x.shape, w.shape, (1, 1), (1, 1), 1)
    dy = jnp.ones((2, 7, 7, 64), jnp.float32)
    dx1, dw1 = jax.vjp(conv1x1_nhwc, x, w)[1](dy)
    dx2, dw2 = jax.vjp(_conv1x1_fwd_math, x, w)[1](dy)
    onp.testing.assert_allclose(dx1, dx2, rtol=1e-5)
    onp.testing.assert_allclose(dw1, dw2, rtol=1e-5)


def test_gate_rejects_non_1x1():
    assert not fused_bwd_supported((2, 8, 8, 64), (64, 64, 3, 3),
                                   (1, 1), (1, 1), 1)
    assert not fused_bwd_supported((2, 8, 8, 64), (64, 64, 1, 1),
                                   (2, 2), (1, 1), 1)
    assert not fused_bwd_supported((2, 8, 8, 64), (64, 32, 1, 1),
                                   (1, 1), (1, 1), 2)


def test_resnet50_shapes_all_tile():
    """Every stride-1 1x1 of ResNet-50 at bench batch sizes must take
    the fused path (the perf claim rests on it)."""
    for bs in (128, 256):
        for (hw, ci, co) in [(56, 64, 256), (56, 256, 64),
                             (28, 128, 512), (28, 512, 128),
                             (14, 256, 1024), (14, 1024, 256),
                             (7, 512, 2048), (7, 2048, 512)]:
            p = bs * hw * hw
            assert _pick_tile(p, ci, co) > 0, (bs, hw, ci, co)


def test_convolution_op_routes_nhwc_1x1():
    """The registered Convolution op's NHWC branch must hit the fused
    path (monkeypatch-observe the gate) and produce identical values."""
    from mxnet_tpu.ops import conv_fused, nn as nn_ops

    rng = onp.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 8, 8, 64), jnp.float32)
    w = jnp.asarray(rng.randn(32, 64, 1, 1) * 0.1, jnp.float32)
    calls = []
    orig = conv_fused.conv1x1_nhwc

    def spy(*a):
        calls.append(1)
        return orig(*a)

    old = conv_fused.conv1x1_nhwc
    conv_fused.conv1x1_nhwc = spy
    try:
        out = nn_ops.Convolution.__wrapped__(
            x, w, kernel=(1, 1), num_filter=32, no_bias=True,
            layout="NHWC")
    finally:
        conv_fused.conv1x1_nhwc = old
    assert calls, "NHWC 1x1 did not route through the fused kernel"
    ref = _conv1x1_fwd_math(x, w)
    onp.testing.assert_allclose(out, ref, rtol=1e-5)
