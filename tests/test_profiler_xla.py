"""Trace-parsing device profiler (SURVEY.md §5.1 — per-op aggregate table
recovered inside fused jit steps)."""
import glob
import gzip
import json
import os

import pytest

from mxnet_tpu import profiler_xla


def _fake_trace(tmp_path, events):
    session = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    session.mkdir(parents=True)
    with gzip.open(session / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def _device_meta():
    return [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 701, "name": "process_name",
         "args": {"name": "/host:CPU"}},
    ]


def test_parse_trace_device_lane_only(tmp_path):
    events = _device_meta() + [
        # device op with full args
        {"ph": "X", "pid": 3, "tid": 3, "ts": 0, "dur": 12.6,
         "name": "fusion",
         "args": {"device_duration_ps": "12600000",
                  "hlo_category": "convolution fusion",
                  "model_flops": "2147483648",
                  "raw_bytes_accessed": "6291456",
                  "tf_op": "jit(step)/dot_general:"}},
        # host event on a python thread — must be skipped
        {"ph": "X", "pid": 701, "tid": 1, "ts": 0, "dur": 99.0,
         "name": "PjitFunction(step)"},
        # device event on a non-op lane (XLA Modules) — skipped
        {"ph": "X", "pid": 3, "tid": 2, "ts": 0, "dur": 50.0,
         "name": "jit_step(123)"},
    ]
    recs = profiler_xla.parse_trace(_fake_trace(tmp_path, events))
    assert len(recs) == 1
    r = recs[0]
    assert r["name"] == "fusion"
    assert r["category"] == "convolution fusion"
    assert abs(r["dur_us"] - 12.6) < 1e-6      # ps field preferred
    assert r["flops"] == 2147483648
    assert r["bytes"] == 6291456
    assert r["tf_op"].startswith("jit(step)")


def test_aggregate_and_format(tmp_path):
    events = _device_meta() + [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 0, "dur": 10.0,
         "name": "fusion", "args": {
             "device_duration_ps": "10000000", "hlo_category": "fusion",
             "model_flops": "1000000000", "raw_bytes_accessed": "1000",
             "tf_op": "jit(f)/dot_general:"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 20, "dur": 30.0,
         "name": "fusion.1", "args": {
             "device_duration_ps": "30000000", "hlo_category": "fusion",
             "model_flops": "0", "raw_bytes_accessed": "4000",
             "tf_op": "jit(f)/add:"}},
    ]
    recs = profiler_xla.parse_trace(_fake_trace(tmp_path, events))
    by_cat = profiler_xla.aggregate(recs, by="category")
    assert len(by_cat) == 1 and by_cat[0]["calls"] == 2
    assert abs(by_cat[0]["dur_us"] - 40.0) < 1e-6
    assert abs(by_cat[0]["pct"] - 100.0) < 1e-6

    by_op = profiler_xla.aggregate(recs, by="tf_op")
    assert [r["key"] for r in by_op] == ["jit(f)/add:", "jit(f)/dot_general:"]
    # achieved TFLOP/s: 1e9 flops / 10 us = 1e14 flops/s = 100 TFLOP/s
    assert abs(by_op[1]["tflops"] - 100.0) < 1e-6

    table = profiler_xla.format_table(by_op, peak_tflops=197.0)
    assert "jit(f)/add:" in table and "TOTAL" in table and "MFU%" in table


def test_latest_session_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        profiler_xla.latest_session(str(tmp_path))


def test_profile_fn_cpu_no_crash():
    """On CPU the trace has no TPU device lane — profile_fn must still
    run the function and return a (possibly empty) record list."""
    import jax.numpy as jnp
    import jax

    f = jax.jit(lambda x: (x * 2).sum())
    recs = profiler_xla.profile_fn(f, jnp.ones((8, 8)), iters=1)
    assert isinstance(recs, list)


def test_profiler_facade_device_dumps(tmp_path, monkeypatch):
    """mx.profiler.device_dumps() renders the table for the last window."""
    from mxnet_tpu import profiler

    events = _device_meta() + [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 0, "dur": 5.0,
         "name": "fusion", "args": {
             "device_duration_ps": "5000000", "hlo_category": "fusion",
             "model_flops": "0", "raw_bytes_accessed": "128",
             "tf_op": "jit(f)/mul:"}},
    ]
    td = _fake_trace(tmp_path, events)
    monkeypatch.setitem(profiler._state, "trace_dir", td)
    out = profiler.device_dumps(by="tf_op")
    assert "jit(f)/mul:" in out


# --------------------------------------------------------------------- #
# static HLO op counting (count_hlo_ops / hlo_op_count)
# --------------------------------------------------------------------- #

_HLO_SAMPLE = """\
HloModule jit_f, is_scheduled=true

%region_0.1 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add.1 = f32[] add(f32[] %a, f32[] %b)
}

%fused_computation (p0: f32[2,4]) -> f32[2,4] {
  %p0 = f32[2,4]{1,0} parameter(0)
  %c = f32[] constant(2)
  %bc = f32[2,4]{1,0} broadcast(f32[] %c), dimensions={}
  ROOT %mul.0 = f32[2,4]{1,0} multiply(f32[2,4]{1,0} %p0, f32[2,4]{1,0} %bc)
}

%body.2 (t: (s32[], f32[2,4])) -> (s32[], f32[2,4]) {
  %t = (s32[], f32[2,4]{1,0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[2,4]{1,0}) %t), index=0
  %x = f32[2,4]{1,0} get-tuple-element((s32[], f32[2,4]{1,0}) %t), index=1
  %one = s32[] constant(1)
  %ip = s32[] add(s32[] %i, s32[] %one)
  %fus = f32[2,4]{1,0} fusion(f32[2,4]{1,0} %x), kind=kLoop, calls=%fused_computation
  %z = f32[] constant(0)
  %red = f32[2]{0} reduce(f32[2,4]{1,0} %fus, f32[] %z), dimensions={1}, to_apply=%region_0.1
  %bcast.0 = f32[2,4]{1,0} broadcast(f32[2]{0} %red), dimensions={0}
  ROOT %tup = (s32[], f32[2,4]{1,0}) tuple(s32[] %ip, f32[2,4]{1,0} %bcast.0)
}

%cond.3 (t: (s32[], f32[2,4])) -> pred[] {
  %t = (s32[], f32[2,4]{1,0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[2,4]{1,0}) %t), index=0
  %n = s32[] constant(4)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %n), direction=LT
}

ENTRY %main.4 (arg: f32[2,4]) -> f32[2,4] {
  %arg = f32[2,4]{1,0} parameter(0)
  %zero = s32[] constant(0)
  %tup.0 = (s32[], f32[2,4]{1,0}) tuple(s32[] %zero, f32[2,4]{1,0} %arg)
  %wh = (s32[], f32[2,4]{1,0}) while((s32[], f32[2,4]{1,0}) %tup.0), condition=%cond.3, body=%body.2
  ROOT %out = f32[2,4]{1,0} get-tuple-element((s32[], f32[2,4]{1,0}) %wh), index=1
}
"""


def test_count_hlo_ops_convention():
    """Fusion bodies and reduce combinators are excluded (they execute
    as ONE op in their caller), while bodies/conds count once, and
    parameter/constant/tuple plumbing is free.  Sample counts: body.2
    has add+fusion+reduce+broadcast = 4, cond.3 has compare = 1, entry
    has while = 1."""
    assert profiler_xla.count_hlo_ops(_HLO_SAMPLE) == 6


def test_hlo_op_count_scan_collapses_unrolled_loop():
    """The API motivation in miniature: a scanned body compiles to one
    body's worth of instructions regardless of trip count; the unrolled
    loop grows with it."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def unrolled(x, w):
        for i in range(8):
            x = jnp.tanh(x @ w[i])
        return x

    def scanned(x, w):
        return lax.scan(lambda x, wi: (jnp.tanh(x @ wi), None), x, w)[0]

    x = jax.ShapeDtypeStruct((2, 16), jnp.float32)
    w = jax.ShapeDtypeStruct((8, 16, 16), jnp.float32)
    n_unrolled = profiler_xla.hlo_op_count(unrolled, x, w)
    n_scanned = profiler_xla.hlo_op_count(jax.jit(scanned), x, w)
    assert n_scanned < n_unrolled
    assert n_unrolled >= 8  # at least one dot per unrolled layer
