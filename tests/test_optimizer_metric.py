"""Optimizer, lr_scheduler, initializer, and metric tests (reference model:
tests/python/unittest/test_optimizer.py / test_metric.py)."""
import math

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt_mod


def _nd(x):
    return mx.nd.array(onp.asarray(x, onp.float32))


def _run_steps(opt, w0, grads):
    w = _nd(w0)
    state = opt.create_state_multi_precision(0, w)
    for g in grads:
        state = opt.update_multi_precision(0, w, _nd(g), state)
    return w.asnumpy()


def test_sgd_matches_formula():
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9, wd=0.01)
    w = _run_steps(opt, [1.0, 2.0], [[0.5, 0.5], [0.5, 0.5]])
    # manual
    wm = onp.array([1.0, 2.0])
    mom = onp.zeros(2)
    for _ in range(2):
        g = onp.array([0.5, 0.5]) + 0.01 * wm
        mom = 0.9 * mom - 0.1 * g
        wm = wm + mom
    onp.testing.assert_allclose(w, wm, rtol=1e-6)


def test_adam_matches_formula():
    opt = opt_mod.Adam(learning_rate=0.01)
    w = _run_steps(opt, [1.0], [[0.1]] * 3)
    wm, m, v = onp.array([1.0]), onp.zeros(1), onp.zeros(1)
    for t in range(1, 4):
        g = onp.array([0.1])
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        lr_t = 0.01 * math.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        wm = wm - lr_t * m / (onp.sqrt(v) + 1e-8)
    onp.testing.assert_allclose(w, wm, rtol=1e-6)


@pytest.mark.parametrize("name", ["sgd", "nag", "adam", "adamw", "lamb",
                                  "lars", "rmsprop", "adagrad", "adadelta",
                                  "ftrl", "ftml", "signum", "nadam"])
def test_all_optimizers_reduce_quadratic(name):
    """Each optimizer must make progress on f(w) = ||w||^2 / 2."""
    opt = opt_mod.create(name)
    w = _nd(onp.ones(4))
    state = opt.create_state_multi_precision(0, w)
    for _ in range(30):
        g = mx.nd.array(w.asnumpy())  # grad of quadratic
        state = opt.update_multi_precision(0, w, g, state)
    assert onp.linalg.norm(w.asnumpy()) < onp.linalg.norm(onp.ones(4))


def test_multi_precision_master_weights():
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = mx.nd.array(onp.ones(3)).astype("float16")
    state = opt.create_state_multi_precision(0, w)
    assert state[0].dtype == onp.float32  # master copy
    g = mx.nd.array(onp.full(3, 0.1)).astype("float16")
    opt.update_multi_precision(0, w, g, state)
    assert w.dtype == onp.float16


def test_lr_schedulers():
    s = opt_mod.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(0) == 1.0
    assert s(10) == 0.5
    assert s(25) == 0.25
    m = opt_mod.MultiFactorScheduler(step=[5, 15], factor=0.1, base_lr=1.0)
    assert m(4) == 1.0
    assert abs(m(5) - 0.1) < 1e-12
    assert abs(m(20) - 0.01) < 1e-12
    c = opt_mod.CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert abs(c(0) - 1.0) < 1e-12
    assert abs(c(50) - 0.5) < 1e-12
    assert c(100) == 0.0
    p = opt_mod.PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
    assert abs(p(0) - 1.0) < 1e-12
    assert p(100) == 0.0
    # warmup
    ws = opt_mod.FactorScheduler(step=100, base_lr=1.0, warmup_steps=10,
                                 warmup_begin_lr=0.0)
    assert ws(5) == 0.5


def test_scheduler_in_optimizer():
    sched = opt_mod.FactorScheduler(step=1, factor=0.5, base_lr=0.4)
    opt = opt_mod.SGD(learning_rate=0.4, lr_scheduler=sched)
    w = _nd([1.0])
    s = opt.create_state(0, w)
    opt.update(0, w, _nd([1.0]), s)
    assert opt.learning_rate == 0.4 * 0.5  # stepped once


def test_initializers():
    import jax
    key = jax.random.PRNGKey(0)
    x = mx.init.Xavier(rnd_type="gaussian").generate("w_weight", key,
                                                     (64, 32))
    assert x.shape == (64, 32)
    assert abs(float(x.std()) - math.sqrt(3.0 / 48)) < 0.05
    o = mx.init.Orthogonal().generate("w_weight", key, (16, 16))
    q = onp.asarray(o) / 1.414
    onp.testing.assert_allclose(q @ q.T, onp.eye(16), atol=1e-4)
    b = mx.init.Normal().generate("fc_bias", key, (8,))
    onp.testing.assert_allclose(onp.asarray(b), onp.zeros(8))
    g = mx.init.Uniform().generate("bn_gamma", key, (8,))
    onp.testing.assert_allclose(onp.asarray(g), onp.ones(8))


def test_metrics_accuracy():
    m = mx.metric.Accuracy()
    pred = _nd([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])
    label = _nd([0, 1, 1])
    m.update([label], [pred])
    assert m.get() == ("accuracy", 2.0 / 3)


def test_metrics_topk_f1_mse():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = _nd([[0.2, 0.5, 0.3], [0.7, 0.2, 0.1]])
    m.update([_nd([2, 2])], [pred])
    assert m.get()[1] == 0.5

    f1 = mx.metric.F1()
    f1.update([_nd([1, 0, 1, 1])], [_nd([0.9, 0.2, 0.8, 0.3])])
    prec, rec = 2 / 2, 2 / 3
    assert abs(f1.get()[1] - 2 * prec * rec / (prec + rec)) < 1e-9

    mse = mx.metric.MSE()
    mse.update([_nd([1.0, 2.0])], [_nd([1.5, 2.0])])
    assert abs(mse.get()[1] - 0.125) < 1e-7


def test_metric_composite_and_create():
    m = mx.metric.create(["acc", "ce"])
    pred = _nd([[0.9, 0.1]])
    m.update([_nd([0])], [pred])
    names, values = m.get()
    assert "accuracy" in names

    cm = mx.metric.np(lambda l, p: float((l == p.argmax(-1)).mean()))
    cm.update([_nd([0])], [pred])
    assert cm.get()[1] == 1.0


def test_perplexity():
    m = mx.metric.Perplexity(ignore_label=None)
    pred = _nd([[0.5, 0.5], [0.25, 0.75]])
    m.update([_nd([0, 1])], [pred])
    expect = math.exp(-(math.log(0.5) + math.log(0.75)) / 2)
    assert abs(m.get()[1] - expect) < 1e-6
