"""Llama family (BASELINE config 5 second architecture): RoPE, RMSNorm,
GQA, SwiGLU, TP sharding, training convergence."""
import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel
from mxnet_tpu.models import Llama, LlamaConfig, llama_tiny, llama_tp_rules


def _net(seed=0, **overrides):
    mx.random.seed(seed)
    net, cfg = llama_tiny(**overrides)
    net.initialize(mx.init.Normal(0.02))
    return net, cfg


class TestRoPE:
    def test_norm_preserving(self):
        """Rotations preserve per-pair L2 norms."""
        x = onp.random.RandomState(0).randn(1, 2, 8, 16).astype("float32")
        out = nd.rope(nd.array(x)).asnumpy()
        onp.testing.assert_allclose(
            onp.linalg.norm(out, axis=-1),
            onp.linalg.norm(x, axis=-1), rtol=1e-5)
        # position 0 is the identity rotation
        onp.testing.assert_allclose(out[:, :, 0], x[:, :, 0], rtol=1e-6)

    def test_relative_position_property(self):
        """q·k after RoPE depends only on the position DIFFERENCE."""
        rng = onp.random.RandomState(1)
        q = rng.randn(1, 1, 1, 32).astype("float32")
        k = rng.randn(1, 1, 1, 32).astype("float32")

        def dot_at(pq, pk):
            qr = nd.rope(nd.array(q), position_offset=pq).asnumpy()
            kr = nd.rope(nd.array(k), position_offset=pk).asnumpy()
            return float((qr * kr).sum())

        assert dot_at(3, 1) == pytest.approx(dot_at(7, 5), rel=1e-4)
        assert dot_at(3, 1) != pytest.approx(dot_at(3, 2), rel=1e-3)

    def test_position_offset_matches_slice(self):
        """rope(x, offset=k) == rope(full)[, k:] — the KV-decode contract."""
        x = onp.random.RandomState(2).randn(1, 1, 10, 8).astype("float32")
        full = nd.rope(nd.array(x)).asnumpy()
        part = nd.rope(nd.array(x[:, :, 4:]), position_offset=4).asnumpy()
        onp.testing.assert_allclose(part, full[:, :, 4:], rtol=1e-5)


class TestLlamaModel:
    def test_forward_shape_and_finite(self):
        net, cfg = _net()
        toks = onp.random.RandomState(0).randint(0, cfg.vocab_size, (2, 12))
        out = net(nd.array(toks))
        assert out.shape == (2, 12, cfg.vocab_size)
        assert onp.isfinite(out.asnumpy()).all()

    def test_gqa_param_shapes(self):
        net, cfg = _net()
        d = cfg.units // cfg.num_heads
        kshape = [p.shape for n, p in net.collect_params().items()
                  if "attn_k_weight" in n][0]
        assert kshape == (cfg.num_kv_heads * d, cfg.units)
        qshape = [p.shape for n, p in net.collect_params().items()
                  if "attn_q_weight" in n][0]
        assert qshape == (cfg.units, cfg.units)

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        net, cfg = _net()
        toks = onp.random.RandomState(3).randint(0, cfg.vocab_size, (1, 8))
        a = net(nd.array(toks)).asnumpy()
        toks2 = toks.copy()
        toks2[0, -1] = (toks2[0, -1] + 7) % cfg.vocab_size
        b = net(nd.array(toks2)).asnumpy()
        onp.testing.assert_allclose(a[0, :7], b[0, :7], rtol=2e-4,
                                    atol=2e-5)
        assert not onp.allclose(a[0, 7], b[0, 7], rtol=1e-3)

    def test_training_reduces_loss(self):
        net, cfg = _net()
        mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        tr = parallel.SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
            {"learning_rate": 3e-3}, mesh=mesh)
        rng = onp.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size, (4, 17))
        d, l = toks[:, :-1], toks[:, 1:]
        losses = [float(onp.asarray(tr.step(nd.array(d), nd.array(l))
                                    .asnumpy()).reshape(()))
                  for _ in range(12)]
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

    def test_tp_sharded_train_step(self):
        """Megatron-style llama_tp_rules over a dp×tp mesh: one step,
        finite loss, q weights actually sharded over tp."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        net, cfg = _net()
        mesh = parallel.make_mesh({"dp": 2, "tp": 4})
        tr = parallel.SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
            {"learning_rate": 1e-3}, mesh=mesh,
            rules=llama_tp_rules("tp"))
        rng = onp.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size, (4, 9))
        loss = tr.step(nd.array(toks[:, :-1]), nd.array(toks[:, 1:]))
        assert onp.isfinite(float(onp.asarray(loss.asnumpy())
                                  .reshape(())))
        qw = [p for n, p in net.collect_params().items()
              if "attn_q_weight" in n][0]._data._data
        assert len({s.device for s in qw.addressable_shards}) == 8

    def test_generate(self):
        net, cfg = _net()
        prompt = onp.random.RandomState(4).randint(0, cfg.vocab_size,
                                                   (2, 3))
        out = net.generate(prompt, max_new_tokens=5, temperature=0.0)
        assert out.shape == (2, 8)
        onp.testing.assert_array_equal(out[:, :3], prompt)
        assert ((0 <= out) & (out < cfg.vocab_size)).all()

    def test_bf16_forward(self):
        net, cfg = _net(dtype="bfloat16")
        toks = onp.random.RandomState(5).randint(0, cfg.vocab_size, (1, 8))
        out = net(nd.array(toks))
        assert onp.isfinite(out.asnumpy().astype("float32")).all()

    def test_config_param_count(self):
        _net_, cfg = _net()
        total = sum(p.data().size
                    for p in _net_.collect_params().values())
        assert total == cfg.num_params, (total, cfg.num_params)


def test_rmsnorm_axis_not_last():
    """RMSNorm with axis != -1 must reshape gamma to the normalized axis
    (review regression)."""
    import jax.numpy as jnp
    x = onp.random.RandomState(0).randn(2, 8, 16).astype("float32")
    g = onp.random.RandomState(1).rand(8).astype("float32") + 0.5
    out = nd.RMSNorm(nd.array(x), nd.array(g), axis=1).asnumpy()
    ms = (x ** 2).mean(axis=1, keepdims=True)
    ref = x / onp.sqrt(ms + 1e-6) * g[None, :, None]
    onp.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestLlamaKVDecode:
    def test_greedy_matches_full_recompute(self):
        from mxnet_tpu.models import kv_generate
        net, cfg = _net()
        prompt = onp.random.RandomState(6).randint(0, cfg.vocab_size,
                                                   (2, 4))
        ref = net.generate(prompt, max_new_tokens=10, temperature=0.0)
        out = kv_generate(net, prompt, max_new_tokens=10, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_gqa_cache_shape_and_sampling(self):
        """kv cache carries KV (not H) heads; sampled decode is
        deterministic per seed."""
        from mxnet_tpu.models import kv_generate
        net, cfg = _net()
        assert cfg.num_kv_heads < cfg.num_heads  # llama_tiny is GQA
        prompt = onp.random.RandomState(7).randint(0, cfg.vocab_size,
                                                   (1, 3))
        a = kv_generate(net, prompt, max_new_tokens=6, temperature=0.9,
                        top_k=7, seed=11)
        b = kv_generate(net, prompt, max_new_tokens=6, temperature=0.9,
                        top_k=7, seed=11)
        onp.testing.assert_array_equal(a, b)
        assert a.shape == (1, 9)
