"""Error-path probes (the verify skill's 'worthwhile probes' + reference
error-semantics parity): clear MXNetError diagnostics instead of silent
corruption or raw jax tracebacks."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.base import MXNetError


class TestErrorPaths:
    def test_double_backward_without_retain_raises(self):
        x = mx.nd.array(onp.ones(3, onp.float32))
        x.attach_grad()
        with autograd.record():
            y = (x * x).sum()
        y.backward()
        with pytest.raises(MXNetError):
            y.backward()

    def test_corrupt_params_file(self, tmp_path):
        p = tmp_path / "bad.params"
        p.write_bytes(b"\x00" * 64)
        with pytest.raises(MXNetError, match="magic"):
            mx.nd.load(str(p))

    def test_out_of_range_context(self):
        import jax
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        if accel:
            with pytest.raises(MXNetError):
                mx.tpu(len(accel) + 5).jax_device()
        else:
            # documented graceful degrade: no accelerator -> host device
            assert mx.tpu(99).jax_device().platform == "cpu"

    def test_uninitialized_parameter_data(self):
        from mxnet_tpu.gluon import Parameter
        p = Parameter("w", shape=(3,))
        with pytest.raises(MXNetError):
            p.data()

    def test_kvstore_unknown_type(self):
        with pytest.raises(MXNetError):
            mx.kv.create("bogus")

    def test_kvstore_push_uninit_key(self):
        kv = mx.kv.create("local")
        with pytest.raises(MXNetError):
            kv.push(42, mx.nd.ones(2))

    def test_shape_mismatch_load_parameters(self, tmp_path):
        from mxnet_tpu import gluon
        a = gluon.nn.Dense(4, in_units=3)
        a.initialize()
        f = str(tmp_path / "p.params")
        a.save_parameters(f)
        b = gluon.nn.Dense(4, in_units=3)
        b.initialize()
        b.load_parameters(f)  # ok
        c = gluon.nn.Dense(4, in_units=5)
        c.initialize()
        with pytest.raises(Exception):
            c.load_parameters(f)

    def test_naive_engine_mode_still_correct(self, monkeypatch):
        monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
        a = mx.nd.array(onp.arange(6, dtype=onp.float32).reshape(2, 3))
        out = mx.nd.dot(a, a.T)
        onp.testing.assert_allclose(
            out.asnumpy(), a.asnumpy() @ a.asnumpy().T, rtol=1e-6)

    def test_seeded_reproducibility(self):
        mx.random.seed(42)
        a = mx.nd.random_normal(shape=(4,)).asnumpy()
        mx.random.seed(42)
        b = mx.nd.random_normal(shape=(4,)).asnumpy()
        onp.testing.assert_array_equal(a, b)


def test_gpu_memory_info_gauge():
    """HBM gauge (reference mx.context.gpu_memory_info): returns a
    (free, total) pair; free <= total; on accelerator-less backends the
    total degrades to 0 rather than raising (no HBM to gauge)."""
    import mxnet_tpu as mx
    free, total = mx.context.gpu_memory_info(0)
    assert isinstance(free, int) and isinstance(total, int)
    assert free >= 0 and total >= 0
    assert free <= total or total == 0
