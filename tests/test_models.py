"""Transformer model-family tests (tiny configs, CPU mesh)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.models import (GPT, GPTConfig, BERTModel, BERTConfig,
                              MultiHeadAttention, gpt_tp_rules)


def _tiny_gpt():
    return GPT(GPTConfig(vocab_size=97, max_length=32, num_layers=2,
                         units=32, num_heads=4, hidden_size=64))


def _tokens(B=2, L=16, vocab=97, seed=0):
    return onp.random.RandomState(seed).randint(0, vocab, size=(B, L))


def test_mha_shapes_and_grad():
    mx.random.seed(0)
    mha = MultiHeadAttention(32, 4, causal=True)
    mha.initialize()
    x = mx.nd.array(onp.random.randn(2, 8, 32).astype("float32"))
    x.attach_grad()
    with autograd.record():
        y = mha(x)
        loss = (y * y).sum()
    loss.backward()
    assert y.shape == (2, 8, 32)
    assert onp.isfinite(x.grad.asnumpy()).all()


def test_gpt_forward_and_causality():
    mx.random.seed(0)
    net = _tiny_gpt()
    net.initialize()
    toks = _tokens()
    out = net(mx.nd.array(toks))
    assert out.shape == (2, 16, 97)
    # causality: changing a future token must not affect earlier logits
    toks2 = toks.copy()
    toks2[:, 10:] = (toks2[:, 10:] + 1) % 97
    out2 = net(mx.nd.array(toks2))
    onp.testing.assert_allclose(out.asnumpy()[:, :10],
                                out2.asnumpy()[:, :10], rtol=1e-5,
                                atol=1e-5)
    assert not onp.allclose(out.asnumpy()[:, 10:], out2.asnumpy()[:, 10:])


def test_gpt_hybridize_consistent():
    mx.random.seed(0)
    net = _tiny_gpt()
    net.initialize()
    toks = mx.nd.array(_tokens())
    eager = net(toks).asnumpy()
    net.hybridize()
    jitted = net(toks).asnumpy()
    onp.testing.assert_allclose(eager, jitted, rtol=1e-5, atol=1e-5)


def test_gpt_trains_imperative():
    mx.random.seed(0)
    net = _tiny_gpt()
    net.initialize()
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    toks = _tokens(B=4, L=16)
    data, label = toks[:, :-1], toks[:, 1:]
    losses = []
    for _ in range(10):
        with autograd.record():
            logits = net(mx.nd.array(data))
            L = loss_fn(logits, mx.nd.array(label)).mean()
        L.backward()
        trainer.step(1)
        losses.append(L.asnumpy().item())
    assert losses[-1] < losses[0], losses


def test_gpt_spmd_tp_dp():
    """Flagship path: GPT trained by the fused SPMD step on a dp×tp mesh."""
    from mxnet_tpu import parallel
    mx.random.seed(0)
    net = _tiny_gpt()
    net.initialize()
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    tr = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
        {"learning_rate": 3e-3}, mesh=mesh, rules=gpt_tp_rules("tp"))
    toks = _tokens(B=4, L=16)
    data, label = toks[:, :-1], toks[:, 1:]
    losses = [float(tr.step(mx.nd.array(data),
                            mx.nd.array(label)).asnumpy().item())
              for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_bert_forward_masking():
    mx.random.seed(0)
    cfg = BERTConfig(vocab_size=101, max_length=32, num_layers=2,
                     units=32, num_heads=4, hidden_size=64)
    net = BERTModel(cfg)
    net.initialize()
    toks = _tokens(B=2, L=16, vocab=101)
    types = onp.zeros((2, 16), "int32")
    vlen = onp.array([16, 10])
    seq, pooled, mlm = net(mx.nd.array(toks), mx.nd.array(types),
                           mx.nd.array(vlen))
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)
    assert mlm.shape == (2, 16, 101)
    # masked positions must not influence valid ones: change a padded token
    toks2 = toks.copy()
    toks2[1, 12] = (toks2[1, 12] + 1) % 101
    seq2, _, _ = net(mx.nd.array(toks2), mx.nd.array(types),
                     mx.nd.array(vlen))
    onp.testing.assert_allclose(seq.asnumpy()[1, :10],
                                seq2.asnumpy()[1, :10], rtol=1e-5,
                                atol=1e-5)


def test_gpt_generate():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    cfg = GPTConfig(vocab_size=64, max_length=32, num_layers=2, units=32,
                    num_heads=4, hidden_size=64)
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    prompt = mx.nd.array(onp.array([[1, 2, 3], [4, 5, 6]]), dtype="int32")
    g1 = net.generate(prompt, max_new_tokens=5, temperature=0.0)
    g2 = net.generate(prompt, max_new_tokens=5, temperature=0.0)
    assert g1.shape == (2, 8)
    onp.testing.assert_array_equal(g1, g2)  # greedy is deterministic
    sampled = net.generate(prompt, max_new_tokens=4, temperature=1.0,
                           top_k=5, seed=3)
    assert sampled.shape == (2, 7)
    onp.testing.assert_array_equal(sampled[:, :3], prompt.asnumpy())


def test_seq2seq_learns_copy_task():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.models import TransformerSeq2Seq
    onp.random.seed(0)
    mx.random.seed(0)
    net = TransformerSeq2Seq(vocab_size=50, units=32, hidden_size=64,
                             num_heads=4, num_enc_layers=2, num_dec_layers=2,
                             max_length=16, dropout=0.0)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    seq = onp.random.randint(3, 50, (4, 7))
    src = mx.nd.array(seq, dtype="int32")
    tgt_in = mx.nd.array(onp.concatenate([onp.ones((4, 1)), seq[:, :-1]], 1),
                         dtype="int32")
    tgt_out = mx.nd.array(seq.astype(onp.float32))
    losses = []
    for _ in range(25):
        with autograd.record():
            L = loss_fn(net(src, tgt_in), tgt_out)
        L.backward()
        trainer.step(4)
        losses.append(float(onp.asarray(L.mean().asnumpy())))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
    dec = net.greedy_decode(src, max_len=8, bos=1, eos=2)
    assert dec.shape[0] == 4 and dec[0, 0] == 1


class TestKVCacheDecoding:
    """kv_generate (models/decoding.py): one-jit KV-cache decoder must
    reproduce the full-recompute GPT.generate exactly in greedy mode."""

    def _model(self):
        from mxnet_tpu.models import GPT, GPTConfig
        mx.random.seed(0)
        net = GPT(GPTConfig(vocab_size=97, max_length=64, num_layers=2,
                            units=32, num_heads=4, hidden_size=64))
        net.initialize(mx.init.Normal(0.02))
        return net

    def test_greedy_matches_full_recompute(self):
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(0).randint(0, 97, (2, 5))
        ref = net.generate(prompt, max_new_tokens=12, temperature=0.0)
        out = kv_generate(net, prompt, max_new_tokens=12, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_sampled_modes_run(self):
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(1).randint(0, 97, (1, 4))
        out = kv_generate(net, prompt, max_new_tokens=8, temperature=0.8,
                          top_k=5, seed=3)
        assert out.shape == (1, 12)
        assert (out[:, :4] == prompt).all()
        assert ((0 <= out) & (out < 97)).all()
        # deterministic per seed
        out2 = kv_generate(net, prompt, max_new_tokens=8, temperature=0.8,
                           top_k=5, seed=3)
        onp.testing.assert_array_equal(out, out2)

    def test_length_guard(self):
        from mxnet_tpu.models import kv_generate
        net = self._model()
        with pytest.raises(ValueError, match="max_length"):
            kv_generate(net, onp.zeros((1, 60), onp.int32),
                        max_new_tokens=10)

    def test_sampling_parity_with_full_recompute(self):
        """Sampled (temperature>0, top_k) decode must match a reference
        full-recompute loop that uses the identical fold_in/categorical
        sampler — not just greedy (VERDICT r2 item 8)."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(2).randint(0, 97, (2, 4))
        T, K, SEED = 0.7, 7, 11
        out = kv_generate(net, prompt, max_new_tokens=6, temperature=T,
                          top_k=K, seed=SEED)

        # reference: full-prefix recompute + the same documented sampler
        key0 = jax.random.PRNGKey(SEED)
        ref = onp.asarray(prompt, onp.int32)
        for t_ in range(prompt.shape[1] - 1, prompt.shape[1] + 5):
            logits = net(mx.nd.array(ref, dtype="int32")).asnumpy()
            lg = jnp.asarray(logits[:, -1].astype(onp.float32)) / T
            kth = jax.lax.top_k(lg, K)[0][:, -1]
            lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
            nxt = onp.asarray(jax.random.categorical(
                jax.random.fold_in(key0, t_), lg, axis=-1), onp.int32)
            ref = onp.concatenate([ref, nxt[:, None]], axis=1)
        onp.testing.assert_array_equal(out, ref)

    def test_batched_prefill_matches_scan_prefill(self):
        """prefill='batched' (one causal forward fills the cache) must
        emit the same token stream as the token-at-a-time scan prefill —
        greedy AND sampled (the per-position fold_in keys are shared)."""
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(3).randint(0, 97, (2, 6))
        for kw in (dict(temperature=0.0),
                   dict(temperature=0.8, top_k=5, seed=7)):
            a = kv_generate(net, prompt, max_new_tokens=9,
                            prefill="batched", **kw)
            b = kv_generate(net, prompt, max_new_tokens=9,
                            prefill="scan", **kw)
            onp.testing.assert_array_equal(a, b)

    def test_zero_new_tokens_is_identity(self):
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(8).randint(0, 97, (2, 5))
        for mode in ("batched", "scan"):
            out = kv_generate(net, prompt, max_new_tokens=0, prefill=mode)
            onp.testing.assert_array_equal(out, prompt)

    def test_single_new_token_batched(self):
        """N=1 means an empty decode scan — the prefill logits alone
        produce the one new token."""
        from mxnet_tpu.models import kv_generate
        net = self._model()
        prompt = onp.random.RandomState(4).randint(0, 97, (1, 5))
        ref = net.generate(prompt, max_new_tokens=1, temperature=0.0)
        out = kv_generate(net, prompt, max_new_tokens=1, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)

    def test_int8_weight_streaming(self):
        """weights='int8': per-channel weight-only quantization.  The
        path is documented-approximate, so assert (a) runs/shape/
        determinism, (b) the quantized logits stay close to native — via
        the _quantize_rows error bound on a real layer weight."""
        import jax.numpy as jnp
        from mxnet_tpu.models import kv_generate
        from mxnet_tpu.models.decoding import _quantize_rows
        net = self._model()
        prompt = onp.random.RandomState(6).randint(0, 97, (2, 5))
        out = kv_generate(net, prompt, max_new_tokens=8, temperature=0.0,
                          weights="int8")
        assert out.shape == (2, 13)
        assert (out[:, :5] == prompt).all()
        out2 = kv_generate(net, prompt, max_new_tokens=8, temperature=0.0,
                           weights="int8")
        onp.testing.assert_array_equal(out, out2)
        # quantization error bound: per-channel int8 reconstruction of a
        # real weight is within half a quantization step of the original
        # (codes come back transposed (in, out) for the streaming kernel)
        w = net.blocks[0].attn.qkv.weight.data()._data
        wt, s = _quantize_rows(w)
        recon = onp.asarray(wt, onp.float32).T * onp.asarray(s)[:, None]
        err = onp.abs(recon - onp.asarray(w, onp.float32)).max(axis=1)
        bound = onp.asarray(s) * 0.5 + 1e-6
        assert (err <= bound).all()

    def test_int8_llama_family(self):
        """int8 weight streaming covers the Llama family too (split
        q/k/v/o projections, GQA kv heads, SwiGLU mlp): runs, keeps the
        prompt, deterministic across calls."""
        from mxnet_tpu.models import Llama, LlamaConfig, kv_generate
        mx.random.seed(0)
        net = Llama(LlamaConfig(vocab_size=64, max_length=32, num_layers=2,
                                units=32, num_heads=4, num_kv_heads=2,
                                hidden_size=64))
        net.initialize(mx.init.Normal(0.05))
        prompt = onp.random.RandomState(0).randint(0, 64, (2, 4))
        out = kv_generate(net, prompt, max_new_tokens=6, temperature=0.0,
                          weights="int8")
        assert out.shape == (2, 10)
        assert (out[:, :4] == prompt).all()
        out2 = kv_generate(net, prompt, max_new_tokens=6, temperature=0.0,
                           weights="int8")
        onp.testing.assert_array_equal(out, out2)
        # mis-wired projections (k/v or gate/up swapped) would diverge
        # from the native path immediately; ~0.4% weight noise does not
        ref = kv_generate(net, prompt, max_new_tokens=6, temperature=0.0)
        assert (out == ref).mean() >= 0.8, (out, ref)

    def test_second_model_config_relu_ffn(self):
        """The decoder derives layer math from the Block itself: a model
        variant with a RELU FFN (different activation inside ffn) must
        decode in exact greedy parity with its own full recompute — the
        old inline-GELU decoder would silently diverge here."""
        from mxnet_tpu.models import GPT, GPTConfig, kv_generate
        from mxnet_tpu.models.transformer import PositionwiseFFN
        mx.random.seed(4)
        cfg = GPTConfig(vocab_size=61, max_length=48, num_layers=3,
                        units=48, num_heads=6, hidden_size=96)
        net = GPT(cfg)
        for i, blk in enumerate(net.blocks):
            blk.ffn = PositionwiseFFN(cfg.units, cfg.hidden_size,
                                      activation="relu",
                                      prefix=f"h{i}_ffn_")
        net.initialize(mx.init.Normal(0.02))
        prompt = onp.random.RandomState(5).randint(0, 61, (2, 3))
        ref = net.generate(prompt, max_new_tokens=10, temperature=0.0)
        out = kv_generate(net, prompt, max_new_tokens=10, temperature=0.0)
        onp.testing.assert_array_equal(out, ref)
