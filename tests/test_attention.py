"""Flash / ring attention numerics vs the naive O(L²) softmax reference
(the reference framework's vanilla attention path, SURVEY.md §5.7)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def _naive(q, k, v, causal=False, scale=None):
    import jax.numpy as jnp
    scale = scale or 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Lq, Lk = s.shape[-2], s.shape[-1]
        mask = onp.tril(onp.ones((Lq, Lk), bool))
        s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _rand(*shape):
    return onp.random.RandomState(0).randn(*shape).astype("float32")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(causal):
    q, k, v = (_rand(2, 3, 64, 16) for _ in range(3))
    out = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                mx.nd.array(v), causal=causal)
    ref = _naive(q, k, v, causal=causal)
    onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)


def test_flash_long_seq_blocks():
    # seq > q_block so the scan path actually tiles
    q, k, v = (_rand(1, 2, 300, 8) for _ in range(3))
    out = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                mx.nd.array(v), causal=True)
    ref = _naive(q, k, v, causal=True)
    onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                rtol=3e-5, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_naive(causal):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash

    q, k, v = (_rand(1, 2, 48, 8) for _ in range(3))

    def f_flash(q, k, v):
        return jnp.sum(_flash(q, k, v, None, jnp.uint32(0), 0.125, causal) ** 2)

    def f_naive(q, k, v):
        return jnp.sum(_naive(q, k, v, causal=causal, scale=0.125) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-4)


def test_flash_autograd_through_tape():
    q = mx.nd.array(_rand(1, 2, 32, 8))
    k = mx.nd.array(_rand(1, 2, 32, 8))
    v = mx.nd.array(_rand(1, 2, 32, 8))
    for x in (q, k, v):
        x.attach_grad()
    with autograd.record():
        out = mx.nd.flash_attention(q, k, v, causal=True)
        loss = (out * out).sum()
    loss.backward()
    assert q.grad is not None and onp.isfinite(q.grad.asnumpy()).all()
    assert onp.abs(v.grad.asnumpy()).sum() > 0


def test_pallas_kernel_interpret_mode():
    """Run the actual Pallas kernel through the interpreter on CPU and
    check numerics (128-aligned shapes as on real TPU)."""
    from mxnet_tpu.ops import attention as attn

    q, k, v = (_rand(1, 1, 128, 128) for _ in range(3))
    os.environ["MXNET_FLASH_INTERPRET"] = "1"
    try:
        out, lse = attn._pallas_fwd(q, k, v, 0.08838834765, True)
    finally:
        del os.environ["MXNET_FLASH_INTERPRET"]
    ref = _naive(q, k, v, causal=True, scale=0.08838834765)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)
    assert onp.isfinite(onp.asarray(lse)).all()


def test_flash_padding_mask_bias():
    import jax.numpy as jnp
    q, k, v = (_rand(2, 2, 32, 8) for _ in range(3))
    valid = 20  # keys >= valid are masked out
    bias = onp.zeros((2, 1, 32, 32), "float32")
    bias[:, :, :, valid:] = -1e30
    out = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                mx.nd.array(v), mx.nd.array(bias))
    ref = _naive(q, k[:, :, :valid], v[:, :, :valid])
    onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)


def test_flash_bias_grad_matches_naive():
    """A learned (e.g. ALiBi-style) bias must receive real gradients."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash

    q, k, v = (_rand(2, 2, 24, 8) for _ in range(3))
    bias = (_rand(2, 1, 24, 24) * 0.1).astype("float32")

    def f_flash(bias):
        return jnp.sum(_flash(q, k, v, bias, jnp.uint32(0), 0.3, False) ** 2)

    def f_naive(bias):
        import jax.numpy as jnp
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.3 + bias
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v) ** 2)

    g1 = jax.grad(f_flash)(bias)
    g2 = jax.grad(f_naive)(bias)
    onp.testing.assert_allclose(onp.asarray(g1), onp.asarray(g2),
                                rtol=1e-4, atol=1e-5)


def test_pallas_kernel_interpret_head_dim_64():
    """head_dim 64 (every shipped model) must reach the kernel via lane
    padding."""
    from mxnet_tpu.ops import attention as attn

    q, k, v = (_rand(1, 2, 256, 64) for _ in range(3))
    os.environ["MXNET_FLASH_INTERPRET"] = "1"
    try:
        out, lse = attn._pallas_fwd(q, k, v, 0.125, True)
    finally:
        del os.environ["MXNET_FLASH_INTERPRET"]
    assert out.shape == (1, 2, 256, 64)
    ref = _naive(q, k, v, causal=True, scale=0.125)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)


def test_bf16_transformer_forward():
    from mxnet_tpu.models import GPT, GPTConfig
    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=97, max_length=32, num_layers=1,
                        units=32, num_heads=4, hidden_size=64,
                        dtype="bfloat16"))
    net.initialize()
    # every Dense/Embedding param must actually be bf16
    import jax.numpy as jnp
    dts = {n: p.data().dtype for n, p in net.collect_params().items()}
    assert all(onp.dtype(dt) == onp.dtype(jnp.bfloat16) for n, dt in
               dts.items() if "weight" in n or "bias" in n), dts
    toks = onp.random.RandomState(0).randint(0, 97, size=(2, 16))
    out = net(mx.nd.array(toks))
    assert onp.isfinite(out.asnumpy().astype("float32")).all()


def test_ring_attention_matches_full():
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"sp": 8})
    q, k, v = (_rand(1, 2, 64, 8) for _ in range(3))
    for causal in (False, True):
        out = mx.nd.ring_attention(mx.nd.array(q), mx.nd.array(k),
                                   mx.nd.array(v), causal=causal,
                                   axis="sp", mesh=mesh)
        ref = _naive(q, k, v, causal=causal)
        onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                    rtol=2e-5, atol=2e-5)


def test_flash_path_beyond_plain_threshold():
    """L=640 exceeds the plain-attention score cap — the op must route to
    the blockwise kernel and still match naive attention."""
    q, k, v = (_rand(1, 1, 640, 8) for _ in range(3))
    out = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                mx.nd.array(v), causal=True)
    ref = _naive(q, k, v, causal=True)
    onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                rtol=3e-5, atol=3e-5)


def test_plain_and_blockwise_paths_agree():
    """Same inputs through both implementations (the op picks by length;
    here both are invoked explicitly) must agree."""
    from mxnet_tpu.ops.attention import _flash, _plain_attn
    import jax.numpy as jnp
    q, k, v = (jnp.asarray(_rand(1, 2, 96, 8)) for _ in range(3))
    a = _plain_attn(q, k, v, None, 0.125, True)
    b = _flash(q, k, v, None, jnp.uint32(0), 0.125, True)
    onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------- #
# round 2: Pallas backward kernels, in-kernel padding mask, dropout
# --------------------------------------------------------------------------- #

def _naive_dropout(q, k, v, bias, scale, causal, rate, seed):
    """Naive attention using the SAME position-hash keep mask as the
    kernels — exact reference for dropout numerics on every path."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from mxnet_tpu.ops.attention import _keep
    B, H, Lq, _ = q.shape
    Lk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qp = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        kp = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        s = jnp.where(qp >= kp, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if rate > 0:
        bh = (lax.broadcasted_iota(jnp.int32, (B, H), 0) * H +
              lax.broadcasted_iota(jnp.int32, (B, H), 1))[..., None, None]
        qp = lax.broadcasted_iota(jnp.int32, (1, 1, Lq, 1), 2)
        kp = lax.broadcasted_iota(jnp.int32, (1, 1, 1, Lk), 3)
        p = jnp.where(_keep(seed, bh, qp, kp, rate), p, 0.0) / (1 - rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def test_pallas_bwd_kernels_match_naive_grads():
    """Interpret-mode Pallas dq + dkdv kernels vs jax.grad of naive."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash

    q, k, v = (_rand(1, 2, 128, 64) for _ in range(3))
    for causal in (False, True):
        os.environ["MXNET_FLASH_INTERPRET"] = "1"
        try:
            g1 = jax.grad(lambda *a: jnp.sum(
                _flash(*a, None, jnp.uint32(0), 0.125, causal) ** 2),
                argnums=(0, 1, 2))(q, k, v)
        finally:
            del os.environ["MXNET_FLASH_INTERPRET"]
        g2 = jax.grad(lambda *a: jnp.sum(
            _naive(*a, causal=causal, scale=0.125) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=2e-4, atol=2e-4)


def test_pallas_kmask_in_kernel_fwd_bwd():
    """Key-padding-mask bias stays ON the Pallas path (fwd + both bwd
    kernels, incl. dbias) and matches masked naive attention."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash, _pallas_eligible

    q, k, v = (_rand(2, 2, 128, 64) for _ in range(3))
    bias = onp.zeros((2, 1, 1, 128), "float32")
    bias[:, :, :, 100:] = -1e30
    bias = jnp.asarray(bias)
    os.environ["MXNET_FLASH_INTERPRET"] = "1"
    try:
        assert _pallas_eligible(jnp.asarray(q), jnp.asarray(k), bias)
        out = _flash(q, k, v, bias, jnp.uint32(0), 0.125, False)
        g1 = jax.grad(lambda qq, kk, vv, bb: jnp.sum(
            _flash(qq, kk, vv, bb, jnp.uint32(0), 0.125, False) ** 2),
            argnums=(0, 1, 2, 3))(q, k, v, bias)
    finally:
        del os.environ["MXNET_FLASH_INTERPRET"]
    ref = _naive(q, k[:, :, :100], v[:, :, :100], scale=0.125)
    onp.testing.assert_allclose(onp.asarray(out[:, :, :, :]),
                                onp.asarray(ref), rtol=2e-5, atol=2e-5)

    def f_naive(qq, kk, vv, bb):
        s = jnp.einsum("bhqd,bhkd->bhqk", qq, kk) * 0.125 + bb
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, vv) ** 2)

    g2 = jax.grad(f_naive, argnums=(0, 1, 2, 3))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_dropout_fwd_stats_and_determinism():
    """Dropout keeps ~(1-rate) mass, is deterministic per seed, differs
    across seeds, and is off in inference mode."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _plain_attn

    q, k, v = (jnp.asarray(_rand(2, 4, 64, 16)) for _ in range(3))
    a1 = _plain_attn(q, k, v, None, 0.25, False, dropout=0.5,
                     seed=jnp.uint32(7))
    a2 = _plain_attn(q, k, v, None, 0.25, False, dropout=0.5,
                     seed=jnp.uint32(7))
    a3 = _plain_attn(q, k, v, None, 0.25, False, dropout=0.5,
                     seed=jnp.uint32(8))
    onp.testing.assert_array_equal(onp.asarray(a1), onp.asarray(a2))
    assert onp.abs(onp.asarray(a1) - onp.asarray(a3)).max() > 1e-4

    # E[dropped p row-sum] == 1; check the keep fraction is ~50%
    from mxnet_tpu.ops.attention import _keep
    import jax.lax as lax
    bits = _keep(jnp.uint32(7), jnp.int32(0),
                 lax.broadcasted_iota(jnp.int32, (256, 1), 0),
                 lax.broadcasted_iota(jnp.int32, (1, 256), 1), 0.5)
    frac = onp.asarray(bits).mean()
    assert 0.47 < frac < 0.53, frac


def test_dropout_grads_consistent_across_paths():
    """XLA blockwise fwd+bwd with dropout == grads of the hash-identical
    naive implementation (the mask regenerates identically)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash

    q, k, v = (_rand(1, 2, 96, 8) for _ in range(3))
    seed = jnp.uint32(42)
    g1 = jax.grad(lambda *a: jnp.sum(
        _flash(*a, None, seed, 0.125, False, 0.3) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(
        _naive_dropout(*a, None, 0.125, False, 0.3, seed) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_dropout_pallas_kernels_match_naive():
    """Pallas fwd + bwd with in-kernel dropout == hash-identical naive."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _flash

    q, k, v = (_rand(1, 2, 128, 64) for _ in range(3))
    seed = jnp.uint32(5)
    os.environ["MXNET_FLASH_INTERPRET"] = "1"
    try:
        out = _flash(q, k, v, None, seed, 0.125, False, 0.2)
        g1 = jax.grad(lambda *a: jnp.sum(
            _flash(*a, None, seed, 0.125, False, 0.2) ** 2),
            argnums=(0, 1, 2))(q, k, v)
    finally:
        del os.environ["MXNET_FLASH_INTERPRET"]
    ref = _naive_dropout(q, k, v, None, 0.125, False, 0.2, seed)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)
    g2 = jax.grad(lambda *a: jnp.sum(
        _naive_dropout(*a, None, 0.125, False, 0.2, seed) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_flash_attention_op_dropout_training_flag():
    """The public op applies dropout only in training mode."""
    q, k, v = (mx.nd.array(_rand(1, 2, 32, 8)) for _ in range(3))
    mx.random.seed(0)
    out_infer = mx.nd.flash_attention(q, k, v, dropout=0.5)
    with autograd.record(train_mode=True):
        out_train = mx.nd.flash_attention(q, k, v, dropout=0.5)
    ref = _naive(q.asnumpy(), k.asnumpy(), v.asnumpy())
    onp.testing.assert_allclose(out_infer.asnumpy(), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)
    assert onp.abs(out_train.asnumpy() - out_infer.asnumpy()).max() > 1e-4


def test_ring_attention_grads_match_full():
    """Ring attention must be differentiable through the ppermute ring
    (long-context training, not just inference)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel
    from mxnet_tpu.ops.attention import _ring_attn_local
    from mxnet_tpu._jax_compat import NO_CHECK, shard_map
    from mxnet_tpu.parallel.mesh import P
    import functools

    mesh = parallel.make_mesh({"sp": 8})
    q, k, v = (_rand(1, 2, 64, 8) for _ in range(3))

    fn = shard_map(
        functools.partial(_ring_attn_local, scale=0.125, causal=True,
                          axis="sp", n_shards=8),
        mesh=mesh, in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None), **NO_CHECK)

    def ring_loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(jnp.asarray(
            _naive(q, k, v, causal=True, scale=0.125)) ** 2)

    g1 = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


# --------------------------------------------------------------------------- #
# measured dispatch table (VERDICT r2 item 4)
# --------------------------------------------------------------------------- #

class TestDispatch:
    def _choose(self, Lq, Lk=None, bias=None, training=True, pallas_ok=True):
        import jax.numpy as jnp
        from mxnet_tpu.ops import attention as attn
        Lk = Lk or Lq
        q = jnp.zeros((1, 1, Lq, 8))
        saved = attn._use_pallas
        attn._use_pallas = lambda: pallas_ok
        try:
            return attn._choose_path(Lq, Lk, bias, training)
        finally:
            attn._use_pallas = saved

    def test_short_is_plain(self):
        assert self._choose(128) == "plain"
        assert self._choose(512) == "plain"

    def test_mid_range_follows_table(self):
        from mxnet_tpu.ops.attention import _PATH_TABLE
        # training column: the table rows must be respected exactly
        for bound, impl in _PATH_TABLE["train"]:
            if bound is None or bound <= 512:
                continue
            assert self._choose(bound, training=True) == impl

    def test_long_is_pallas(self):
        assert self._choose(8192, training=True) == "pallas"
        assert self._choose(8192, training=False) == "pallas"

    def test_unaligned_long_still_pallas(self):
        # 128-unaligned lengths are padded inside the op, not demoted
        assert self._choose(8000, training=True) == "pallas"

    def test_dense_bias_never_pallas(self):
        import jax.numpy as jnp
        dense_bias = jnp.zeros((1, 1, 8192, 8192))
        assert self._choose(8192, bias=dense_bias) == "xla"

    def test_no_pallas_backend_degrades_to_xla(self):
        assert self._choose(8192, pallas_ok=False) == "xla"


    def test_dispatch_matches_measured_best(self):
        """Frozen copy of the v5e sweep (benchmark/attention_bench.py,
        2026-07-30): chosen path == fastest measured path at every
        measured (seq, pass) point (VERDICT r2 item 4 done-criterion)."""
        measured_best = {
            (512, False): "plain", (512, True): "plain",
            (1024, False): "xla", (1024, True): "xla",
            (2048, False): "xla", (2048, True): "pallas",
            (4096, False): "xla", (4096, True): "pallas",
            (8192, False): "pallas", (8192, True): "pallas",
        }
        for (seq, training), want in measured_best.items():
            got = self._choose(seq, training=training)
            assert got == want, (seq, training, got, want)


class TestPadding:
    def test_pad_to_block_shapes_and_mask(self):
        import jax.numpy as jnp
        from mxnet_tpu.ops.attention import _pad_to_block, _NEG_INF
        q = jnp.ones((2, 3, 200, 16))
        k = jnp.ones((2, 3, 250, 16))
        v = jnp.ones((2, 3, 250, 16))
        q2, k2, v2, bias2, Lq = _pad_to_block(q, k, v, None)
        assert Lq == 200
        assert q2.shape[2] == 256 and k2.shape[2] == 256
        assert v2.shape == k2.shape
        # synthesized key mask: 0 for real keys, -inf for pad keys
        assert bias2.shape == (1, 1, 1, 256)
        assert float(bias2[0, 0, 0, 249]) == 0.0
        assert float(bias2[0, 0, 0, 250]) <= _NEG_INF / 2

    def test_pad_preserves_existing_kmask(self):
        import jax.numpy as jnp
        from mxnet_tpu.ops.attention import _pad_to_block, _NEG_INF
        q = jnp.ones((2, 1, 128, 8))
        k = jnp.ones((2, 1, 130, 8))
        bias = jnp.zeros((2, 1, 1, 130)).at[0, 0, 0, 5].set(_NEG_INF)
        q2, k2, v2, bias2, _ = _pad_to_block(q, k, jnp.ones_like(k), bias)
        assert bias2.shape == (2, 1, 1, 256)
        assert float(bias2[0, 0, 0, 5]) <= _NEG_INF / 2   # user mask kept
        assert float(bias2[1, 0, 0, 129]) == 0.0          # real key open
        assert float(bias2[1, 0, 0, 130]) <= _NEG_INF / 2  # pad key masked

    def test_padded_pallas_matches_naive(self, monkeypatch):
        """Unaligned seq through the actual Pallas kernel (interpret mode)
        must equal the naive reference after the in-op pad+slice."""
        import jax.numpy as jnp
        from mxnet_tpu.ops import attention as attn
        monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
        q, k, v = (jnp.asarray(_rand(1, 2, 200, 16)) for _ in range(3))
        q2, k2, v2, bias2, Lq = attn._pad_to_block(q, k, v, None)
        out = attn._flash(q2, k2, v2, bias2, jnp.uint32(0), 0.25, False,
                          0.0, "pallas")[:, :, :Lq]
        ref = _naive(q, k, v, causal=False, scale=0.25)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                    rtol=3e-5, atol=3e-5)

    def test_broadcast_kmask_bias_not_pallas(self):
        """A (B,1,1,1) broadcast bias cannot become a padded kernel mask —
        dispatch must route it to the XLA path, and the op must compute
        correctly (review regression)."""
        import jax.numpy as jnp
        from mxnet_tpu.ops import attention as attn
        bias = jnp.zeros((1, 1, 1, 1))
        assert attn._choose_path(8000, 8000, bias, False) == "xla"
        q, k, v = (jnp.asarray(_rand(1, 1, 600, 8)) for _ in range(3))
        out = mx.nd.flash_attention(mx.nd.array(onp.asarray(q)),
                                    mx.nd.array(onp.asarray(k)),
                                    mx.nd.array(onp.asarray(v)),
                                    bias=mx.nd.array(onp.zeros(
                                        (1, 1, 1, 1), "float32")))
        ref = _naive(q, k, v, causal=False)
        onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                    rtol=3e-5, atol=3e-5)


class TestQ8MatvecTiling:
    """ADVICE r4 (medium): large-K layers must tile K within the VMEM
    budget instead of streaming the whole (K, bo) block, and unaligned
    vocabs must not silently fall off the kernel path."""

    def test_pick_tiles_bounds_bytes(self):
        from mxnet_tpu.ops import q8_matvec as q8
        # Llama-7B down-proj: K=11008, O=4096 — must find a tiling whose
        # working set fits the budget (pre-fix this streamed ~86 MB f32)
        bk, bo = q8._pick_tiles(1, 11008, 4096)
        assert bk and bo and bk % 32 == 0 and bo % 128 == 0
        assert 11008 % bk == 0 and 4096 % bo == 0
        assert q8._tile_bytes(1, bk, bo) <= q8._VMEM_BUDGET
        # huge-K pathological shape still admits the minimum lane tile
        bk2, bo2 = q8._pick_tiles(1, 32768, 128)
        assert bk2 and bo2 == 128
        assert q8._tile_bytes(1, bk2, bo2) <= q8._VMEM_BUDGET

    def test_k_tiled_kernel_matches_einsum(self, monkeypatch):
        import jax.numpy as jnp
        from mxnet_tpu.ops.q8_matvec import q8_matvec, _pick_tiles
        monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
        # shrink the budget so K genuinely tiles even at this test size
        monkeypatch.setattr("mxnet_tpu.ops.q8_matvec._VMEM_BUDGET",
                            256 * 1024)
        B, K, O = 2, 512, 384
        bk, bo = _pick_tiles(B, K, O)
        assert bk < K  # the accumulation path is actually exercised
        x = jnp.asarray(onp.random.RandomState(0).randn(B, K), "float32")
        wq = jnp.asarray(
            onp.random.RandomState(1).randint(-127, 128, (K, O)), "int8")
        s = jnp.asarray(onp.random.RandomState(2).rand(O) + 0.5, "float32")
        b = jnp.asarray(onp.random.RandomState(3).randn(O), "float32")
        got = q8_matvec(x, wq, s, b)
        ref = (x @ wq.astype(jnp.float32)) * s + b
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(ref),
                                    rtol=2e-4, atol=2e-3)

    def test_misaligned_O_falls_back(self):
        """bo must stay a 128 lane multiple — O=1000 has no admissible
        tile and must route to the einsum fallback (review regression)."""
        from mxnet_tpu.ops import q8_matvec as q8
        assert q8._pick_tiles(1, 256, 1000) == (0, 0)
        assert q8._pick_tiles(1, 64, 192) == (0, 0)
