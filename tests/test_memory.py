"""Memory observability (ISSUE 10): per-executable memory analysis on
compile events (``MXNET_TELEMETRY_MEM``), the live HBM accountant and
its ``jax.live_arrays()`` reconciliation, budget-aware serving
(``MXNET_SERVE_HBM_BUDGET`` / ``DecodeServer(hbm_budget=)``), and the
offline ``tools/memory_report.py``.

Conventions follow tests/test_telemetry.py: the registry / event ring /
accountant are process-global, so tests use unique subsystem names and
measure deltas instead of absolute values."""
import json
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import memory as tmem


@pytest.fixture(scope="module")
def tiny_gpt():
    from mxnet_tpu.models import GPT, GPTConfig

    mx.random.seed(0)
    net = GPT(GPTConfig(vocab_size=64, max_length=24, num_layers=2,
                        units=16, num_heads=2, hidden_size=32))
    net.initialize(mx.init.Normal(0.02))
    return net


def _pool1_bytes(net):
    """Exact device bytes of a 1-slot pool for ``net`` at T=24 — the
    unit the budget tests price against."""
    from mxnet_tpu.serve import DecodeServer

    srv = DecodeServer(net, max_total_len=24, pool_sizes=(1,),
                       autostart=False)
    try:
        return srv.stats()["pool_bytes"]
    finally:
        srv.close()


# --------------------------------------------------------------------- #
# byte helpers
# --------------------------------------------------------------------- #

class TestByteHelpers:
    def test_parse_bytes(self):
        assert tmem.parse_bytes(1024) == 1024
        assert tmem.parse_bytes("1024") == 1024
        assert tmem.parse_bytes("4k") == 4 << 10
        assert tmem.parse_bytes("2M") == 2 << 20
        assert tmem.parse_bytes("1.5G") == 3 << 29
        with pytest.raises(MXNetError, match="t_budget"):
            tmem.parse_bytes("lots", "t_budget")
        with pytest.raises(MXNetError, match=">= 0"):
            tmem.parse_bytes(-1)
        # overflow/inf degrade to the same clean error, not a raw
        # OverflowError out of int()
        with pytest.raises(MXNetError, match="expected bytes"):
            tmem.parse_bytes("1e999")
        with pytest.raises(MXNetError, match="expected bytes"):
            tmem.parse_bytes(float("inf"))
        with pytest.raises(MXNetError, match="expected bytes"):
            tmem.parse_bytes(True)

    def test_format_bytes(self):
        assert tmem.format_bytes(512) == "512 B"
        assert tmem.format_bytes(3 << 29) == "1.50 GiB"
        assert "MiB" in tmem.format_bytes(5 << 20)

    def test_nbytes_of(self):
        import jax.numpy as jnp

        assert tmem.nbytes_of(None) == 0
        assert tmem.nbytes_of(onp.zeros((4, 4), onp.float32)) == 64
        assert tmem.nbytes_of(jnp.zeros((8,), jnp.int32)) == 32
        nd = mx.nd.array(onp.zeros((2, 2), onp.float32))
        assert tmem.nbytes_of(nd) == 16
        tree = {"a": [onp.zeros(2, onp.float64), None],
                "b": (jnp.zeros(3, jnp.float32),)}
        assert tmem.nbytes_of(tree) == 16 + 12
        assert tmem.nbytes_of("not an array") == 0

    def test_per_device_bytes(self):
        import jax.numpy as jnp

        pd = tmem.per_device_bytes(jnp.zeros((4,), jnp.float32))
        assert sum(pd.values()) == 16
        assert all(":" in k for k in pd)
        # host numpy is charged to the host bucket, not a device
        assert tmem.per_device_bytes(onp.zeros(4, onp.int8)) == \
            {"host:0": 4}


# --------------------------------------------------------------------- #
# per-executable analysis on compile events
# --------------------------------------------------------------------- #

class TestCompileMemoryFields:
    def test_mem_fields_under_env(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("MXNET_TELEMETRY_MEM", "1")
        fn = telemetry.instrument_jit(
            jax.jit(lambda x: jnp.tanh(x) @ x, donate_argnums=(0,)),
            "t.mem_on")
        out = fn(jnp.ones((16, 16)))
        ev = [e for e in telemetry.events("compile")
              if e.get("site") == "t.mem_on"][-1]
        assert ev["mem_arg_bytes"] == 16 * 16 * 4
        assert ev["mem_out_bytes"] == 16 * 16 * 4
        assert ev["mem_temp_bytes"] >= 0
        # peak is the documented arithmetic over the parts
        assert ev["mem_peak_bytes"] == (
            ev["mem_arg_bytes"] + ev["mem_out_bytes"]
            + ev["mem_temp_bytes"] + ev.get("mem_code_bytes", 0)
            - ev.get("mem_alias_bytes", 0))
        # the analysis recompiles from shape structs: the just-donated
        # input buffer was never dereferenced, the output is live
        assert float(out[0, 0]) != 0.0

    def test_mem_off_by_default(self):
        import jax
        import jax.numpy as jnp

        fn = telemetry.instrument_jit(jax.jit(lambda x: x + 1),
                                      "t.mem_off")
        fn(jnp.ones(4))
        ev = [e for e in telemetry.events("compile")
              if e.get("site") == "t.mem_off"][-1]
        assert not any(k.startswith("mem_") for k in ev)

    def test_memory_analysis_helper(self):
        import jax
        import jax.numpy as jnp

        compiled = jax.jit(lambda x: x * 2).lower(
            jax.ShapeDtypeStruct((8,), jnp.float32)).compile()
        ma = telemetry.memory_analysis(compiled)
        assert ma["arg_bytes"] == 32 and ma["out_bytes"] == 32
        assert ma["peak_bytes"] >= 64
        # objects without the stats surface degrade to None, not a crash
        assert telemetry.memory_analysis(object()) is None


# --------------------------------------------------------------------- #
# the accountant
# --------------------------------------------------------------------- #

class TestAccountant:
    def test_set_drop_gauge_and_events(self):
        import jax.numpy as jnp

        def my_events():
            # scoped to THIS test's subsystem: under the full suite,
            # other tests' gc'd trainers/rings drain deferred drops
            # (their own device_memory events) inside our set() calls
            return [e for e in telemetry.events("device_memory")
                    if e.get("subsystem") == "t.acct"]

        A = telemetry.ACCOUNTANT
        arr = jnp.zeros((8, 8), jnp.float32)
        before = len(my_events())
        A.set("t.acct", "k1", arr)
        assert A.bytes(subsystem="t.acct") == 256
        dev = next(iter(tmem.per_device_bytes(arr)))
        g = telemetry.gauge("device_bytes", subsystem="t.acct",
                            device=dev)
        assert g.value == 256
        # unchanged re-registration is free: no second event
        A.set("t.acct", "k1", arr)
        assert len(my_events()) == before + 1
        # a second key accumulates into the subsystem gauge
        A.set("t.acct", "k2", jnp.zeros((4,), jnp.float32))
        assert A.bytes(subsystem="t.acct") == 256 + 16
        assert g.value == 272
        assert A.snapshot()["t.acct"][dev] == 272
        A.drop("t.acct", "k1")
        A.drop("t.acct", "k2")
        A.drop("t.acct", "k2")          # idempotent
        assert A.bytes(subsystem="t.acct") == 0
        assert g.value == 0
        last = my_events()[-1]
        assert last["subsystem"] == "t.acct" and last["bytes"] == 0

    def test_deferred_drop_lock_free_and_drained_on_query(self):
        """``drop_deferred`` (the ``__del__``-safe path) takes no lock
        at enqueue time; the entry is fully retired — ledger, gauge,
        event — by the next normal-thread query."""
        A = telemetry.ACCOUNTANT
        A.set("t.acct_def", "k", per_device={"cpu:0": 64})
        A.drop_deferred("t.acct_def", "k")
        A.drop_deferred("t.acct_def", "never-registered")   # harmless
        # the query drains the queue before reading
        assert A.bytes(subsystem="t.acct_def") == 0
        g = telemetry.gauge("device_bytes", subsystem="t.acct_def",
                            device="cpu:0")
        assert g.value == 0
        assert "t.acct_def" not in A.snapshot()

    def test_explicit_per_device_mapping(self):
        A = telemetry.ACCOUNTANT
        A.set("t.acct_pd", "ring", per_device={"cpu:0": 100,
                                               "cpu:1": 50})
        assert A.bytes(subsystem="t.acct_pd") == 150
        assert A.bytes(subsystem="t.acct_pd", device="cpu:1") == 50
        A.drop("t.acct_pd", "ring")

    def test_reconcile_against_live_arrays(self):
        import jax.numpy as jnp

        A = telemetry.ACCOUNTANT
        arr = jnp.ones((32, 32), jnp.float32)   # keep a live ref
        A.set("t.acct_rec", "arr", arr)
        try:
            rec = telemetry.reconcile()
            dev = next(iter(tmem.per_device_bytes(arr)))
            assert dev in rec
            # live_arrays sees this registered array plus everything the
            # ledger was never told about — the accounted bytes for a
            # LIVE allocation can never exceed the live total
            assert rec[dev]["live"] >= 32 * 32 * 4
            assert rec[dev]["accounted"] >= 32 * 32 * 4
            assert 0 < rec[dev]["coverage"] <= 1 or \
                rec[dev]["delta"] < 0   # stale entries from other tests
        finally:
            A.drop("t.acct_rec", "arr")


# --------------------------------------------------------------------- #
# acceptance: mem fields from >= 4 distinct compile sites + reconcile
# --------------------------------------------------------------------- #

class TestSiteCoverage:
    def test_four_sites_carry_memory_analysis(self, monkeypatch,
                                              tiny_gpt):
        """With ``MXNET_TELEMETRY_MEM=1``, compile events from the
        fused train step, the CachedOp, offline decode, and the serve
        step/admit programs all carry ``mem_*`` fields — and the live
        accountant reconciles against ``jax.live_arrays()`` while the
        pool is resident (the documented tolerance: live >= accounted
        for live allocations; live also holds unregistered buffers)."""
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.models import kv_generate
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_TELEMETRY_MEM", "1")
        before = len(telemetry.events("compile"))

        # 1. fused train step
        mx.random.seed(0)
        net = nn.Dense(4, in_units=6)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=None)
        loss_l = gluon.loss.L2Loss()

        def loss_fn(xx, yy):
            return loss_l(net(xx), yy)

        rng = onp.random.RandomState(0)
        trainer.fused_step(loss_fn,
                           mx.nd.array(rng.rand(2, 6).astype("f")),
                           mx.nd.array(rng.rand(2, 4).astype("f")))
        # ledger: this trainer's params are exactly accounted
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="train.params", key=trainer._mem_label) == \
            sum(tmem.nbytes_of(p.data())
                for p in net.collect_params().values())

        # 2. CachedOp (hybridized inference)
        hnet = nn.Dense(3, in_units=5)
        hnet.initialize(mx.init.Xavier())
        hnet.hybridize()
        hnet(mx.nd.array(rng.rand(2, 5).astype("f")))

        # 3. offline decode (kv_generate)
        kv_generate(tiny_gpt, rng.randint(0, 64, (1, 3)),
                    max_new_tokens=5, temperature=0.0)

        # 4. serve step + admit
        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           autostart=False)
        s = srv.submit(rng.randint(0, 64, (3,)), max_new_tokens=3)
        while srv.pump():
            pass
        s.tokens(30)
        pool_bytes = srv.stats()["pool_bytes"]
        assert pool_bytes > 0
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="serve.kv_pool",
            key=srv.telemetry_label) == pool_bytes
        rec = telemetry.reconcile()
        # reconcile on the device the pool actually lives on (under the
        # suite's 8-device virtual mesh other devices hold other tests'
        # entries) — live >= this live allocation's accounted bytes
        pool_devs = telemetry.ACCOUNTANT.snapshot()["serve.kv_pool"]
        dev = max(pool_devs, key=pool_devs.get)
        assert rec[dev]["live"] >= pool_bytes
        srv.close()

        sites = {e.get("site") for e in
                 telemetry.events("compile")[before:]
                 if "mem_peak_bytes" in e}
        assert {"gluon.fused_step", "gluon.cached_op",
                "models.kv_generate", "serve.step",
                "serve.admit"} <= sites, sites


# --------------------------------------------------------------------- #
# paged-pool pricing (ISSUE 16)
# --------------------------------------------------------------------- #

class TestPagedPricing:
    """ISSUE 16 satellite: ``pool_state_bytes`` pages pricing equals
    the allocator-reported device bytes of the paged state at init and
    after growth, and ``stats()['pool_bytes']`` stays truthful while
    pages are recycled.  ISSUE 18 re-pins every identity for BOTH pool
    dtypes — an int8 pool's (codes, scales) pages must price exactly
    like they allocate."""

    @pytest.mark.parametrize("kv_dtype", ["native", "int8"])
    def test_pool_state_bytes_matches_device_state(self, tiny_gpt,
                                                   kv_dtype):
        from mxnet_tpu.serve import engine as seng

        progs = seng.PoolPrograms(tiny_gpt, num_slots=2, max_total=24,
                                  kv_dtype=kv_dtype)
        state = seng.pool_state_init(progs)
        assert sum(tmem.nbytes_of(x) for x in state) == \
            seng.pool_state_bytes(progs)

    @pytest.mark.parametrize("kv_dtype", ["native", "int8"])
    def test_pool_state_grow_matches_pricing(self, tiny_gpt, kv_dtype):
        """Growth adds slots AND pages; the priced bytes track the
        grown state exactly (no drift between pricer and allocator)."""
        from mxnet_tpu.serve import engine as seng

        progs = seng.PoolPrograms(tiny_gpt, num_slots=1, max_total=24,
                                  kv_dtype=kv_dtype)
        state = seng.pool_state_init(progs)
        new_pages = 3 * progs.maxp
        grown = seng.pool_state_grow(state, 3, new_pages=new_pages)
        assert sum(tmem.nbytes_of(x) for x in grown) == \
            seng.pool_state_bytes(progs, 3, num_pages=new_pages)

    def test_int8_pool_shrinks_pages_about_4x(self, tiny_gpt):
        """The capacity claim at the pricing layer: an int8 page costs
        codes + per-page scales, ~4x under the f32 page (>= 2x is the
        budget-doubling bar; the exact ratio depends on page geometry
        via the scale overhead)."""
        from mxnet_tpu.serve import engine as seng

        f32 = seng.PoolPrograms(tiny_gpt, num_slots=2, max_total=24)
        i8 = seng.PoolPrograms(tiny_gpt, num_slots=2, max_total=24,
                               kv_dtype="int8")
        assert i8.page_bytes() * 2 < f32.page_bytes()
        assert seng.pool_state_bytes(i8) * 2 < \
            seng.pool_state_bytes(f32)

    @pytest.mark.parametrize("kv_dtype", ["native", "int8"])
    def test_pool_bytes_truthful_under_page_reuse(self, tiny_gpt,
                                                  kv_dtype):
        """Admit/retire churn recycles pages in place: the resident
        pool's reported and accountant-metered bytes never move (and
        under int8 they agree with the allocator's view of the
        (codes, scales) state)."""
        from mxnet_tpu.serve import DecodeServer
        from mxnet_tpu.serve.engine import pool_state_bytes

        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           prefix_cache=False, autostart=False,
                           kv_dtype=kv_dtype)
        try:
            b0 = srv.stats()["pool_bytes"]
            assert b0 > 0
            assert b0 == pool_state_bytes(srv._progs)
            for seed in range(3):
                rng = onp.random.RandomState(seed)
                s = srv.submit(rng.randint(0, 64, (5,)),
                               max_new_tokens=4)
                while srv.pump():
                    pass
                s.tokens(10)
                st = srv.stats()
                assert st["pool_bytes"] == b0
                assert st["pages_in_use"] == 0
                assert telemetry.ACCOUNTANT.bytes(
                    subsystem="serve.kv_pool",
                    key=srv.telemetry_label) == b0
        finally:
            srv.close()


# --------------------------------------------------------------------- #
# budget-aware serving
# --------------------------------------------------------------------- #

class TestServeBudget:
    def test_growth_over_budget_raises(self, tiny_gpt):
        """The acceptance pin: an over-budget pool growth is a clean
        ``MXNetError`` naming requested vs available bytes — never an
        allocator OOM."""
        from mxnet_tpu.serve import DecodeServer

        pool1 = _pool1_bytes(tiny_gpt)
        # 2.5x: fits the minimum usable config (pool + A=1 scratch =
        # 2x) and steady serving at 1 slot, refuses the growth's
        # transient old+new peak (3x)
        srv = DecodeServer(tiny_gpt, max_total_len=24,
                           pool_sizes=(1, 2),
                           hbm_budget=int(pool1 * 2.5),
                           autostart=False)
        try:
            srv.submit(onp.array([1, 2, 3]), max_new_tokens=6)
            srv.submit(onp.array([4, 5, 6]), max_new_tokens=6)
            with pytest.raises(MXNetError,
                               match=r"pool growth 1 -> 2") as ei:
                while srv.pump():
                    pass
            msg = str(ei.value)
            # requested vs available, in bytes, plus the remedy
            assert "requests" in msg and "remains" in msg
            assert "KiB" in msg or " B" in msg
            assert "MXNET_SERVE_HBM_BUDGET" in msg
        finally:
            srv.close(drain=False)

    def test_growth_priced_at_transient_peak(self, tiny_gpt):
        """pool_state_grow holds old AND new pools until the copy
        completes — a budget the settled 2-slot pool fits (2x) but the
        transient old+new peak (3x) does not is refused at the peak."""
        from mxnet_tpu.serve import DecodeServer

        pool1 = _pool1_bytes(tiny_gpt)
        srv = DecodeServer(tiny_gpt, max_total_len=24,
                           pool_sizes=(1, 2),
                           hbm_budget=int(pool1 * 2.2),
                           autostart=False)
        try:
            srv.submit(onp.array([1, 2], onp.int32), max_new_tokens=6)
            srv.submit(onp.array([3, 4], onp.int32), max_new_tokens=6)
            with pytest.raises(MXNetError, match="pool growth"):
                while srv.pump():
                    pass
        finally:
            srv.close(drain=False)

    def test_grad_accum_ledger_per_fused_step(self):
        """Two FusedSteps on one trainer own two accumulator rings —
        two ledger entries, not one overwriting the other — and
        release_accounting (the eviction hook) retires an entry."""
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn

        mx.random.seed(0)
        net = nn.Dense(4, in_units=6)
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None,
                           update_interval=2)
        loss_l = gluon.loss.L2Loss()

        def loss_a(xx, yy):
            return loss_l(net(xx), yy)

        def loss_b(xx, yy):
            return loss_l(net(xx), yy) * 2

        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.rand(2, 6).astype("f"))
        y = mx.nd.array(rng.rand(2, 4).astype("f"))
        ring = sum(tmem.nbytes_of(p.data())
                   for p in net.collect_params().values())
        before = telemetry.ACCOUNTANT.bytes(
            subsystem="train.grad_accum")
        tr.fused_step(loss_a, x, y)
        tr.fused_step(loss_b, x, y)
        after = telemetry.ACCOUNTANT.bytes(subsystem="train.grad_accum")
        assert after - before == 2 * ring, (after, before, ring)
        for fs in list(tr._fused_steps.values()):
            fs.release_accounting()
            fs.release_accounting()    # idempotent
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="train.grad_accum") == before
        # the trainer-level release retires params/opt-state entries
        # too (the __del__ path for discarded trainers)
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="train.params", key=tr._mem_label) > 0
        tr.release_accounting()
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="train.params", key=tr._mem_label) == 0
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="train.opt_states", key=tr._mem_label) == 0

    def test_initial_pool_over_budget_raises(self, tiny_gpt):
        from mxnet_tpu.serve import DecodeServer

        with pytest.raises(MXNetError, match="initial pool"):
            DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                         hbm_budget=16, autostart=False)

    def test_admission_wave_clamped_to_budget(self, tiny_gpt):
        """A burst whose big (A=2) wave bucket's prefill scratch would
        overflow the budget is not refused — it admits in smaller
        waves the budget CAN hold (2 dispatches at A=1) and every
        request still serves."""
        from mxnet_tpu.serve import DecodeServer

        pool1 = _pool1_bytes(tiny_gpt)
        # pool(2 slots)=2x + A=1 scratch=1x fits; the A=2 bucket's 2x
        # scratch (total 4x) does not — so the wave must clamp to 1
        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(2,),
                           hbm_budget=int(pool1 * 3) + 100,
                           autostart=False)
        try:
            a = srv.submit(onp.array([1, 2, 3]), max_new_tokens=4)
            b = srv.submit(onp.array([4, 5, 6]), max_new_tokens=4)
            while srv.pump():
                pass
            assert len(a.tokens(30)) == 4 and len(b.tokens(30)) == 4
            assert srv.counters["admit_dispatches"] == 2
        finally:
            srv.close(drain=False)

    def test_admission_unserveable_after_growth_raises(self, tiny_gpt):
        """When even the SMALLEST wave bucket's scratch no longer fits
        next to the (grown) pool, admission refuses cleanly — before
        the wave touches the slot table, so nothing is stranded."""
        from mxnet_tpu.serve import DecodeServer

        pool1 = _pool1_bytes(tiny_gpt)
        # min bucket A=2: constructor check pool(1)+scratch(2)=3x fits
        # the 3.5x budget and growth's transient peak (3x) fits — but
        # the grown pool(2)+scratch(2)=4x does not
        srv = DecodeServer(tiny_gpt, max_total_len=24,
                           pool_sizes=(1, 2), admit_sizes=(2,),
                           hbm_budget=int(pool1 * 3.5),
                           autostart=False)
        try:
            srv.submit(onp.array([1, 2, 3]), max_new_tokens=4)
            srv.submit(onp.array([4, 5, 6]), max_new_tokens=4)
            with pytest.raises(MXNetError, match="admission wave"):
                while srv.pump():
                    pass
            st = srv.stats()
            assert st["in_flight"] == 0 and st["pending"] == 2, st
        finally:
            srv.close(drain=False)

    def test_budget_below_minimum_usable_fails_at_construction(
            self, tiny_gpt):
        """A budget the resident pool fits but the smallest admission
        scratch does not would fail EVERY request — refused at
        construction, naming the scratch."""
        from mxnet_tpu.serve import DecodeServer

        pool1 = _pool1_bytes(tiny_gpt)
        with pytest.raises(MXNetError,
                           match=r"smallest admission wave"):
            DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                         hbm_budget=pool1 + 100, autostart=False)

    def test_env_budget_parsed(self, monkeypatch, tiny_gpt):
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_SERVE_HBM_BUDGET", "64M")
        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           autostart=False)
        assert srv.hbm_budget == 64 << 20
        srv.close()
        monkeypatch.setenv("MXNET_SERVE_HBM_BUDGET", "plenty")
        with pytest.raises(MXNetError, match="MXNET_SERVE_HBM_BUDGET"):
            DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                         autostart=False)

    def test_kwarg_budget_accepts_suffix(self, tiny_gpt):
        from mxnet_tpu.serve import DecodeServer

        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           hbm_budget="1G", autostart=False)
        assert srv.hbm_budget == 1 << 30
        # within budget: serving works end to end
        s = srv.submit(onp.array([5, 6]), max_new_tokens=3)
        while srv.pump():
            pass
        assert len(s.tokens(30)) == 3
        srv.close()
        assert telemetry.ACCOUNTANT.bytes(
            subsystem="serve.kv_pool", key=srv.telemetry_label) == 0


# --------------------------------------------------------------------- #
# satellite: stats()/histogram behavior on fresh & empty state
# --------------------------------------------------------------------- #

class TestStatsAudit:
    def test_fresh_server_stats_sensible_zeros(self, tiny_gpt):
        from mxnet_tpu.serve import DecodeServer

        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           autostart=False)
        st = srv.stats()
        assert st["steps"] == 0 and st["occupancy"] == 0.0
        assert st["pending"] == 0 and st["in_flight"] == 0
        assert st["pool_bytes"] > 0 and st["hbm_budget"] is None
        for hist in ("ttft", "token_gap", "queue_wait"):
            assert st[hist]["count"] == 0
            assert st[hist]["p50"] is None
            assert st[hist]["mean"] is None
        assert all(v == 0 for v in st["counters"].values())
        srv.close()
        # stats after close: no crash, pool actually RELEASED (state
        # refs dropped, so the allocator agrees with the zeroed gauge)
        st2 = srv.stats()
        assert st2["in_flight"] == 0 and st2["pool_bytes"] == 0
        assert srv._state is None

    def test_sync_mode_pool_bytes_zero(self, monkeypatch, tiny_gpt):
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_SERVE_SYNC", "1")
        srv = DecodeServer(tiny_gpt, max_total_len=24, autostart=False)
        st = srv.stats()
        assert st["sync_mode"] and st["pool_bytes"] == 0
        s = srv.submit(onp.array([1, 2]), max_new_tokens=2)
        srv.pump()
        assert len(s.tokens(30)) == 2
        srv.close()

    def test_sync_mode_budget_warns_inert(self, monkeypatch, tiny_gpt):
        """A configured hbm_budget has nothing to meter on the
        kv_generate fallback — the constructor says so instead of
        silently carrying an unenforced limit."""
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_SERVE_SYNC", "1")
        with pytest.warns(UserWarning,
                          match="NOT enforced in sync mode"):
            srv = DecodeServer(tiny_gpt, max_total_len=24,
                               hbm_budget="1G", autostart=False)
        srv.close()

    def test_empty_histogram_full_surface(self):
        h = telemetry.histogram("t_mem_empty_hist")
        assert h.quantile(0.9) is None
        s = h.summary()
        assert s["count"] == 0 and s["sum"] == 0.0
        assert s["min"] is None and s["max"] is None
        assert s["p50"] is None and s["p99"] is None
        # an empty histogram renders (all-zero buckets), no crash
        text = telemetry.render_prometheus()
        assert "t_mem_empty_hist_count 0" in text


# --------------------------------------------------------------------- #
# satellite: the MXNET_TELEMETRY / MXNET_TELEMETRY_MEM hatches
# --------------------------------------------------------------------- #

class TestHatches:
    def test_telemetry_off_serve_uninstrumented(self, monkeypatch,
                                                tiny_gpt):
        """``MXNET_TELEMETRY=0``: the serve programs are plain jitted
        fns (no compile-watch wrapper), no events are emitted, and the
        served stream still reproduces ``kv_generate`` — the
        uninstrumented path is dispatch-identical."""
        from mxnet_tpu.models import kv_generate
        from mxnet_tpu.serve import DecodeServer
        from mxnet_tpu.telemetry.compile import _CompileWatch

        ref = list(kv_generate(tiny_gpt, onp.array([[7, 8, 9]]),
                               max_new_tokens=4,
                               temperature=0.0)[0, 3:])
        monkeypatch.setenv("MXNET_TELEMETRY", "0")
        before = len(telemetry.events())
        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           autostart=False)
        s = srv.submit(onp.array([7, 8, 9]), max_new_tokens=4)
        while srv.pump():
            pass
        assert s.tokens(30) == ref
        assert not isinstance(srv._progs.step_fn(), _CompileWatch)
        assert srv.counters["step_dispatches"] >= 1
        assert len(telemetry.events()) == before    # nothing emitted
        srv.close()

    def test_mem_off_serve_no_fields(self, monkeypatch, tiny_gpt):
        """``MXNET_TELEMETRY_MEM=0`` (the default): serve compile
        events carry no ``mem_*`` fields and no extra AOT compile
        happens — the PR-9 event schema is unchanged."""
        from mxnet_tpu.serve import DecodeServer

        monkeypatch.setenv("MXNET_TELEMETRY_MEM", "0")
        srv = DecodeServer(tiny_gpt, max_total_len=24, pool_sizes=(1,),
                           autostart=False)
        s = srv.submit(onp.array([3, 4]), max_new_tokens=3)
        while srv.pump():
            pass
        s.tokens(30)
        evs = [e for e in telemetry.events("compile")
               if e.get("server") == srv.telemetry_label]
        assert evs, "serve compile events missing"
        assert not any(k.startswith("mem_") for e in evs for k in e)
        srv.close()


# --------------------------------------------------------------------- #
# tools/memory_report.py
# --------------------------------------------------------------------- #

def _mem_stream(pool_bytes=4096, budget=None):
    cfg = {"ts": 1.0, "kind": "serve_config", "server": "m0",
           "pool_sizes": [2], "admit_sizes": [1, 2],
           "prefill_buckets": [8], "max_total_len": 32,
           "sync_mode": False, "hbm_budget": budget,
           "pool_bytes": pool_bytes}
    return [
        cfg,
        {"ts": 1.1, "kind": "compile", "site": "serve.step",
         "server": "m0", "pool": 2, "wall_s": 0.5, "cache_size": 1,
         "mem_arg_bytes": 1000, "mem_out_bytes": 500,
         "mem_temp_bytes": 2048, "mem_code_bytes": 0,
         "mem_alias_bytes": 0, "mem_peak_bytes": 3548},
        {"ts": 1.2, "kind": "device_memory", "subsystem":
         "serve.kv_pool", "key": "m0", "device": "cpu:0",
         "bytes": pool_bytes, "subsystem_bytes": pool_bytes},
        {"ts": 1.3, "kind": "device_memory", "subsystem":
         "train.params", "key": "trainer0", "device": "cpu:0",
         "bytes": 800, "subsystem_bytes": 800},
        {"ts": 2.0, "kind": "serve_stats", "server": "m0", "steps": 4,
         "occupancy": 0.5, "pool_bytes": pool_bytes,
         "counters": {"step_dispatches": 4, "admit_dispatches": 1,
                      "sync_requests": 0, "pool_grows": 0}},
    ]


class TestMemoryReport:
    def test_budget_table_and_fit(self):
        sys.path.insert(0, "/root/repo")
        from tools import memory_report

        events = _mem_stream()
        comp = memory_report.compile_memory(events)
        assert comp[0]["site"] == "serve.step"
        assert comp[0]["temp_bytes"] == 2048
        subs = memory_report.subsystem_memory(events)
        assert subs["serve.kv_pool"]["cpu:0"] == 4096
        table = memory_report.budget_table(events)
        total = table[-1]
        assert total["kind"] == "total"
        assert total["bytes"] == 4096 + 800 + 2048
        good = memory_report.fit_verdict(events, 1 << 20)
        assert good["fits"] and good["measured"]
        assert good["headroom_bytes"] > 0
        bad = memory_report.fit_verdict(events, 1024)
        assert not bad["fits"] and bad["headroom_bytes"] < 0
        # an UNMEASURED recording must never pass a fit gate: 0 bytes
        # of telemetry is "don't know", not "fits"
        empty = memory_report.fit_verdict(
            [{"ts": 1.0, "kind": "bench"}], 1 << 30)
        assert not empty["measured"] and not empty["fits"]
        # accountant-only streams (recorded without MXNET_TELEMETRY_
        # MEM=1) are ALSO unmeasured: resident rows without any
        # per-executable scratch cannot answer "does a step fit"
        acct_only = memory_report.fit_verdict(
            [e for e in events if e["kind"] != "compile"], 1 << 30)
        assert not acct_only["measured"] and not acct_only["fits"]
        # the fit math uses PEAK bytes: a pool dropped to 0 at close
        # still counts (it had to fit while live); the last-known
        # display view reports the 0
        dropped = events + [
            {"ts": 3.0, "kind": "device_memory",
             "subsystem": "serve.kv_pool", "key": "m0",
             "device": "cpu:0", "bytes": 0, "subsystem_bytes": 0}]
        t2 = memory_report.budget_table(dropped)
        assert t2[-1]["bytes"] == 4096 + 800 + 2048, t2
        assert memory_report.subsystem_memory(
            dropped)["serve.kv_pool"]["cpu:0"] == 0
        text = memory_report.render(events)
        assert "serve.kv_pool" in text and "TOTAL" in text

    def test_cli_fit_exit_codes(self, tmp_path):
        path = str(tmp_path / "mem.jsonl")
        with open(path, "w") as fh:
            for e in _mem_stream():
                fh.write(json.dumps(e) + "\n")
        ok = subprocess.run(
            [sys.executable, "tools/memory_report.py", path,
             "--hbm", "16G"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert ok.returncode == 0, ok.stderr
        assert "FITS" in ok.stdout
        over = subprocess.run(
            [sys.executable, "tools/memory_report.py", path,
             "--hbm", "1k"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert over.returncode == 1
        assert "DOES NOT FIT" in over.stdout
        js = subprocess.run(
            [sys.executable, "tools/memory_report.py", path, "--json"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert js.returncode == 0
        parsed = json.loads(js.stdout)
        assert parsed["budget"][-1]["kind"] == "total"
        # malformed --hbm is a clean argparse error, not a traceback
        bad = subprocess.run(
            [sys.executable, "tools/memory_report.py", path,
             "--hbm", "16GB"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert bad.returncode == 2
        assert "--hbm" in bad.stderr and "Traceback" not in bad.stderr
        # a recording with no memory telemetry fails the gate
        empty_path = str(tmp_path / "empty.jsonl")
        with open(empty_path, "w") as fh:
            fh.write(json.dumps({"ts": 1.0, "kind": "bench"}) + "\n")
        unmeasured = subprocess.run(
            [sys.executable, "tools/memory_report.py", empty_path,
             "--hbm", "16G"],
            capture_output=True, text=True, cwd="/root/repo",
            timeout=60)
        assert unmeasured.returncode == 1
        assert "NO MEMORY TELEMETRY" in unmeasured.stdout

    def test_memory_report_smoke(self, tmp_path):
        """``tools/memory_report.py --smoke`` records a tiny train +
        serve workload under ``MXNET_TELEMETRY_MEM=1`` and asserts the
        whole pipeline (the ISSUE 10 tier-1 gate)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("MXNET_TELEMETRY_JSONL", None)
        r = subprocess.run(
            [sys.executable, "tools/memory_report.py", "--smoke"],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=540)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "memory report smoke OK" in r.stdout
        assert "gluon.fused_step" in r.stdout
        assert "serve.step" in r.stdout


class TestCheckServeBudget:
    """telemetry_report --check-serve: pool bytes vs configured
    budget, from the recording alone."""

    def test_within_budget_passes(self):
        from tools import telemetry_report

        events = _mem_stream(pool_bytes=4096, budget=8192)
        assert telemetry_report.check_serve(events) == []

    def test_over_budget_flagged(self):
        from tools import telemetry_report

        events = _mem_stream(pool_bytes=4096, budget=1000)
        fails = telemetry_report.check_serve(events)
        assert any("hbm_budget" in f for f in fails)

    def test_no_budget_not_checked(self):
        from tools import telemetry_report

        events = _mem_stream(pool_bytes=4096, budget=None)
        assert telemetry_report.check_serve(events) == []

    def test_pages_over_capacity_flagged(self):
        """ISSUE 16: serve_stats carrying the paged-pool fields must
        report pages_in_use <= pages_total; pre-paging streams lack
        the fields and skip the check (the no-budget stream above)."""
        from tools import telemetry_report

        events = _mem_stream(pool_bytes=4096)
        stats = next(e for e in events if e["kind"] == "serve_stats")
        stats["pages_total"] = 8
        stats["pages_in_use"] = 3
        assert telemetry_report.check_serve(events) == []
        stats["pages_in_use"] = 9
        fails = telemetry_report.check_serve(events)
        assert any("pool capacity" in f for f in fails), fails

    @pytest.mark.parametrize("kv_dtype,page_bytes",
                             [("native", 512), ("int8", 132)])
    def test_pool_bytes_vs_priced_pages(self, kv_dtype, page_bytes):
        """ISSUE 18: serve_stats carrying the dtype-priced page fields
        must satisfy ``pages_total * page_bytes <= pool_bytes`` within
        the slot-state slack — the identity that catches a pricer that
        forgot an int8 pool's scales (or priced codes at f32).
        Recordings from before the fields existed skip the check."""
        from tools import telemetry_report

        total = 8
        events = _mem_stream(pool_bytes=total * page_bytes + 58)
        stats = next(e for e in events if e["kind"] == "serve_stats")
        stats.update(pages_total=total, pages_in_use=0, num_slots=2,
                     kv_dtype=kv_dtype, page_bytes=page_bytes)
        assert telemetry_report.check_serve(events) == []
        # a pool priced at the WRONG dtype (4x codes) is flagged
        stats["pool_bytes"] = total * page_bytes * 4
        events[0]["pool_bytes"] = stats["pool_bytes"]
        fails = telemetry_report.check_serve(events)
        assert any("priced page bytes" in f and kv_dtype in f
                   for f in fails), fails
        # a torn-down pool (pool_bytes 0) has nothing resident: skip
        stats["pool_bytes"] = 0
        events[0]["pool_bytes"] = 0
        assert telemetry_report.check_serve(events) == []
