"""Golden ``.params`` fixture: bit-exact interchange with the public
apache/mxnet NDArray binary format (VERDICT r2 item 10, SURVEY.md §5.4a).

``tests/fixtures/golden.params`` was written by an INDEPENDENT
struct.pack generator (``make_golden_params.py``) straight from the
format spec — these tests pin the serializer to that byte layout in both
directions."""
import os

import numpy as onp

import mxnet_tpu as mx

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures")
GOLDEN = os.path.join(FIXTURE_DIR, "golden.params")


def _expected():
    import sys
    sys.path.insert(0, FIXTURE_DIR)
    try:
        from make_golden_params import golden_arrays
    finally:
        sys.path.pop(0)
    return golden_arrays()


def test_load_golden_fixture():
    loaded = mx.nd.load(GOLDEN)
    expected = dict(_expected())
    assert set(loaded.keys()) == set(expected.keys())
    for name, arr in expected.items():
        got = loaded[name].asnumpy()
        assert got.dtype == arr.dtype, name
        assert got.shape == arr.shape, name
        onp.testing.assert_array_equal(got, arr, err_msg=name)


def test_save_reproduces_golden_bytes(tmp_path):
    """Writing the same dict must reproduce the fixture byte-for-byte."""
    data = {name: mx.nd.array(arr, dtype=arr.dtype)
            for name, arr in _expected()}
    out = tmp_path / "roundtrip.params"
    mx.nd.save(str(out), data)
    with open(GOLDEN, "rb") as f:
        want = f.read()
    with open(out, "rb") as f:
        got = f.read()
    assert got == want, (
        f"serializer drifted from the golden byte layout "
        f"(len {len(got)} vs {len(want)})")


def test_i64_demotes_exactly_or_raises(tmp_path):
    """64-bit blobs (jax x64 off): in-range values demote exactly to
    32-bit; out-of-range fails loudly instead of silently truncating."""
    import struct

    import pytest

    def write(path, arr):
        import sys
        sys.path.insert(0, FIXTURE_DIR)
        try:
            from make_golden_params import write_blob
        finally:
            sys.path.pop(0)
        with open(path, "wb") as f:
            f.write(struct.pack("<QQ", 0x112, 0))
            f.write(struct.pack("<Q", 1))
            write_blob(f, arr)
            f.write(struct.pack("<Q", 0))

    ok = tmp_path / "ok.params"
    write(ok, onp.asarray([1, -5, 2**30], dtype=onp.int64))
    (got,) = mx.nd.load(str(ok))
    onp.testing.assert_array_equal(got.asnumpy(), [1, -5, 2**30])

    bad = tmp_path / "bad.params"
    write(bad, onp.asarray([2**40], dtype=onp.int64))
    with pytest.raises(mx.base.MXNetError):
        mx.nd.load(str(bad))


def test_round_trip_preserves_bytes(tmp_path):
    """load(golden) -> save -> identical bytes (lossless round-trip)."""
    loaded = mx.nd.load(GOLDEN)
    out = tmp_path / "again.params"
    mx.nd.save(str(out), loaded)
    with open(GOLDEN, "rb") as f:
        want = f.read()
    with open(out, "rb") as f:
        got = f.read()
    assert got == want
