"""Legacy multi-device data parallelism (VERDICT r1 item 8): Parameter
per-ctx replicas + Trainer/kvstore grad reduction, on the 8-device virtual
CPU mesh.  Mirrors the reference pattern: initialize(ctx=[...]) →
split_and_load → per-ctx forward/backward → trainer.step."""
import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.utils import split_and_load


def _ctxs(n=4):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} devices, have {len(devs)}")
    return [mx.Context("cpu", i) for i in range(n)]


class TestParameterReplicas:
    def test_replicas_created_per_ctx(self):
        ctxs = _ctxs(4)
        net = gluon.nn.Dense(8, in_units=4)
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        w = net.weight
        assert len(w.list_data()) == 4
        assert [c.device_id for c in w.list_ctx()] == [0, 1, 2, 3]
        # each replica actually lives on its own device
        for i, arr in enumerate(w.list_data()):
            assert list(arr._data.devices())[0].id == i
        # replicas start identical
        base = w.list_data()[0].asnumpy()
        for arr in w.list_data()[1:]:
            onp.testing.assert_array_equal(arr.asnumpy(), base)

    def test_data_ctx_lookup_and_missing_ctx_error(self):
        ctxs = _ctxs(2)
        net = gluon.nn.Dense(3, in_units=2)
        net.initialize(ctx=ctxs)
        arr = net.weight.data(ctxs[1])
        assert list(arr._data.devices())[0].id == 1
        with pytest.raises(mx.MXNetError, match="not initialized on"):
            net.weight.data(mx.Context("cpu", 7))

    def test_forward_uses_input_device_replica(self):
        ctxs = _ctxs(2)
        net = gluon.nn.Dense(5, in_units=3)
        net.initialize(ctx=ctxs)
        x1 = mx.nd.array(onp.ones((2, 3), onp.float32)).as_in_context(ctxs[1])
        out = net(x1)
        assert list(out._data.devices())[0].id == 1


class TestMultiDeviceTraining:
    def _train(self, ctxs, kvstore, steps=3, hybridize=False):
        mx.random.seed(0)
        net = gluon.nn.Dense(1, in_units=4)
        net.initialize(mx.init.Constant(0.1), ctx=ctxs)
        if hybridize:
            net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=kvstore)
        rng = onp.random.RandomState(0)
        X = rng.rand(16, 4).astype(onp.float32)  # fixed total batch
        Y = (X.sum(1, keepdims=True) * 2).astype(onp.float32)
        loss_fn = gluon.loss.L2Loss()
        losses = []
        for _ in range(steps):
            xs = split_and_load(mx.nd.array(X), ctxs)
            ys = split_and_load(mx.nd.array(Y), ctxs)
            with autograd.record():
                ls = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
            for l in ls:
                l.backward()
            trainer.step(X.shape[0])
            losses.append(float(sum(l.asnumpy().mean() for l in ls)))
        return net, losses

    @pytest.mark.parametrize("kvstore", ["device", "local"])
    def test_multi_ctx_training_converges(self, kvstore):
        ctxs = _ctxs(4)
        net, losses = self._train(ctxs, kvstore)
        assert losses[-1] < losses[0], losses
        # all replicas stay in sync after updates
        reps = [a.asnumpy() for a in net.weight.list_data()]
        for r in reps[1:]:
            onp.testing.assert_allclose(r, reps[0], rtol=1e-6)

    def test_multi_ctx_matches_single_ctx_math(self):
        """N-device DP with summed grads / N-scaled step must equal the
        same single-device batch run (the reference DP contract)."""
        ctxs = _ctxs(2)
        net_m, _ = self._train(ctxs, "device", steps=2)
        net_s, _ = self._train([mx.Context("cpu", 0)], "device", steps=2)
        onp.testing.assert_allclose(net_m.weight.data().asnumpy(),
                                    net_s.weight.data().asnumpy(),
                                    rtol=1e-5, atol=1e-6)

    def test_hybridized_multi_ctx(self):
        ctxs = _ctxs(2)
        net, losses = self._train(ctxs, "device", hybridize=True)
        assert losses[-1] < losses[0], losses

    def test_gradients_actually_computed_per_device(self):
        ctxs = _ctxs(2)
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize(ctx=ctxs)
        xs = split_and_load(mx.nd.array(onp.ones((4, 3), onp.float32)), ctxs)
        with autograd.record():
            outs = [net(x).sum() for x in xs]
        for o in outs:
            o.backward()
        grads = net.weight.list_grad()
        assert len(grads) == 2
        for i, g in enumerate(grads):
            assert list(g._data.devices())[0].id == i
            assert onp.abs(g.asnumpy()).sum() > 0
