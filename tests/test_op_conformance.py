"""Registry-wide operator conformance sweep (VERDICT r3 item 3).

Mirrors the reference's registry-wide ``check_consistency`` strategy
(SURVEY.md §7): iterate EVERY op in the registry — nothing is tested "by
name"; a newly registered op is swept automatically.  For each op:

- **forward smoke on ≥2 dtypes** (float32 + bfloat16 for float ops; ops
  with a fixed natural dtype — int indices, int8 quantized, bool — run
  twice with their natural inputs and are listed in ``FIXED_DTYPE`` with
  the reason), all outputs finite;
- **vjp check** for every op registered ``differentiable=True``: the
  gradient of the summed float outputs w.r.t. every float input computes
  and is finite.

``SPECIALS`` supplies inputs for ops whose generic inputs don't fit
(shape/rank/dtype constraints); ``SKIP`` documents every exemption with
the reason and the place the op IS exercised.  A meta-test asserts the
tables only name real ops, so entries cannot go stale silently.
"""
import inspect

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.ops import registry

# --------------------------------------------------------------------- #
# input builders
# --------------------------------------------------------------------- #
_SEED = 0


def F(*shape):
    """Positive-ish float array factory (dtype applied per sweep)."""
    def make(dt):
        rng = onp.random.RandomState(_SEED)
        return jnp.asarray(rng.rand(*shape) + 0.1, dt)
    return make


def FN(*shape):
    """Zero-centered float array factory."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 1)
        return jnp.asarray(rng.randn(*shape), dt)
    return make


def I(*shape, lo=0, hi=3):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 2)
        return jnp.asarray(rng.randint(lo, hi, shape), jnp.int32)
    return make


def B(*shape):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 3)
        return jnp.asarray(rng.rand(*shape) > 0.5)
    return make


def I8(*shape):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 4)
        return jnp.asarray(rng.randint(-10, 10, shape), jnp.int8)
    return make


def PSD(n):
    """Symmetric positive-definite matrix (for potrf/potri/syevd)."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 5)
        a = rng.randn(n, n)
        return jnp.asarray(a @ a.T + n * onp.eye(n), dt)
    return make


def TRI(n):
    """Lower-triangular non-singular matrix."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 6)
        return jnp.asarray(onp.tril(rng.rand(n, n)) + onp.eye(n), dt)
    return make


def SORTED(n):
    def make(dt):
        return jnp.asarray(onp.linspace(0.0, 1.0, n), dt)
    return make


def U(*shape, lo=0.05, hi=0.85):
    """Uniform in an open sub-interval — for domain-restricted ops
    (arcsin/arccos/logit/erfinv/arctanh need |x| < 1 or x in (0,1))."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 7)
        return jnp.asarray(rng.uniform(lo, hi, shape), dt)
    return make


def Z(*shape):
    return lambda dt: jnp.zeros(shape, dt)


def KEY():
    def make(dt):
        return jax.random.PRNGKey(0)
    return make


def spec(*arg_makers, **kwargs):
    """(args..., kwargs) special-case entry."""
    return lambda dt: ([m(dt) for m in arg_makers], dict(kwargs))


# --------------------------------------------------------------------- #
# exemptions — every entry carries its reason (VERDICT: explicit
# skip-list documenting every exemption)
# --------------------------------------------------------------------- #
SKIP = {
    "ring_attention": "requires an 'sp' mesh axis; parity-tested in "
                      "tests/test_parallel.py and the __graft_entry__ "
                      "dryrun (ring == dense attention, loss + grads)",
}

# ops whose inputs have one natural dtype (indices, quantized int8,
# packed bits, ...): the two sweep passes run the same natural inputs —
# there is no second meaningful dtype for them
FIXED_DTYPE = {
    "bitwise_and": "int-only by definition",
    "bitwise_or": "int-only by definition",
    "bitwise_xor": "int-only by definition",
    "bitwise_not": "int-only by definition",
    "left_shift": "int-only by definition",
    "right_shift": "int-only by definition",
    "quantized_conv_int8": "int8 storage is the op's contract",
    "quantized_matmul_int8": "int8 storage is the op's contract",
}

# float ops whose backing XLA kernels are f32/f64-only on every backend
# (lax.linalg decompositions and FFT) — swept at float32 twice
F32_ONLY = {
    "linalg_potrf", "linalg_potri", "linalg_syevd", "linalg_inverse",
    "linalg_det", "linalg_slogdet", "linalg_trsm", "linalg_trmm",
    "linalg_gelqf", "linalg_extracttrian", "linalg_maketrian",
    "linalg_sumlogdiag", "linalg_syrk", "linalg_gemm", "linalg_gemm2",
    "fft", "ifft", "interp_op", "searchsorted",
    "_DropoutImpl",  # PRNG key input; bf16 data path covered via p=0
}

# --------------------------------------------------------------------- #
# static-kwarg defaults by parameter name (applied when a required
# keyword-only parameter has no entry in SPECIALS)
# --------------------------------------------------------------------- #
KWARG_DEFAULTS = {
    "lr": 0.05,
    "axis": 0,
    "shift": 1,
    "repeats": 2,
    "depth": 3,
    "q": 50.0,
    "dtype": "float32",
    "a_min": 0.2,
    "a_max": 0.8,
    "max_norm": 1.0,
    "indices_or_sections": 2,
}

# --------------------------------------------------------------------- #
# per-op input specials
# --------------------------------------------------------------------- #
SPECIALS = {
    # ---- NCHW / vision ------------------------------------------------ #
    "LRN": spec(F(1, 3, 8, 8)),
    "ROIPooling": spec(F(1, 3, 8, 8),
                       lambda dt: jnp.asarray(
                           [[0, 0, 0, 6, 6], [0, 1, 1, 7, 7]], jnp.float32),
                       pooled_size=(2, 2), spatial_scale=1.0),
    "_contrib_ROIAlign": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray([[0, 0, 0, 6, 6]], jnp.float32),
        pooled_size=(2, 2), spatial_scale=1.0),
    "SpatialTransformer": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray([[1, 0, 0, 0, 1, 0]], dt),
        target_shape=(8, 8)),
    "UpSampling": spec(F(1, 3, 4, 4), scale=2, sample_type="nearest"),
    "_contrib_BilinearResize2D": spec(F(1, 3, 4, 4), height=8, width=8),
    "_contrib_DeformableConvolution": spec(
        F(1, 4, 8, 8), FN(1, 18, 8, 8), FN(2, 4, 3, 3),
        kernel=(3, 3), num_filter=2, pad=(1, 1)),
    "_contrib_MultiBoxPrior": spec(F(1, 3, 8, 8), sizes=(0.5, 0.25),
                                   ratios=(1.0, 2.0)),
    "_contrib_MultiBoxDetection": spec(
        F(1, 2, 4),                       # cls_prob (N, classes+1, A)
        FN(1, 16),                        # loc_pred (N, A*4)
        lambda dt: jnp.asarray(
            onp.random.RandomState(9).rand(1, 4, 4) * 0.5, jnp.float32)),
    "_contrib_MultiBoxTarget": spec(
        lambda dt: jnp.asarray(
            onp.random.RandomState(9).rand(1, 4, 4) * 0.5, jnp.float32),
        lambda dt: jnp.asarray([[[0, 0.1, 0.1, 0.4, 0.4]]], jnp.float32),
        F(1, 2, 4)),                      # cls_pred (N, classes+1, A)
    "_contrib_Proposal": spec(
        F(1, 2, 4, 4), FN(1, 4, 4, 4),
        lambda dt: jnp.asarray([[64, 64, 1.0]], jnp.float32),
        scales=(8,), ratios=(1.0,), rpn_pre_nms_top_n=8,
        rpn_post_nms_top_n=4, rpn_min_size=1),
    "pad": spec(F(1, 1, 4, 4), mode="constant",
                pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
    "im2col": spec(F(1, 3, 8, 8), kernel=(3, 3)),
    "col2im": spec(F(1, 27, 36), output_size=(8, 8), kernel=(3, 3)),
    "depth_to_space": spec(F(1, 4, 4, 4), block_size=2),
    "space_to_depth": spec(F(1, 1, 4, 4), block_size=2),

    # ---- image (HWC / NHWC) ------------------------------------------ #
    "_image_to_tensor": spec(F(8, 8, 3)),
    "_image_crop": spec(F(8, 8, 3), x=1, y=1, width=4, height=4),
    "_image_resize": spec(F(8, 8, 3), size=(4, 4)),
    "_image_flip_top_bottom": spec(F(8, 8, 3)),
    "_image_random_flip_top_bottom": spec(F(8, 8, 3)),
    "_image_random_contrast": spec(F(8, 8, 3)),
    "_image_random_saturation": spec(F(8, 8, 3)),

    # ---- norm layers -------------------------------------------------- #
    "LayerNorm": spec(FN(4, 5), F(5), FN(5)),
    "RMSNorm": spec(FN(4, 5), F(5)),
    "_BatchNormStats": spec(FN(2, 5, 4, 4), F(5), FN(5), FN(5), F(5)),
    "GroupNorm": spec(FN(2, 4, 3, 3), F(4), FN(4), num_groups=2),
    "InstanceNorm": spec(FN(2, 4, 3, 3), F(4), FN(4)),
    "prelu": spec(FN(2, 4), F(4)),

    # ---- conv family -------------------------------------------------- #
    "Convolution": spec(F(1, 3, 8, 8), FN(2, 3, 3, 3),
                        kernel=(3, 3), num_filter=2, no_bias=True),
    "Deconvolution": spec(F(1, 3, 8, 8), FN(3, 2, 3, 3),
                          kernel=(3, 3), num_filter=2),
    "Correlation": spec(F(1, 3, 8, 8), F(1, 3, 8, 8)),
    "BilinearSampler": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray(onp.random.RandomState(8).uniform(
            -0.9, 0.9, (1, 2, 8, 8)), dt)),
    "GridGenerator": spec(
        lambda dt: jnp.asarray([[1, 0, 0, 0, 1, 0]], dt),
        transform_type="affine", target_shape=(8, 8)),

    # ---- losses with class labels ------------------------------------ #
    "CTCLoss": spec(FN(5, 2, 4),
                    lambda dt: jnp.asarray([[1, 2], [2, 1]], jnp.float32)),
    "SVMOutput": spec(FN(4, 5),
                      lambda dt: jnp.asarray([0, 1, 2, 3], jnp.float32)),

    # ---- domain-restricted elementwise -------------------------------- #
    "arcsin": spec(U(4, 5)),
    "arccos": spec(U(4, 5)),
    "arctanh": spec(U(4, 5)),
    "erfinv": spec(U(4, 5)),
    "logit": spec(U(4, 5)),
    "arccosh": spec(lambda dt: 1.0 + F(4, 5)(dt)),
    "log1mexp": spec(lambda dt: -F(4, 5)(dt)),

    # ---- indexing/selection ------------------------------------------ #
    "batch_take": spec(F(4, 5), I(4, hi=5)),
    "choose_element_0index": spec(F(4, 5), I(4, hi=5)),
    "pick": spec(F(4, 5), I(4, hi=5)),
    "fill_element_0index": spec(F(4, 5), F(4), I(4, hi=5)),
    "softmax_cross_entropy": spec(FN(4, 5), I(4, hi=5)),
    "one_hot": spec(I(4, hi=3), depth=3),
    "gather_nd": spec(F(4, 5), I(2, 3, hi=4)),
    "scatter_nd": spec(F(3), I(2, 3, hi=3), shape=(4, 5)),
    "boolean_mask": spec(F(4, 5), B(4)),
    "_contrib_index_add": spec(F(4, 5), I(2, hi=4), F(2, 5)),
    "_contrib_index_copy": spec(F(4, 5), I(2, hi=4), F(2, 5)),
    "bincount_op": spec(I(10, hi=5), length=5),
    "searchsorted": spec(SORTED(5), F(3)),
    "unravel_index": spec(I(4, hi=19), shape=(4, 5)),
    "ravel_multi_index": spec(I(2, 3, hi=3), shape=(4, 5)),
    "interp_op": spec(F(4), SORTED(5), FN(5)),

    # ---- shape manipulation ------------------------------------------ #
    "reshape": spec(F(4, 5), shape=(5, 4)),
    "_onnx_expand": spec(F(4, 1), shape=(1, 5)),
    "broadcast_to": spec(F(1, 5), shape=(4, 5)),
    "broadcast_axis": spec(F(1, 5), axis=0, size=4),
    "slice": spec(F(4, 5), begin=(0, 1), end=(3, 4)),
    "slice_axis": spec(F(4, 5), axis=0, begin=0, end=2),
    "split": spec(F(4, 6), num_outputs=2),
    "dsplit": spec(F(4, 4, 4), indices_or_sections=2),
    "hsplit": spec(F(4, 4), indices_or_sections=2),
    "tile": spec(F(4, 5), reps=(2, 1)),
    "moveaxis": spec(F(4, 5), source=0, destination=1),
    "resize_op": spec(F(4, 5), new_shape=(2, 10)),
    "flip": spec(F(4, 5), axis=0),
    "cast": spec(F(4, 5), dtype="float16"),

    # ---- int/bool dtype ops ------------------------------------------ #
    "bitwise_and": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_or": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_xor": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_not": spec(I(4, 5, hi=7)),
    "left_shift": spec(I(4, 5, hi=7), I(4, 5, hi=2)),
    "right_shift": spec(I(4, 5, hi=7), I(4, 5, hi=2)),

    # ---- matmul/linalg ------------------------------------------------ #
    "dot": spec(F(4, 5), F(5, 3)),
    "matmul": spec(F(4, 5), F(5, 3)),
    "batch_dot": spec(F(2, 4, 5), F(2, 5, 3)),
    "linalg_gemm": spec(F(4, 5), F(5, 3), FN(4, 3)),
    "linalg_gemm2": spec(F(4, 5), F(5, 3)),
    "linalg_det": spec(PSD(4)),
    "linalg_slogdet": spec(PSD(4)),
    "linalg_inverse": spec(PSD(4)),
    "linalg_potrf": spec(PSD(4)),
    "linalg_potri": spec(PSD(4)),
    "linalg_syevd": spec(PSD(4)),
    "linalg_trmm": spec(TRI(4), F(4, 3)),
    "linalg_trsm": spec(TRI(4), F(4, 3)),
    "linalg_maketrian": spec(F(2, 6)),
    "cross_op": spec(F(4, 3), F(4, 3)),
    "ifft": spec(F(4, 8)),

    # ---- attention / rnn / rope -------------------------------------- #
    "flash_attention": spec(FN(2, 2, 8, 16), FN(2, 2, 8, 16),
                            FN(2, 2, 8, 16)),
    "rope": spec(FN(2, 2, 8, 16)),
    "_contrib_interleaved_matmul_selfatt_qk": spec(FN(4, 2, 24), heads=2),
    "_contrib_interleaved_matmul_selfatt_valatt": spec(
        FN(4, 2, 24), F(4, 4, 4), heads=2),
    "fused_rnn": spec(FN(3, 2, 4), FN(1, 2, 5), FN(1, 2, 5),
                      FN(20, 4), FN(20, 5), FN(20), FN(20),
                      mode="lstm"),
    "rnn_param_concat": spec(FN(3, 4), FN(3, 4)),
    "_DropoutImpl": spec(FN(4, 5), KEY(), p=0.5),

    # ---- quantization ------------------------------------------------- #
    "quantized_matmul_int8": spec(I8(4, 5), I8(3, 5), transpose_b=True),
    "quantized_conv_int8": spec(I8(1, 3, 8, 8), I8(2, 3, 3, 3)),

    # ---- optimizer states with domain constraints --------------------- #
    # centered RMSProp: n - g² must stay ≥ 0 (it is a running variance);
    # start from the optimizer's real init (zeros) like the reference
    "rmspropalex_update": spec(F(4, 5), FN(4, 5), Z(4, 5), Z(4, 5),
                               Z(4, 5), lr=0.05),

    # ---- sparse kernels ----------------------------------------------- #
    "_sparse_segment_dot": spec(F(4), I(4, hi=5), I(4, hi=3), F(5, 3),
                                num_segments=3),
    "_sparse_rowsparse_dot": spec(F(2, 5), I(2, hi=4), F(5, 3),
                                  num_rows=4),
    "_sparse_rowsparse_dot_t": spec(F(2, 5), I(2, hi=4), F(2, 3),
                                    num_cols=4),

    # ---- distribution samplers with domain constraints ---------------- #
    "sample_negative_binomial": spec(F(3), U(3)),       # k > 0, p in (0,1)
    "sample_generalized_negative_binomial": spec(F(3), F(3)),

    # ---- variadic / multi-tensor ------------------------------------- #
    "concat": spec(F(4, 5), F(4, 5)),
    "stack": spec(F(4, 5), F(4, 5)),
    "dstack": spec(F(4, 5), F(4, 5)),
    "meshgrid": spec(F(4), F(5)),
    "broadcast_arrays": spec(F(4, 1), F(1, 5)),
    "amp_multicast": spec(F(4, 5), F(4, 5), num_outputs=2),
    "multi_all_finite": spec(F(4, 5), F(4, 5)),
    "reset_arrays": spec(F(4, 5), F(4, 5)),
    "clip_global_norm": spec(FN(4, 5), FN(3), max_norm=1.0),
    "multi_sgd_update": spec(F(4, 5), FN(4, 5), F(3), FN(3),
                             lrs=(0.05, 0.05), wds=(0.0, 0.0)),
    "multi_sgd_mom_update": spec(F(4, 5), FN(4, 5), FN(4, 5),
                                 lrs=(0.05,), wds=(0.0,)),
    "multi_mp_sgd_update": spec(F(4, 5), FN(4, 5), F(4, 5),
                                lrs=(0.05,), wds=(0.0,)),
    "multi_mp_sgd_mom_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                                    lrs=(0.05,), wds=(0.0,)),
    "multi_adamw_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                               lrs=(0.05,), etas=(1.0,)),
    "multi_lamb_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                              learning_rates=(0.05,)),
    "preloaded_multi_sgd_update": spec(
        F(4, 5), FN(4, 5), lambda dt: jnp.asarray([0.05], jnp.float32),
        lambda dt: jnp.asarray([0.0], jnp.float32)),
    "preloaded_multi_sgd_mom_update": spec(
        F(4, 5), FN(4, 5), FN(4, 5),
        lambda dt: jnp.asarray([0.05], jnp.float32),
        lambda dt: jnp.asarray([0.0], jnp.float32)),
}


# --------------------------------------------------------------------- #
# generic builder for everything else
# --------------------------------------------------------------------- #
def build_inputs(o, dt):
    if o.name in SPECIALS:
        return SPECIALS[o.name](dt)
    sig = inspect.signature(o.fn)
    if o.variadic:
        return [F(4, 5)(dt), F(4, 5)(dt)], {}
    args = []
    kwargs = {}
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.default is inspect.Parameter.empty:
                args.append(F(4, 5)(dt))
        elif p.kind == p.KEYWORD_ONLY and \
                p.default is inspect.Parameter.empty:
            if p.name not in KWARG_DEFAULTS:
                raise AssertionError(
                    f"op {o.name}: required kwarg {p.name!r} has no "
                    "KWARG_DEFAULTS entry and no SPECIALS entry — add one")
            kwargs[p.name] = KWARG_DEFAULTS[p.name]
    return args, kwargs


def _flat_outputs(res):
    return list(res) if isinstance(res, (tuple, list)) else [res]


def _assert_finite(res, name, dt):
    for r in _flat_outputs(res):
        # check via jnp: onp.asarray(bf16).dtype.kind is 'V', which would
        # silently skip the whole bfloat16 half of the sweep
        if jnp.issubdtype(jnp.asarray(r).dtype, jnp.floating):
            a = onp.asarray(jnp.asarray(r).astype(jnp.float32))
            assert onp.isfinite(a).all(), \
                f"{name}[{dt}]: non-finite output"


def _sweep_dtypes(name):
    if name in FIXED_DTYPE or name in F32_ONLY:
        return [jnp.float32, jnp.float32]
    return [jnp.float32, jnp.bfloat16]


ALL_OPS = registry.list_ops()


@pytest.mark.parametrize("name", ALL_OPS)
def test_forward_smoke(name):
    if name in SKIP:
        pytest.skip(SKIP[name])
    o = registry.OPS[name]
    for dt in _sweep_dtypes(name):
        args, kwargs = build_inputs(o, dt)
        res = o.fn(*args, **kwargs)
        jax.block_until_ready(res)
        _assert_finite(res, name, dt)


@pytest.mark.parametrize(
    "name", [n for n in ALL_OPS if registry.OPS[n].differentiable])
def test_vjp(name):
    if name in SKIP:
        pytest.skip(SKIP[name])
    o = registry.OPS[name]
    args, kwargs = build_inputs(o, jnp.float32)
    flat = list(args)
    diff_idx = [i for i, a in enumerate(flat)
                if hasattr(a, "dtype") and
                jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)]
    if not diff_idx:
        pytest.skip(f"{name}: no float inputs to differentiate")

    def scalar_loss(*diff_args):
        full = list(flat)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        res = o.fn(*full, **kwargs)
        outs = [r for r in _flat_outputs(res)
                if jnp.issubdtype(jnp.asarray(r).dtype, jnp.floating)]
        if not outs:
            return jnp.float32(0.0)
        return sum(jnp.sum(r.astype(jnp.float32)) for r in outs)

    grads = jax.grad(scalar_loss, argnums=tuple(range(len(diff_idx))))(
        *[flat[i] for i in diff_idx])
    for g in grads:
        assert onp.isfinite(onp.asarray(g)).all(), \
            f"{name}: non-finite gradient"


def test_exemption_tables_are_live():
    """SKIP/SPECIALS/FIXED_DTYPE/F32_ONLY entries must name real ops —
    stale entries fail here instead of silently shrinking coverage."""
    known = set(ALL_OPS)
    for table, tname in ((SKIP, "SKIP"), (SPECIALS, "SPECIALS"),
                         (FIXED_DTYPE, "FIXED_DTYPE"),
                         (F32_ONLY, "F32_ONLY")):
        stale = set(table) - known
        assert not stale, f"{tname} names unknown ops: {sorted(stale)}"


def test_sweep_covers_registry():
    """The sweep runs every registered op minus the documented SKIPs —
    and the SKIP list stays short, so coverage cannot quietly erode."""
    assert len(ALL_OPS) >= 370
    assert set(SKIP) <= set(ALL_OPS)
    assert len(SKIP) <= 5, "document the op in SPECIALS instead of SKIP"
