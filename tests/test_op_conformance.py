"""Registry-wide operator conformance sweep (VERDICT r3 item 3).

Mirrors the reference's registry-wide ``check_consistency`` strategy
(SURVEY.md §7): iterate EVERY op in the registry — nothing is tested "by
name"; a newly registered op is swept automatically.  For each op:

- **forward smoke on ≥2 dtypes** (float32 + bfloat16 for float ops; ops
  with a fixed natural dtype — int indices, int8 quantized, bool — run
  twice with their natural inputs and are listed in ``FIXED_DTYPE`` with
  the reason), all outputs finite;
- **vjp check** for every op registered ``differentiable=True``: the
  gradient of the summed float outputs w.r.t. every float input computes
  and is finite.

``SPECIALS`` supplies inputs for ops whose generic inputs don't fit
(shape/rank/dtype constraints); ``SKIP`` documents every exemption with
the reason and the place the op IS exercised.  A meta-test asserts the
tables only name real ops, so entries cannot go stale silently.
"""
import inspect

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.ops import registry

# --------------------------------------------------------------------- #
# input builders
# --------------------------------------------------------------------- #
_SEED = 0


def F(*shape):
    """Positive-ish float array factory (dtype applied per sweep)."""
    def make(dt):
        rng = onp.random.RandomState(_SEED)
        return jnp.asarray(rng.rand(*shape) + 0.1, dt)
    return make


def FN(*shape):
    """Zero-centered float array factory."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 1)
        return jnp.asarray(rng.randn(*shape), dt)
    return make


def I(*shape, lo=0, hi=3):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 2)
        return jnp.asarray(rng.randint(lo, hi, shape), jnp.int32)
    return make


def B(*shape):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 3)
        return jnp.asarray(rng.rand(*shape) > 0.5)
    return make


def I8(*shape):
    def make(dt):
        rng = onp.random.RandomState(_SEED + 4)
        return jnp.asarray(rng.randint(-10, 10, shape), jnp.int8)
    return make


def PSD(n):
    """Symmetric positive-definite matrix (for potrf/potri/syevd)."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 5)
        a = rng.randn(n, n)
        return jnp.asarray(a @ a.T + n * onp.eye(n), dt)
    return make


def TRI(n):
    """Lower-triangular non-singular matrix."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 6)
        return jnp.asarray(onp.tril(rng.rand(n, n)) + onp.eye(n), dt)
    return make


def SORTED(n):
    def make(dt):
        return jnp.asarray(onp.linspace(0.0, 1.0, n), dt)
    return make


def U(*shape, lo=0.05, hi=0.85):
    """Uniform in an open sub-interval — for domain-restricted ops
    (arcsin/arccos/logit/erfinv/arctanh need |x| < 1 or x in (0,1))."""
    def make(dt):
        rng = onp.random.RandomState(_SEED + 7)
        return jnp.asarray(rng.uniform(lo, hi, shape), dt)
    return make


def Z(*shape):
    return lambda dt: jnp.zeros(shape, dt)


def KEY():
    def make(dt):
        return jax.random.PRNGKey(0)
    return make


def spec(*arg_makers, **kwargs):
    """(args..., kwargs) special-case entry."""
    return lambda dt: ([m(dt) for m in arg_makers], dict(kwargs))


# --------------------------------------------------------------------- #
# exemptions — every entry carries its reason (VERDICT: explicit
# skip-list documenting every exemption)
# --------------------------------------------------------------------- #
SKIP = {
    "ring_attention": "requires an 'sp' mesh axis; parity-tested in "
                      "tests/test_parallel.py and the __graft_entry__ "
                      "dryrun (ring == dense attention, loss + grads)",
}

# ops whose inputs have one natural dtype (indices, quantized int8,
# packed bits, ...): the two sweep passes run the same natural inputs —
# there is no second meaningful dtype for them
FIXED_DTYPE = {
    "bitwise_and": "int-only by definition",
    "bitwise_or": "int-only by definition",
    "bitwise_xor": "int-only by definition",
    "bitwise_not": "int-only by definition",
    "left_shift": "int-only by definition",
    "right_shift": "int-only by definition",
    "quantized_conv_int8": "int8 storage is the op's contract",
    "quantized_matmul_int8": "int8 storage is the op's contract",
}

# float ops whose backing XLA kernels are f32/f64-only on every backend
# (lax.linalg decompositions and FFT) — swept at float32 twice
F32_ONLY = {
    "linalg_potrf", "linalg_potri", "linalg_syevd", "linalg_inverse",
    "linalg_det", "linalg_slogdet", "linalg_trsm", "linalg_trmm",
    "linalg_gelqf", "linalg_extracttrian", "linalg_maketrian",
    "linalg_sumlogdiag", "linalg_syrk", "linalg_gemm", "linalg_gemm2",
    "fft", "ifft", "interp_op", "searchsorted",
    "_DropoutImpl",  # PRNG key input; bf16 data path covered via p=0
}

# --------------------------------------------------------------------- #
# static-kwarg defaults by parameter name (applied when a required
# keyword-only parameter has no entry in SPECIALS)
# --------------------------------------------------------------------- #
KWARG_DEFAULTS = {
    "lr": 0.05,
    "axis": 0,
    "shift": 1,
    "repeats": 2,
    "depth": 3,
    "q": 0.5,  # valid for both quantile ([0,1]) and percentile ([0,100])
    "dtype": "float32",
    "a_min": 0.2,
    "a_max": 0.8,
    "max_norm": 1.0,
    "indices_or_sections": 2,
}

# --------------------------------------------------------------------- #
# per-op input specials
# --------------------------------------------------------------------- #
SPECIALS = {
    # ---- NCHW / vision ------------------------------------------------ #
    "LRN": spec(F(1, 3, 8, 8)),
    "ROIPooling": spec(F(1, 3, 8, 8),
                       lambda dt: jnp.asarray(
                           [[0, 0, 0, 6, 6], [0, 1, 1, 7, 7]], jnp.float32),
                       pooled_size=(2, 2), spatial_scale=1.0),
    "_contrib_ROIAlign": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray([[0, 0, 0, 6, 6]], jnp.float32),
        pooled_size=(2, 2), spatial_scale=1.0),
    "SpatialTransformer": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray([[1, 0, 0, 0, 1, 0]], dt),
        target_shape=(8, 8)),
    "UpSampling": spec(F(1, 3, 4, 4), scale=2, sample_type="nearest"),
    "_contrib_BilinearResize2D": spec(F(1, 3, 4, 4), height=8, width=8),
    "_contrib_DeformableConvolution": spec(
        F(1, 4, 8, 8), FN(1, 18, 8, 8), FN(2, 4, 3, 3),
        kernel=(3, 3), num_filter=2, pad=(1, 1)),
    "_contrib_MultiBoxPrior": spec(F(1, 3, 8, 8), sizes=(0.5, 0.25),
                                   ratios=(1.0, 2.0)),
    "_contrib_MultiBoxDetection": spec(
        F(1, 2, 4),                       # cls_prob (N, classes+1, A)
        FN(1, 16),                        # loc_pred (N, A*4)
        lambda dt: jnp.asarray(
            onp.random.RandomState(9).rand(1, 4, 4) * 0.5, jnp.float32)),
    "_contrib_MultiBoxTarget": spec(
        lambda dt: jnp.asarray(
            onp.random.RandomState(9).rand(1, 4, 4) * 0.5, jnp.float32),
        lambda dt: jnp.asarray([[[0, 0.1, 0.1, 0.4, 0.4]]], jnp.float32),
        F(1, 2, 4)),                      # cls_pred (N, classes+1, A)
    "_contrib_Proposal": spec(
        F(1, 2, 4, 4), FN(1, 4, 4, 4),
        lambda dt: jnp.asarray([[64, 64, 1.0]], jnp.float32),
        scales=(8,), ratios=(1.0,), rpn_pre_nms_top_n=8,
        rpn_post_nms_top_n=4, rpn_min_size=1),
    "pad": spec(F(1, 1, 4, 4), mode="constant",
                pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
    "im2col": spec(F(1, 3, 8, 8), kernel=(3, 3)),
    "col2im": spec(F(1, 27, 36), output_size=(8, 8), kernel=(3, 3)),
    "depth_to_space": spec(F(1, 4, 4, 4), block_size=2),
    "space_to_depth": spec(F(1, 1, 4, 4), block_size=2),

    # ---- image (HWC / NHWC) ------------------------------------------ #
    "_image_to_tensor": spec(F(8, 8, 3)),
    "_image_normalize": spec(F(3, 8, 8), mean=(0.2, 0.3, 0.4),
                             std=(0.5, 0.5, 0.5)),
    "_image_crop": spec(F(8, 8, 3), x=1, y=1, width=4, height=4),
    "_image_resize": spec(F(8, 8, 3), size=(4, 4)),
    "_image_flip_top_bottom": spec(F(8, 8, 3)),
    "_image_random_flip_top_bottom": spec(F(8, 8, 3)),
    "_image_random_contrast": spec(F(8, 8, 3)),
    "_image_random_saturation": spec(F(8, 8, 3)),

    # ---- norm layers -------------------------------------------------- #
    "LayerNorm": spec(FN(4, 5), F(5), FN(5)),
    "RMSNorm": spec(FN(4, 5), F(5)),
    "_BatchNormStats": spec(FN(2, 5, 4, 4), F(5), FN(5), FN(5), F(5)),
    "GroupNorm": spec(FN(2, 4, 3, 3), F(4), FN(4), num_groups=2),
    "InstanceNorm": spec(FN(2, 4, 3, 3), F(4), FN(4)),
    "prelu": spec(FN(2, 4), F(4)),

    # ---- conv family -------------------------------------------------- #
    "Convolution": spec(F(1, 3, 8, 8), FN(2, 3, 3, 3),
                        kernel=(3, 3), num_filter=2, no_bias=True),
    "Deconvolution": spec(F(1, 3, 8, 8), FN(3, 2, 3, 3),
                          kernel=(3, 3), num_filter=2),
    "Correlation": spec(F(1, 3, 8, 8), F(1, 3, 8, 8)),
    "BilinearSampler": spec(
        F(1, 3, 8, 8),
        lambda dt: jnp.asarray(onp.random.RandomState(8).uniform(
            -0.9, 0.9, (1, 2, 8, 8)), dt)),
    "GridGenerator": spec(
        lambda dt: jnp.asarray([[1, 0, 0, 0, 1, 0]], dt),
        transform_type="affine", target_shape=(8, 8)),

    # ---- losses with class labels ------------------------------------ #
    "CTCLoss": spec(FN(5, 2, 4),
                    lambda dt: jnp.asarray([[1, 2], [2, 1]], jnp.float32)),
    "SVMOutput": spec(FN(4, 5),
                      lambda dt: jnp.asarray([0, 1, 2, 3], jnp.float32)),

    # ---- domain-restricted elementwise -------------------------------- #
    "arcsin": spec(U(4, 5)),
    "arccos": spec(U(4, 5)),
    "arctanh": spec(U(4, 5)),
    "erfinv": spec(U(4, 5)),
    "logit": spec(U(4, 5)),
    "arccosh": spec(lambda dt: 1.0 + F(4, 5)(dt)),
    "log1mexp": spec(lambda dt: -F(4, 5)(dt)),

    # ---- indexing/selection ------------------------------------------ #
    "batch_take": spec(F(4, 5), I(4, hi=5)),
    "choose_element_0index": spec(F(4, 5), I(4, hi=5)),
    "pick": spec(F(4, 5), I(4, hi=5)),
    "fill_element_0index": spec(F(4, 5), F(4), I(4, hi=5)),
    "softmax_cross_entropy": spec(FN(4, 5), I(4, hi=5)),
    "one_hot": spec(I(4, hi=3), depth=3),
    "gather_nd": spec(F(4, 5), I(2, 3, hi=4)),
    "scatter_nd": spec(F(3), I(2, 3, hi=3), shape=(4, 5)),
    "boolean_mask": spec(F(4, 5), B(4)),
    "_contrib_index_add": spec(F(4, 5), I(2, hi=4), F(2, 5)),
    "_contrib_index_copy": spec(F(4, 5), I(2, hi=4), F(2, 5)),
    "bincount_op": spec(I(10, hi=5), length=5),
    "searchsorted": spec(SORTED(5), F(3)),
    "unravel_index": spec(I(4, hi=19), shape=(4, 5)),
    "ravel_multi_index": spec(I(2, 3, hi=3), shape=(4, 5)),
    "interp_op": spec(F(4), SORTED(5), FN(5)),

    # ---- shape manipulation ------------------------------------------ #
    "reshape": spec(F(4, 5), shape=(5, 4)),
    "_onnx_expand": spec(F(4, 1), shape=(1, 5)),
    "broadcast_to": spec(F(1, 5), shape=(4, 5)),
    "broadcast_axis": spec(F(1, 5), axis=0, size=4),
    "slice": spec(F(4, 5), begin=(0, 1), end=(3, 4)),
    "slice_axis": spec(F(4, 5), axis=0, begin=0, end=2),
    "split": spec(F(4, 6), num_outputs=2),
    "dsplit": spec(F(4, 4, 4), indices_or_sections=2),
    "hsplit": spec(F(4, 4), indices_or_sections=2),
    "tile": spec(F(4, 5), reps=(2, 1)),
    "moveaxis": spec(F(4, 5), source=0, destination=1),
    "resize_op": spec(F(4, 5), new_shape=(2, 10)),
    "flip": spec(F(4, 5), axis=0),
    "cast": spec(F(4, 5), dtype="float16"),

    # ---- int/bool dtype ops ------------------------------------------ #
    "bitwise_and": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_or": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_xor": spec(I(4, 5, hi=7), I(4, 5, hi=7)),
    "bitwise_not": spec(I(4, 5, hi=7)),
    "left_shift": spec(I(4, 5, hi=7), I(4, 5, hi=2)),
    "right_shift": spec(I(4, 5, hi=7), I(4, 5, hi=2)),

    # ---- matmul/linalg ------------------------------------------------ #
    "dot": spec(F(4, 5), F(5, 3)),
    "matmul": spec(F(4, 5), F(5, 3)),
    "batch_dot": spec(F(2, 4, 5), F(2, 5, 3)),
    "linalg_gemm": spec(F(4, 5), F(5, 3), FN(4, 3)),
    "linalg_gemm2": spec(F(4, 5), F(5, 3)),
    "linalg_det": spec(PSD(4)),
    "linalg_slogdet": spec(PSD(4)),
    "linalg_inverse": spec(PSD(4)),
    "linalg_potrf": spec(PSD(4)),
    "linalg_potri": spec(PSD(4)),
    "linalg_syevd": spec(PSD(4)),
    "linalg_trmm": spec(TRI(4), F(4, 3)),
    "linalg_trsm": spec(TRI(4), F(4, 3)),
    "linalg_maketrian": spec(F(2, 6)),
    "linalg_extracttrian": spec(PSD(4)),
    "gcd": spec(I(4, 5, lo=1, hi=30), I(4, 5, lo=1, hi=30)),
    "lcm": spec(I(4, 5, lo=1, hi=12), I(4, 5, lo=1, hi=12)),
    "ldexp": spec(F(4, 5), I(4, 5, hi=4)),
    "cross_op": spec(F(4, 3), F(4, 3)),
    "ifft": spec(F(4, 8)),

    # ---- attention / rnn / rope -------------------------------------- #
    "flash_attention": spec(FN(2, 2, 8, 16), FN(2, 2, 8, 16),
                            FN(2, 2, 8, 16)),
    "rope": spec(FN(2, 2, 8, 16)),
    "_contrib_interleaved_matmul_selfatt_qk": spec(FN(4, 2, 24), heads=2),
    "_contrib_interleaved_matmul_selfatt_valatt": spec(
        FN(4, 2, 24), F(4, 4, 4), heads=2),
    "fused_rnn": spec(FN(3, 2, 4), FN(1, 2, 5), FN(1, 2, 5),
                      FN(20, 4), FN(20, 5), FN(20), FN(20),
                      mode="lstm"),
    "rnn_param_concat": spec(FN(3, 4), FN(3, 4)),
    "_DropoutImpl": spec(FN(4, 5), KEY(), p=0.5),

    # ---- quantization ------------------------------------------------- #
    "quantized_matmul_int8": spec(I8(4, 5), I8(3, 5), transpose_b=True),
    "quantized_conv_int8": spec(I8(1, 3, 8, 8), I8(2, 3, 3, 3)),

    # ---- optimizer states with domain constraints --------------------- #
    # centered RMSProp: n - g² must stay ≥ 0 (it is a running variance);
    # start from the optimizer's real init (zeros) like the reference
    "rmspropalex_update": spec(F(4, 5), FN(4, 5), Z(4, 5), Z(4, 5),
                               Z(4, 5), lr=0.05),

    # ---- sparse kernels ----------------------------------------------- #
    "_sparse_segment_dot": spec(F(4), I(4, hi=5), I(4, hi=3), F(5, 3),
                                num_segments=3),
    "_sparse_rowsparse_dot": spec(F(2, 5), I(2, hi=4), F(5, 3),
                                  num_rows=4),
    # rhs must have num_rows(=4) rows — the transposed dot gathers
    # rhs[indices] (the value sweep caught the old undersized rhs: jnp
    # clamps out-of-bounds gathers silently)
    "_sparse_rowsparse_dot_t": spec(F(2, 5), I(2, hi=4), F(4, 3),
                                    num_cols=4),

    # ---- distribution samplers with domain constraints ---------------- #
    "sample_negative_binomial": spec(F(3), U(3)),       # k > 0, p in (0,1)
    "sample_generalized_negative_binomial": spec(F(3), F(3)),

    # ---- variadic / multi-tensor ------------------------------------- #
    "concat": spec(F(4, 5), F(4, 5)),
    "stack": spec(F(4, 5), F(4, 5)),
    "dstack": spec(F(4, 5), F(4, 5)),
    "meshgrid": spec(F(4), F(5)),
    "broadcast_arrays": spec(F(4, 1), F(1, 5)),
    "amp_multicast": spec(F(4, 5), F(4, 5), num_outputs=2),
    "multi_all_finite": spec(F(4, 5), F(4, 5)),
    "reset_arrays": spec(F(4, 5), F(4, 5)),
    "clip_global_norm": spec(FN(4, 5), FN(3), max_norm=1.0),
    "multi_sgd_update": spec(F(4, 5), FN(4, 5), F(3), FN(3),
                             lrs=(0.05, 0.05), wds=(0.0, 0.0)),
    "multi_sgd_mom_update": spec(F(4, 5), FN(4, 5), FN(4, 5),
                                 lrs=(0.05,), wds=(0.0,)),
    "multi_mp_sgd_update": spec(F(4, 5), FN(4, 5), F(4, 5),
                                lrs=(0.05,), wds=(0.0,)),
    "multi_mp_sgd_mom_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                                    lrs=(0.05,), wds=(0.0,)),
    "multi_adamw_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                               lrs=(0.05,), etas=(1.0,)),
    "multi_lamb_update": spec(F(4, 5), FN(4, 5), FN(4, 5), F(4, 5),
                              learning_rates=(0.05,)),
    "preloaded_multi_sgd_update": spec(
        F(4, 5), FN(4, 5), lambda dt: jnp.asarray([0.05], jnp.float32),
        lambda dt: jnp.asarray([0.0], jnp.float32)),
    "preloaded_multi_sgd_mom_update": spec(
        F(4, 5), FN(4, 5), FN(4, 5),
        lambda dt: jnp.asarray([0.05], jnp.float32),
        lambda dt: jnp.asarray([0.0], jnp.float32)),
}


# --------------------------------------------------------------------- #
# generic builder for everything else
# --------------------------------------------------------------------- #
def build_inputs(o, dt):
    if o.name in SPECIALS:
        return SPECIALS[o.name](dt)
    sig = inspect.signature(o.fn)
    if o.variadic:
        return [F(4, 5)(dt), F(4, 5)(dt)], {}
    args = []
    kwargs = {}
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.default is inspect.Parameter.empty:
                args.append(F(4, 5)(dt))
        elif p.kind == p.KEYWORD_ONLY and \
                p.default is inspect.Parameter.empty:
            if p.name not in KWARG_DEFAULTS:
                raise AssertionError(
                    f"op {o.name}: required kwarg {p.name!r} has no "
                    "KWARG_DEFAULTS entry and no SPECIALS entry — add one")
            kwargs[p.name] = KWARG_DEFAULTS[p.name]
    return args, kwargs


def _flat_outputs(res):
    return list(res) if isinstance(res, (tuple, list)) else [res]


def _assert_finite(res, name, dt):
    for r in _flat_outputs(res):
        # check via jnp: onp.asarray(bf16).dtype.kind is 'V', which would
        # silently skip the whole bfloat16 half of the sweep
        if jnp.issubdtype(jnp.asarray(r).dtype, jnp.floating):
            a = onp.asarray(jnp.asarray(r).astype(jnp.float32))
            assert onp.isfinite(a).all(), \
                f"{name}[{dt}]: non-finite output"


def _sweep_dtypes(name):
    if name in FIXED_DTYPE or name in F32_ONLY:
        return [jnp.float32, jnp.float32]
    return [jnp.float32, jnp.bfloat16]


ALL_OPS = registry.list_ops()


@pytest.mark.parametrize("name", ALL_OPS)
def test_forward_smoke(name):
    if name in SKIP:
        pytest.skip(SKIP[name])
    o = registry.OPS[name]
    for dt in _sweep_dtypes(name):
        args, kwargs = build_inputs(o, dt)
        res = o.fn(*args, **kwargs)
        jax.block_until_ready(res)
        _assert_finite(res, name, dt)


@pytest.mark.parametrize(
    "name", [n for n in ALL_OPS if registry.OPS[n].differentiable])
def test_vjp(name):
    if name in SKIP:
        pytest.skip(SKIP[name])
    o = registry.OPS[name]
    args, kwargs = build_inputs(o, jnp.float32)
    flat = list(args)
    diff_idx = [i for i, a in enumerate(flat)
                if hasattr(a, "dtype") and
                jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)]
    if not diff_idx:
        pytest.skip(f"{name}: no float inputs to differentiate")

    def scalar_loss(*diff_args):
        full = list(flat)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        res = o.fn(*full, **kwargs)
        outs = [r for r in _flat_outputs(res)
                if jnp.issubdtype(jnp.asarray(r).dtype, jnp.floating)]
        if not outs:
            return jnp.float32(0.0)
        return sum(jnp.sum(r.astype(jnp.float32)) for r in outs)

    grads = jax.grad(scalar_loss, argnums=tuple(range(len(diff_idx))))(
        *[flat[i] for i in diff_idx])
    for g in grads:
        assert onp.isfinite(onp.asarray(g)).all(), \
            f"{name}: non-finite gradient"


def test_exemption_tables_are_live():
    """SKIP/SPECIALS/FIXED_DTYPE/F32_ONLY entries must name real ops —
    stale entries fail here instead of silently shrinking coverage."""
    known = set(ALL_OPS)
    for table, tname in ((SKIP, "SKIP"), (SPECIALS, "SPECIALS"),
                         (FIXED_DTYPE, "FIXED_DTYPE"),
                         (F32_ONLY, "F32_ONLY")):
        stale = set(table) - known
        assert not stale, f"{tname} names unknown ops: {sorted(stale)}"


def test_sweep_covers_registry():
    """The sweep runs every registered op minus the documented SKIPs —
    and the SKIP list stays short, so coverage cannot quietly erode."""
    assert len(ALL_OPS) >= 370
    assert set(SKIP) <= set(ALL_OPS)
    assert len(SKIP) <= 5, "document the op in SPECIALS instead of SKIP"


# ===================================================================== #
# VALUE-LEVEL checks (VERDICT r4 item 3): finiteness is smoke, not
# correctness.  Two layers, mirroring the reference's check_consistency:
#
# 1. test_forward_values — f32 forward outputs compared against a
#    NumPy/SciPy reference computation.  References come from three
#    sources: the op name resolving in numpy (139 ops), scipy.special,
#    or the explicit VALUE_REF table.  Ops with no derivable reference
#    are listed in VALUE_EXEMPT with the reason and the place their
#    values ARE asserted.
# 2. test_dtype_consistency — the same op run on f32 inputs and on the
#    bf16-rounded inputs must agree at bf16-scaled tolerance (the
#    reference's cross-dtype check_consistency).
# ===================================================================== #
import scipy.special as _sps
import scipy.linalg as _spl

_NPF = onp.float32


def _np(x):
    a = onp.asarray(x)
    return a.astype(_NPF) if a.dtype == onp.float64 else a


def _sigmoid(x):
    return 1.0 / (1.0 + onp.exp(-x))


def _np_seq_mask(data, length=None, *, use_sequence_length=False,
                 value=0.0, axis=0):
    out = onp.array(data, copy=True)
    if not use_sequence_length or length is None:
        return out
    T = out.shape[axis]
    sw = onp.moveaxis(out, axis, 0)
    for b, L in enumerate(onp.asarray(length).astype(int)):
        sw[L:, b] = value
    return onp.moveaxis(sw, 0, axis)


VALUE_REF = {
    # ---- broadcast_* = plain numpy broadcasting ----------------------- #
    "broadcast_add": onp.add, "broadcast_sub": onp.subtract,
    "broadcast_mul": onp.multiply, "broadcast_div": onp.divide,
    "broadcast_mod": onp.mod, "broadcast_power": onp.power,
    "broadcast_maximum": onp.maximum, "broadcast_minimum": onp.minimum,
    "broadcast_hypot": onp.hypot,
    "broadcast_equal": onp.equal, "broadcast_not_equal": onp.not_equal,
    "broadcast_greater": onp.greater,
    "broadcast_greater_equal": onp.greater_equal,
    "broadcast_lesser": onp.less,
    "broadcast_lesser_equal": onp.less_equal,
    "broadcast_logical_and": onp.logical_and,
    "broadcast_logical_or": onp.logical_or,
    "broadcast_logical_xor": onp.logical_xor,
    "broadcast_like": lambda a, b: onp.broadcast_to(a, b.shape),
    "broadcast_to": lambda a, *, shape: onp.broadcast_to(a, shape),
    "broadcast_axis": lambda a, *, axis, size: onp.broadcast_to(
        a, tuple(size if i == axis else s
                 for i, s in enumerate(a.shape))),
    "broadcast_arrays": lambda *a: list(onp.broadcast_arrays(*a)),

    # ---- activations / simple elementwise ----------------------------- #
    "relu": lambda x: onp.maximum(x, 0),
    "relu6": lambda x: onp.clip(x, 0, 6),
    "sigmoid": _sigmoid,
    "log_sigmoid": lambda x: onp.log(_sigmoid(x)),
    "hard_sigmoid": lambda x, *, alpha=0.2, beta=0.5: onp.clip(
        alpha * x + beta, 0, 1),
    "hard_swish": lambda x: x * onp.clip(x + 3, 0, 6) / 6,
    "softsign": lambda x: x / (1 + onp.abs(x)),
    "softrelu": lambda x: onp.log1p(onp.exp(x)),
    "selu": lambda x: 1.0507009873554805 * onp.where(
        x > 0, x, 1.6732632423543772 * (onp.exp(x) - 1)),
    "elu": lambda x, *, alpha=1.0: onp.where(
        x > 0, x, alpha * (onp.exp(x) - 1)),
    "gelu": lambda x: 0.5 * x * (1 + _sps.erf(x / onp.sqrt(2))),
    "mish": lambda x: x * onp.tanh(onp.log1p(onp.exp(x))),
    "prelu": lambda x, g: onp.where(x > 0, x, g * x),
    "rsqrt": lambda x: 1.0 / onp.sqrt(x),
    "rcbrt": lambda x: 1.0 / onp.cbrt(x),
    "log1mexp": lambda x: onp.log1p(-onp.exp(x)),
    "logit": _sps.logit,
    "smooth_l1": lambda x, *, scalar=1.0: onp.where(
        onp.abs(x) < 1.0 / scalar ** 2,
        0.5 * (x * scalar) ** 2, onp.abs(x) - 0.5 / scalar ** 2),
    "squared_difference": lambda a, b: (a - b) ** 2,
    "quadratic": lambda x, *, a=0.0, b=0.0, c=0.0: a * x * x + b * x + c,
    "_contrib_div_sqrt_dim": lambda x: x / onp.sqrt(x.shape[-1]),
    "_contrib_gradientmultiplier": lambda x, *, scalar=1.0: x,
    "BlockGrad": lambda x: x,
    "MakeLoss": lambda x: x,
    "shape_array": lambda x: onp.asarray(x.shape, onp.int64),
    "size_array": lambda x: onp.asarray([x.size], onp.int64),
    "polyval_op": lambda p, x: onp.polyval(onp.asarray(p), x),
    "trapz_op": lambda y, *, dx=1.0: onp.trapz(y, dx=dx, axis=-1),
    "inner_op": lambda a, b: onp.inner(a, b),
    "vdot_op": lambda a, b: onp.vdot(a, b),
    "cross_op": lambda a, b: onp.cross(a, b),
    "unique_op": lambda x: onp.unique(x),
    "bincount_op": lambda x, *, length: onp.bincount(
        x.ravel(), minlength=length)[:length],
    "interp_op": lambda x, xp, fp: onp.interp(x, xp, fp),
    "searchsorted": lambda a, v, *, side="left": onp.searchsorted(
        a, v, side=side),

    # ---- reductions / norms ------------------------------------------ #
    "norm": lambda x, *, ord=2, axis=None, keepdims=False:
        onp.linalg.norm(x.ravel() if axis is None else x,
                        ord=ord, axis=axis, keepdims=keepdims),
    "moments": lambda x, *, axes=None, keepdims=False: [
        onp.mean(x, axis=tuple(axes) if axes else None, keepdims=keepdims),
        onp.var(x, axis=tuple(axes) if axes else None, keepdims=keepdims)],
    "L2Normalization": lambda x, *, mode="instance", eps=1e-10:
        x / onp.sqrt((x.reshape(x.shape[0], -1) ** 2)
                     .sum(1).reshape((-1,) + (1,) * (x.ndim - 1)) + eps),
    "argmax_channel": lambda x: onp.argmax(x, 1).astype(_NPF),

    # ---- softmax family ----------------------------------------------- #
    "softmin": lambda x, *, axis=-1: _sps.softmax(-_np(x), axis=axis),
    "SoftmaxActivation": lambda x, *, mode="instance": _sps.softmax(
        _np(x), axis=1 if mode == "channel" else -1),
    "masked_softmax": lambda x, mask=None, *, axis=-1: _sps.softmax(
        onp.where(onp.asarray(mask, bool), _np(x), -1e30)
        if mask is not None else _np(x), axis=axis),
    "masked_log_softmax": lambda x, mask=None, *, axis=-1:
        onp.log(_sps.softmax(
            onp.where(onp.asarray(mask, bool), _np(x), -1e30)
            if mask is not None else _np(x), axis=axis) + 1e-30),

    # ---- manipulation -------------------------------------------------- #
    "slice": lambda x, *, begin, end, step=None: x[tuple(
        __import__("builtins").slice(b, e, s) for b, e, s in zip(
            begin, end, step or (None,) * len(begin)))],
    "slice_axis": lambda x, *, axis, begin, end:
        onp.take(x, onp.arange(begin, end if end is not None
                               else x.shape[axis]), axis=axis),
    "slice_like": lambda a, b, *, axes=None: a[tuple(
        __import__("builtins").slice(0, b.shape[i]
                                     if (axes is None or i in axes)
                                     else None)
        for i in range(a.ndim))],
    "flatten": lambda x: x.reshape(x.shape[0], -1),
    "reshape": lambda x, *, shape: x.reshape(shape),
    "reshape_like": lambda a, b: a.reshape(b.shape),
    "resize_op": lambda x, *, new_shape: onp.resize(x, new_shape),
    "one_hot": lambda i, *, depth, on_value=1.0, off_value=0.0:
        onp.where(onp.eye(depth)[i.astype(int)] > 0, on_value, off_value),
    "pick": lambda x, i, *, axis=-1, keepdims=False:
        onp.take_along_axis(
            x, onp.expand_dims(i.astype(int), 1), axis=1).squeeze(1),
    "choose_element_0index": lambda x, i:
        x[onp.arange(x.shape[0]), i.astype(int)],
    "batch_take": lambda x, i: x[onp.arange(x.shape[0]), i.astype(int)],
    "fill_element_0index": lambda x, v, i: _fill0(x, v, i),
    "gather_nd": lambda d, i: d[tuple(i.astype(int))],
    "scatter_nd": lambda d, i, *, shape: _scatter_nd(d, i, shape),
    "take": lambda a, i, *, axis=0, mode="clip": onp.take(
        a, onp.clip(i.astype(int), 0, a.shape[axis] - 1), axis=axis),
    "tile": lambda x, *, reps: onp.tile(x, reps),
    "flip": lambda x, *, axis: onp.flip(x, axis),
    "depth_to_space": lambda x, *, block_size: _d2s(x, block_size),
    "space_to_depth": lambda x, *, block_size: _s2d(x, block_size),
    "_onnx_expand": lambda x, *, shape: x * onp.ones(shape, x.dtype),
    "sequence_mask": _np_seq_mask,
    "sequence_reverse": lambda data, length=None, *,
        use_sequence_length=False, axis=0: _seq_rev(
            data, length, use_sequence_length, axis),
    "sequence_last": lambda data, length=None, *,
        use_sequence_length=False, axis=0: _seq_last(
            data, length, use_sequence_length, axis),
    "index_array": lambda x, *, axes=None: _index_array(x, axes),
    "arange_like": lambda x, *, start=0.0, step=1.0, axis=None:
        (start + step * onp.arange(x.size)).reshape(x.shape).astype(_NPF)
        if axis is None else
        (start + step * onp.arange(x.shape[axis])).astype(_NPF),
    "cast": lambda x, *, dtype: x.astype(dtype),
    "amp_cast": lambda x, *, dtype="float32": x.astype(dtype),
    "amp_multicast": lambda *a, num_outputs: list(a),
    "reset_arrays": lambda *a: [onp.zeros_like(x) for x in a],
    "add_n": lambda *a: sum(a),
    "rnn_param_concat": lambda *a: onp.concatenate(
        [x.ravel() for x in a]),
    "khatri_rao": lambda a, b: onp.vstack(
        [onp.kron(a[:, k], b[:, k]) for k in range(a.shape[1])]).T,

    # ---- linalg -------------------------------------------------------- #
    "linalg_det": lambda a: onp.linalg.det(a),
    "linalg_slogdet": lambda a: list(onp.linalg.slogdet(a)),
    "linalg_inverse": lambda a: onp.linalg.inv(a),
    "linalg_potrf": lambda a: onp.linalg.cholesky(a),
    "linalg_syevd": lambda a: [onp.linalg.eigh(a)[1].T,
                               onp.linalg.eigh(a)[0]],
    "linalg_gemm": lambda a, b, c, *, alpha=1.0, beta=1.0,
        transpose_a=False, transpose_b=False:
        alpha * (a.T if transpose_a else a) @ (b.T if transpose_b else b)
        + beta * c,
    "linalg_gemm2": lambda a, b, *, alpha=1.0, transpose_a=False,
        transpose_b=False:
        alpha * (a.T if transpose_a else a) @ (b.T if transpose_b else b),
    "linalg_syrk": lambda a, *, alpha=1.0, transpose=False:
        alpha * (a.T @ a if transpose else a @ a.T),
    "linalg_trmm": lambda a, b, *, transpose=False, rightside=False,
        alpha=1.0: alpha * ((b @ (a.T if transpose else a))
                            if rightside else
                            ((a.T if transpose else a) @ b)),
    "linalg_trsm": lambda a, b, *, transpose=False, rightside=False,
        alpha=1.0: alpha * (_spl.solve_triangular(
            a, b.T if rightside else b, trans=1 if transpose else 0,
            lower=True).T if rightside else _spl.solve_triangular(
            a, b, trans=1 if transpose else 0, lower=True)),
    "linalg_sumlogdiag": lambda a: onp.log(onp.diag(a)).sum(),
    "linalg_extractdiag": lambda a, *, offset=0: onp.diag(a, k=offset),
    "linalg_extracttrian": lambda a, *, offset=0, lower=True:
        _extracttrian(a, offset, lower),
    "linalg_maketrian": lambda a, *, offset=0, lower=True:
        _maketrian(a, offset, lower),

    # ---- matmul family ------------------------------------------------- #
    "batch_dot": lambda a, b, *, transpose_a=False, transpose_b=False:
        onp.matmul(a.transpose(0, 2, 1) if transpose_a else a,
                   b.transpose(0, 2, 1) if transpose_b else b),
    "Embedding": lambda i, w, *, input_dim=0, output_dim=0:
        w[i.astype(int)],

    # ---- regression / loss heads -------------------------------------- #
    "LinearRegressionOutput": lambda d, l: d,
    "MAERegressionOutput": lambda d, l: d,
    "LogisticRegressionOutput": lambda d, l: _sigmoid(d),
    "SoftmaxOutput": lambda d, l, *, grad_scale=1.0: _sps.softmax(
        _np(d), axis=-1),
    "softmax_cross_entropy": lambda d, l: -onp.log(_sps.softmax(
        _np(d), -1)[onp.arange(d.shape[0]), l.astype(int)] + 1e-30).sum(),
    "IdentityAttachKLSparseReg": lambda x: x,

    # ---- im2col/col2im ------------------------------------------------- #
    "im2col": lambda x, *, kernel, stride=(1, 1), dilate=(1, 1),
        pad=(0, 0): _im2col(x, kernel, stride, dilate, pad),

    # ---- optimizer updates with simple closed forms -------------------- #
    "sgd_update": lambda w, g, *, lr, wd=0.0, rescale_grad=1.0,
        clip_gradient=-1.0, lazy_update=True:
        w - lr * (_clipg(rescale_grad * g, clip_gradient) + wd * w),
    "signsgd_update": lambda w, g, *, lr, wd=0.0, rescale_grad=1.0,
        clip_gradient=-1.0:
        w - lr * (onp.sign(_clipg(rescale_grad * g, clip_gradient))
                  + wd * w),
}


def _clipg(g, c):
    return onp.clip(g, -c, c) if c is not None and c > 0 else g


def _fill0(x, v, i):
    out = onp.array(x, copy=True)
    out[onp.arange(x.shape[0]), i.astype(int)] = v
    return out


def _scatter_nd(d, i, shape):
    out = onp.zeros(shape, d.dtype)
    onp.add.at(out, tuple(i.astype(int)), d)
    return out


def _d2s(x, bs):
    n, c, h, w = x.shape
    return x.reshape(n, bs, bs, c // bs ** 2, h, w).transpose(
        0, 3, 4, 1, 5, 2).reshape(n, c // bs ** 2, h * bs, w * bs)


def _s2d(x, bs):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // bs, bs, w // bs, bs).transpose(
        0, 3, 5, 1, 2, 4).reshape(n, c * bs ** 2, h // bs, w // bs)


def _seq_rev(data, length, use_len, axis):
    out = onp.array(data, copy=True)
    sw = onp.moveaxis(out, axis, 0)
    T = sw.shape[0]
    if not use_len or length is None:
        res = sw[::-1]
    else:
        res = onp.array(sw, copy=True)
        for b, L in enumerate(onp.asarray(length).astype(int)):
            res[:L, b] = sw[:L, b][::-1]
    return onp.moveaxis(res, 0, axis)


def _seq_last(data, length, use_len, axis):
    sw = onp.moveaxis(onp.asarray(data), axis, 0)
    if not use_len or length is None:
        return sw[-1]
    idx = onp.asarray(length).astype(int) - 1
    return sw[idx, onp.arange(sw.shape[1])]


def _index_array(x, axes):
    axes = tuple(range(x.ndim)) if axes is None else tuple(axes)
    grids = onp.indices(x.shape)
    return onp.stack([grids[a] for a in axes], -1).astype(onp.int64)


def _extracttrian(a, offset, lower):
    mask = onp.tril(onp.ones_like(a), k=offset) if lower else \
        onp.triu(onp.ones_like(a), k=offset)
    idx = onp.nonzero(mask)
    return a[idx]


def _maketrian(a, offset, lower):
    # inverse of extracttrian for the swept (2, 6) input: 6 = 3*(3+1)/2
    k = a.shape[-1]
    n = int((onp.sqrt(8 * k + 1) - 1) / 2)
    out = onp.zeros(a.shape[:-1] + (n, n), a.dtype)
    for b in range(a.shape[0]):
        m = onp.zeros((n, n), a.dtype)
        m[onp.tril_indices(n, offset)] = a[b]
        out[b] = m if lower else m.T
    return out


def _im2col(x, kernel, stride, dilate, pad):
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw_ = stride
    xp = onp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    oh = (xp.shape[2] - (kh - 1) * dilate[0] - 1) // sh + 1
    ow = (xp.shape[3] - (kw - 1) * dilate[1] - 1) // sw_ + 1
    # layout: (c, kh, kw) fastest over kw — build directly
    cols = onp.stack([
        xp[:, :, i * dilate[0]:i * dilate[0] + oh * sh:sh,
           j * dilate[1]:j * dilate[1] + ow * sw_:sw_].reshape(n, c, -1)
        for i in range(kh) for j in range(kw)], axis=2)
    return cols.reshape(n, c * kh * kw, oh * ow)


def _ln_ref(x, g, b, *, axis=-1, eps=1e-5, output_mean_var=False):
    mu = x.mean(axis, keepdims=True)
    var = x.var(axis, keepdims=True)
    return (x - mu) / onp.sqrt(var + eps) * g + b


VALUE_REF.update({
    "Activation": lambda x, *, act_type="relu": {
        "relu": lambda v: onp.maximum(v, 0),
        "sigmoid": _sigmoid,
        "tanh": onp.tanh,
        "softrelu": lambda v: onp.log1p(onp.exp(v)),
        "softsign": lambda v: v / (1 + onp.abs(v)),
    }[act_type](x),
    "LeakyReLU": lambda x, g=None, *, act_type="leaky", slope=0.25,
        lower_bound=0.125, upper_bound=0.334: {
        "leaky": lambda v: onp.where(v > 0, v, slope * v),
        "elu": lambda v: onp.where(v > 0, v, slope * (onp.exp(v) - 1)),
        "prelu": lambda v: onp.where(v > 0, v, (g if g is not None
                                                else slope) * v),
        "gelu": lambda v: 0.5 * v * (1 + _sps.erf(v / onp.sqrt(2))),
        "selu": lambda v: 1.0507009873554805 * onp.where(
            v > 0, v, 1.6732632423543772 * (onp.exp(v) - 1)),
    }[act_type](x),
    "LayerNorm": _ln_ref,
    "RMSNorm": lambda x, g, *, axis=-1, eps=1e-6:
        x / onp.sqrt((x.astype(_NPF) ** 2).mean(axis, keepdims=True)
                     + eps) * g,
    "InstanceNorm": lambda x, g, b, *, eps=1e-3:
        (x - x.mean((2, 3), keepdims=True)) /
        onp.sqrt(x.var((2, 3), keepdims=True) + eps)
        * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1),
    "GroupNorm": lambda x, g, b, *, num_groups=1, eps=1e-5:
        _gn_ref(x, g, b, num_groups, eps),
    "topk": lambda x, *, axis=-1, k=1, ret_typ="indices",
        is_ascend=False, dtype="float32": _topk_ref(
            x, axis, k, ret_typ, is_ascend, dtype),
    "split": lambda x, *, num_outputs, axis=1, squeeze_axis=False:
        [s.squeeze(axis) if squeeze_axis else s
         for s in onp.split(x, num_outputs, axis)],
    "allclose_op": lambda a, b, *, rtol=1e-5, atol=1e-8,
        equal_nan=False: onp.asarray(
            onp.allclose(a, b, rtol, atol, equal_nan), onp.float32),
    "clip_global_norm": lambda *arrays, max_norm, scale=1.0:
        [a * min(1.0, max_norm / (onp.sqrt(sum(
            (x.astype(_NPF) ** 2).sum() for x in arrays)) + 1e-12))
         * scale for a in arrays],
    "_image_flip_left_right": lambda x: x[..., ::-1, :],
    "quantile": lambda a, *, q, axis=None, keepdims=False,
        interpolation="linear": onp.quantile(
            a, q, axis=axis, keepdims=keepdims),
    "histogram_op": lambda x, *, bin_cnt=10, range=None: list(
        onp.histogram(onp.asarray(x).ravel(), bins=int(bin_cnt),
                      range=range if range is not None else (0.0, 1.0))),
    "_image_flip_top_bottom": lambda x: x[..., ::-1, :, :]
        if x.ndim == 4 else x[::-1],
    "_image_normalize": lambda x, *, mean=(0.0,), std=(1.0,):
        (x - onp.asarray(mean).reshape(-1, 1, 1)) /
        onp.asarray(std).reshape(-1, 1, 1),
    "_image_to_tensor": lambda x: (x.transpose(2, 0, 1)
                                   if x.ndim == 3 else
                                   x.transpose(0, 3, 1, 2)) / 255.0,
    "_sparse_segment_dot": lambda data, gi, si, rhs, *, num_segments:
        _seg_dot_ref(data, gi, si, rhs, num_segments),
    "_sparse_rowsparse_dot": lambda v, i, rhs, *, num_rows:
        _rs_dot_ref(v, i, rhs, num_rows),
    "_contrib_index_add": lambda x, idx, val: _idx_binop(x, idx, val, True),
    "_contrib_index_copy": lambda x, idx, val: _idx_binop(x, idx, val,
                                                          False),
    "sgd_mom_update": lambda w, g, m, *, lr, momentum=0.0, wd=0.0,
        rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True:
        _sgd_mom_ref(w, g, m, lr, momentum, wd, rescale_grad,
                     clip_gradient),
    "adam_update": lambda w, g, m, v, *, lr, beta1=0.9, beta2=0.999,
        epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
        lazy_update=True: _adam_ref(w, g, m, v, lr, beta1, beta2,
                                    epsilon, wd, rescale_grad,
                                    clip_gradient),
    # ---- variadic stacking (numpy wants one sequence argument) -------- #
    "concat": lambda *a, dim=1: onp.concatenate(a, axis=dim),
    "stack": lambda *a, axis=0: onp.stack(a, axis=axis),
    "dstack": lambda *a: onp.dstack(a),
    "hstack": lambda *a: onp.hstack(a),
    "vstack": lambda *a: onp.vstack(a),
    "column_stack": lambda *a: onp.column_stack(a),
    "meshgrid": lambda *a, indexing="xy": list(
        onp.meshgrid(*a, indexing=indexing)),
    # ---- axis-default / signature divergences from numpy/scipy -------- #
    "softmax": lambda x, length=None, *, axis=-1, temperature=None,
        use_length=False: _sps.softmax(
            _np(x) / (temperature or 1.0), axis=axis),
    "log_softmax": lambda x, *, axis=-1, temperature=None:
        _sps.log_softmax(_np(x) / (temperature or 1.0), axis=axis),
    "identity": lambda x: x,
    "full_like": lambda x, *, fill_value=0.0: onp.full_like(
        x, fill_value),
    "swapaxes": lambda x, *, dim1=0, dim2=1: onp.swapaxes(x, dim1, dim2),
    "pad": lambda x, *, mode="constant", pad_width=(), constant_value=0:
        onp.pad(x, onp.asarray(pad_width).reshape(-1, 2),
                mode={"constant": "constant", "edge": "edge",
                      "reflect": "reflect"}[mode],
                **({"constant_values": constant_value}
                   if mode == "constant" else {})),
    "unravel_index": lambda i, *, shape: onp.stack(
        onp.unravel_index(i.astype(int), shape)),
    "ravel_multi_index": lambda i, *, shape: onp.ravel_multi_index(
        tuple(i.astype(int)), dims=shape),
    "gcd": lambda a, b: onp.gcd(a.astype(onp.int64), b.astype(onp.int64)),
    "lcm": lambda a, b: onp.lcm(a.astype(onp.int64), b.astype(onp.int64)),
    "ldexp": lambda a, b: onp.ldexp(a, b.astype(int)),
    "FullyConnected": lambda x, w, b=None, *, num_hidden=0,
        no_bias=False, flatten=True:
        (x.reshape(x.shape[0], -1) if flatten else x) @ w.T
        + (0 if (b is None or no_bias) else b),
    "_image_crop": lambda img, **kw: img[
        kw.get("y", 0):kw.get("y", 0) + kw.get("height", 1),
        kw.get("x", 0):kw.get("x", 0) + kw.get("width", 1), :],
    "linalg_potri": lambda a: onp.linalg.inv(onp.tril(a) @ onp.tril(a).T),
    "linalg_makediag": lambda a, *, offset=0: onp.stack(
        [onp.diag(v, k=offset) for v in a]) if a.ndim == 2 else
        onp.diag(a, k=offset),
    "_sparse_rowsparse_dot_t": lambda v, i, rhs, *, num_cols:
        v.T.astype(_NPF) @ rhs[onp.asarray(i).astype(int)],
    "all_finite": lambda x, *, init_output=True: onp.asarray(
        [onp.isfinite(x).all()], onp.float32),
    "multi_all_finite": lambda *a, **kw: onp.asarray(
        [all(onp.isfinite(x).all() for x in a)], onp.float32),
})


def _sgd_mom_ref(w, g, m, lr, momentum, wd, rg, cg):
    m2 = momentum * m - lr * (_clipg(rg * g, cg) + wd * w)
    return [w + m2, m2]


def _gn_ref(x, g, b, ng, eps):
    n, c, h, w = x.shape
    xr = x.reshape(n, ng, c // ng, h, w)
    mu = xr.mean((2, 3, 4), keepdims=True)
    var = xr.var((2, 3, 4), keepdims=True)
    xn = ((xr - mu) / onp.sqrt(var + eps)).reshape(n, c, h, w)
    return xn * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)


def _topk_ref(x, axis, k, ret_typ, is_ascend, dtype):
    key = x if is_ascend else -x
    idx = onp.argsort(key, axis=axis, kind="stable")
    idx = onp.take(idx, onp.arange(k), axis=axis)
    vals = onp.take_along_axis(x, idx, axis=axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return [vals, idx.astype(dtype)]
    return idx.astype(dtype)


def _seg_dot_ref(data, gi, si, rhs, num_segments):
    out = onp.zeros((num_segments, rhs.shape[1]), _NPF)
    for j in range(data.shape[0]):
        out[int(si[j])] += data[j] * rhs[int(gi[j])]
    return out


def _rs_dot_ref(v, i, rhs, num_rows):
    out = onp.zeros((num_rows, rhs.shape[1]), _NPF)
    out[i.astype(int)] = v @ rhs
    return out


def _idx_binop(x, idx, val, add):
    out = onp.array(x, copy=True)
    if add:
        onp.add.at(out, idx.astype(int), val)
    else:
        out[idx.astype(int)] = val
    return out


def _adam_ref(w, g, m, v, lr, b1, b2, eps, wd, rg, cg):
    gr = _clipg(rg * g, cg) + wd * w
    m2 = b1 * m + (1 - b1) * gr
    v2 = b2 * v + (1 - b2) * gr * gr
    return [w - lr * m2 / (onp.sqrt(v2) + eps), m2, v2]


# ops with no derivable closed-form numpy reference; each entry names
# where the op's VALUES are asserted instead
VALUE_EXEMPT = {
    # conv/pool families: golden-tested against scipy/torch-free
    # references in their family tests
    "Convolution": "golden vs explicit loops: tests/test_operator.py",
    "Deconvolution": "golden: tests/test_operator.py",
    "Pooling": "golden: tests/test_operator.py",
    "Correlation": "golden: tests/test_vision_ops.py",
    "col2im": "inverse-of-im2col asserted in tests/test_extended_ops.py",
    "LRN": "cross-channel normalization golden in tests/test_legacy_ops.py",
    "UpSampling": "golden: tests/test_legacy_ops.py",
    # attention / rnn: parity vs naive implementations
    "flash_attention": "parity vs naive attention: tests/test_attention.py",
    "rope": "rotation identities: tests/test_llama.py",
    "fused_rnn": "parity vs unrolled cells: tests/test_rnn.py",
    "_contrib_interleaved_matmul_selfatt_qk":
        "parity vs explicit qk matmul: tests/test_models.py",
    "_contrib_interleaved_matmul_selfatt_valatt":
        "parity vs explicit attention: tests/test_models.py",
    # vision contrib: behavioral tests in tests/test_vision_ops.py
    "BilinearSampler": "grid-sampling goldens: tests/test_vision_ops.py",
    "GridGenerator": "affine grid goldens: tests/test_vision_ops.py",
    "SpatialTransformer": "goldens: tests/test_vision_ops.py",
    "ROIPooling": "goldens: tests/test_vision_ops.py",
    "_contrib_ROIAlign": "goldens: tests/test_vision_ops.py",
    "_contrib_BilinearResize2D": "goldens: tests/test_vision_ops.py",
    "_contrib_DeformableConvolution":
        "reduces-to-Convolution-at-zero-offset: tests/test_vision_ops.py",
    "_contrib_MultiBoxPrior": "anchor goldens: tests/test_vision_ops.py",
    "_contrib_MultiBoxDetection": "goldens: tests/test_vision_ops.py",
    "_contrib_MultiBoxTarget": "goldens: tests/test_vision_ops.py",
    "_contrib_Proposal": "rpn goldens: tests/test_vision_ops.py",
    "_contrib_box_nms": "nms goldens: tests/test_vision_ops.py",
    "im2col": "patch-extraction goldens: tests/test_extended_ops.py",
    # losses with nontrivial dynamic programming
    "CTCLoss": "vs hand-computed alignments: tests/test_op_conformance "
               "vjp + tests/test_gluon.py loss goldens",
    "SVMOutput": "margin semantics: tests/test_legacy_ops.py",
    # quantization: int8 contracts tested end-to-end
    "_contrib_quantize_v2": "roundtrip: tests/test_quantization_onnx_custom.py",
    "_contrib_dequantize": "roundtrip: tests/test_quantization_onnx_custom.py",
    "_contrib_requantize": "roundtrip: tests/test_quantization_onnx_custom.py",
    "quantized_conv_int8": "vs f32 conv: tests/test_quantization_onnx_custom.py",
    "quantized_matmul_int8": "vs f32 matmul: tests/test_quantization_onnx_custom.py",
    "quantized_act_int8": "vs f32 act: tests/test_quantization_onnx_custom.py",
    "quantized_pooling_int8": "vs f32 pool: tests/test_quantization_onnx_custom.py",
    # random draws have no deterministic reference; distribution moments
    # are asserted in tests/test_numpy.py / test_samplers_image_ops.py
    "_random_exponential": "moment tests", "_random_gamma": "moment tests",
    "_random_generalized_negative_binomial": "moment tests",
    "_random_negative_binomial": "moment tests",
    "_random_normal": "moment tests", "_random_poisson": "moment tests",
    "_random_randint": "support tests", "_random_uniform": "support tests",
    "sample_exponential": "moment tests", "sample_gamma": "moment tests",
    "sample_generalized_negative_binomial": "moment tests",
    "sample_multinomial": "support tests",
    "sample_negative_binomial": "moment tests",
    "sample_normal": "moment tests", "sample_poisson": "moment tests",
    "sample_uniform": "support tests",
    "_DropoutImpl": "mask statistics: tests/test_attention.py dropout",
    "_BatchNormStats": "vs jnp closed form: tests/test_parallel.py BN",
    "boolean_mask": "compaction semantics: tests/test_extended_ops.py",
    "_image_random_brightness": "random draw: tests/test_samplers_image_ops.py",
    "_image_random_contrast": "random draw: tests/test_samplers_image_ops.py",
    "_image_random_saturation": "random draw: tests/test_samplers_image_ops.py",
    "_image_random_flip_left_right": "random draw: tests/test_samplers_image_ops.py",
    "_image_random_flip_top_bottom": "random draw: tests/test_samplers_image_ops.py",
    "_image_resize": "interp goldens: tests/test_samplers_image_ops.py",
    "_sparse_softmax_ce": "fused sparse-label CE vs dense CE: tests/test_models.py",
    "fft": "packed real/imag layout: tests/test_legacy_ops.py",
    "ifft": "packed real/imag layout: tests/test_legacy_ops.py",
    "ring_attention": "parity-asserted in __graft_entry__ dryrun",
    # optimizer update ops beyond the closed forms above: each is the
    # registered kernel behind an Optimizer whose trajectory is asserted
    # in tests/test_optimizer_metric.py
    "adadelta_update": "tests/test_optimizer_metric.py",
    "adagrad_update": "tests/test_optimizer_metric.py",
    "adamw_update": "tests/test_optimizer_metric.py",
    "ftml_update": "tests/test_optimizer_metric.py",
    "ftrl_update": "tests/test_optimizer_metric.py",
    "group_adagrad_update": "tests/test_optimizer_metric.py",
    "lamb_update_phase1": "tests/test_optimizer_metric.py",
    "lamb_update_phase2": "tests/test_optimizer_metric.py",
    "lans_update": "tests/test_optimizer_metric.py",
    "mp_adamw_update": "tests/test_optimizer_metric.py",
    "mp_nag_mom_update": "tests/test_optimizer_metric.py",
    "mp_sgd_mom_update": "tests/test_optimizer_metric.py",
    "mp_sgd_update": "tests/test_optimizer_metric.py",
    "multi_adamw_update": "tests/test_optimizer_metric.py",
    "multi_lamb_update": "tests/test_optimizer_metric.py",
    "multi_mp_sgd_mom_update": "tests/test_optimizer_metric.py",
    "multi_mp_sgd_update": "tests/test_optimizer_metric.py",
    "multi_sgd_mom_update": "tests/test_optimizer_metric.py",
    "multi_sgd_update": "tests/test_optimizer_metric.py",
    "nag_mom_update": "tests/test_optimizer_metric.py",
    "preloaded_multi_sgd_mom_update": "tests/test_optimizer_metric.py",
    "preloaded_multi_sgd_update": "tests/test_optimizer_metric.py",
    "rmsprop_update": "tests/test_optimizer_metric.py",
    "rmspropalex_update": "tests/test_optimizer_metric.py",
    "signum_update": "tests/test_optimizer_metric.py",
    "linalg_gelqf": "QR/LQ reconstruction identity: tests/test_linalg_ops.py",
}


def _resolve_ref(name):
    if name in VALUE_REF:
        return VALUE_REF[name]
    f = getattr(onp, name, None)
    if f is not None and callable(f):
        return f
    f = getattr(_sps, name, None)
    if f is not None and callable(f):
        return f
    return None


VALUE_CHECKED = [n for n in ALL_OPS
                 if n not in VALUE_EXEMPT and n not in SKIP
                 and _resolve_ref(n) is not None]
_UNCOVERED = [n for n in ALL_OPS
              if n not in VALUE_EXEMPT and n not in SKIP
              and _resolve_ref(n) is None]


@pytest.mark.parametrize("name", VALUE_CHECKED)
def test_forward_values(name):
    """f32 forward outputs == the independent NumPy/SciPy computation
    (the upgrade from finiteness smoke to value correctness)."""
    o = registry.OPS[name]
    args, kwargs = build_inputs(o, jnp.float32)
    res = _flat_outputs(o.fn(*args, **kwargs))
    np_args = [onp.asarray(a) if hasattr(a, "dtype") else a for a in args]
    expected = _flat_outputs(_resolve_ref(name)(*np_args, **kwargs))
    assert len(res) == len(expected), \
        f"{name}: {len(res)} outputs vs reference {len(expected)}"
    for got, exp in zip(res, expected):
        g = onp.asarray(got)
        e = onp.asarray(exp)
        assert g.shape == tuple(e.shape), \
            f"{name}: shape {g.shape} vs reference {e.shape}"
        onp.testing.assert_allclose(
            g.astype(_NPF), e.astype(_NPF), rtol=2e-3, atol=1e-4,
            err_msg=f"{name}: forward values diverge from numpy reference")


# dtype consistency needs deterministic ops; PRNG-consuming ops are the
# only exclusion beyond the fixed-dtype tables
_CONSISTENCY_EXEMPT = {n for n in ALL_OPS
                       if n.startswith(("_random_", "sample_",
                                        "_image_random_"))} | {
    "_DropoutImpl",  # mask threshold moves under bf16 rounding
    # bilinear sampling positions come FROM the (bf16-rounded) offset
    # input — a rounded offset moves the sample cell, a legitimate
    # discontinuity, not a numeric error
    "_contrib_DeformableConvolution",
}


@pytest.mark.parametrize(
    "name", [n for n in ALL_OPS
             if n not in SKIP and n not in FIXED_DTYPE
             and n not in F32_ONLY and n not in _CONSISTENCY_EXEMPT])
def test_dtype_consistency(name):
    """f32 vs bf16 runs agree at bf16-scaled tolerance (the reference's
    cross-dtype check_consistency, SURVEY.md §7).  Float outputs only —
    integer outputs (argmax/topk indices) may legitimately flip when
    bf16 rounding creates ties."""
    o = registry.OPS[name]
    a32, k32 = build_inputs(o, jnp.float32)
    a16, k16 = build_inputs(o, jnp.bfloat16)
    r32 = _flat_outputs(o.fn(*a32, **k32))
    r16 = _flat_outputs(o.fn(*a16, **k16))
    assert len(r32) == len(r16)
    for g32, g16 in zip(r32, r16):
        if not jnp.issubdtype(jnp.asarray(g32).dtype, jnp.floating):
            continue
        x32 = onp.asarray(jnp.asarray(g32).astype(jnp.float32))
        x16 = onp.asarray(jnp.asarray(g16).astype(jnp.float32))
        assert x32.shape == x16.shape, f"{name}: shape drift across dtype"
        onp.testing.assert_allclose(
            x16, x32, rtol=6e-2, atol=6e-2,
            err_msg=f"{name}: f32 vs bf16 runs diverge beyond bf16 "
                    "tolerance")


def test_value_tables_are_live_and_cover_registry():
    """Extends the staleness meta-test to the value tables (VERDICT r4
    item 3): entries must name real ops, every op must be value-checked
    or explicitly exempted with a reason, and coverage must stay >= 60%
    of the registry."""
    known = set(ALL_OPS)
    for table, tname in ((VALUE_REF, "VALUE_REF"),
                         (VALUE_EXEMPT, "VALUE_EXEMPT")):
        stale = set(table) - known
        assert not stale, f"{tname} names unknown ops: {sorted(stale)}"
    assert not _UNCOVERED, \
        (f"ops with neither a value reference nor a VALUE_EXEMPT entry: "
         f"{sorted(_UNCOVERED)}")
    frac = len(VALUE_CHECKED) / len(ALL_OPS)
    assert frac >= 0.60, \
        f"value-checked coverage {frac:.0%} fell below the 60% floor"
