#!/usr/bin/env python3
"""im2rec — pack an image folder (or .lst file) into RecordIO shards.

Reference surface: ``tools/im2rec.py`` (SURVEY.md L10): makes ``.lst``
listings from a folder tree and packs ``.rec``+``.idx`` files with
IRHeader-tagged JPEG records consumable by ImageRecordIter /
ImageRecordDataset.

Usage::

    python tools/im2rec.py prefix image_root --recursive --list   # make .lst
    python tools/im2rec.py prefix image_root                      # pack .rec
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive):
    i = 0
    cat = {}
    if recursive:
        for path in sorted(os.listdir(root)):
            full = os.path.join(root, path)
            if not os.path.isdir(full):
                continue
            if path not in cat:
                cat[path] = len(cat)
            for fname in sorted(os.listdir(full)):
                if fname.lower().endswith(_EXTS):
                    yield (i, os.path.join(path, fname), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            if fname.lower().endswith(_EXTS):
                yield (i, fname, 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as f:
        for idx, fname, label in image_list:
            f.write(f"{idx}\t{label}\t{fname}\n")


def read_list(path_in):
    with open(path_in) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield (int(parts[0]), parts[-1],
                   [float(x) for x in parts[1:-1]])


def pack(args, lst_path):
    from mxnet_tpu import recordio as rio
    from mxnet_tpu.image import imdecode_np, imencode
    from mxnet_tpu.image.image import _resize_np
    rec_path = lst_path[:-4] + ".rec"
    idx_path = lst_path[:-4] + ".idx"
    writer = rio.MXIndexedRecordIO(idx_path, rec_path, "w")
    n = 0
    for idx, fname, label in read_list(lst_path):
        full = os.path.join(args.root, fname)
        try:
            with open(full, "rb") as f:
                buf = f.read()
            if args.resize or args.quality != 95 or args.center_crop:
                img = imdecode_np(buf)
                if args.resize:
                    h, w = img.shape[:2]
                    if h > w:
                        img = _resize_np(img, args.resize,
                                         int(h * args.resize / w))
                    else:
                        img = _resize_np(img, int(w * args.resize / h),
                                         args.resize)
                if args.center_crop:
                    h, w = img.shape[:2]
                    s = min(h, w)
                    y0, x0 = (h - s) // 2, (w - s) // 2
                    img = img[y0:y0 + s, x0:x0 + s]
                buf = imencode(img, quality=args.quality)
        except Exception as e:
            print(f"skip {fname}: {e}", file=sys.stderr)
            continue
        lbl = label[0] if len(label) == 1 else label
        writer.write_idx(idx, rio.pack(rio.IRHeader(0, lbl, idx, 0), buf))
        n += 1
    writer.close()
    print(f"packed {n} records -> {rec_path}")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="make image lists / pack RecordIO "
                    "(reference tools/im2rec.py workalike)")
    p.add_argument("prefix", help="output prefix (or existing .lst)")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="generate .lst only (no packing)")
    p.add_argument("--recursive", action="store_true",
                   help="folders under root are label categories")
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter edge")
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    args = p.parse_args(argv)

    if args.list:
        images = list(list_images(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        if args.train_ratio < 1.0:
            k = int(len(images) * args.train_ratio)
            write_list(args.prefix + "_train.lst", images[:k])
            write_list(args.prefix + "_val.lst", images[k:])
        else:
            write_list(args.prefix + ".lst", images)
        print(f"listed {len(images)} images")
        return 0

    lst = args.prefix if args.prefix.endswith(".lst") else args.prefix + ".lst"
    if not os.path.isfile(lst):
        images = list(list_images(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        write_list(lst, images)
    pack(args, lst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
