# Makes `python -m tools.tracelint` resolvable from the repo root.  The
# standalone scripts in this directory (im2rec.py, launch.py, ...) are
# still invoked by path and do not rely on package-relative imports.
