"""TL001 host-sync-in-trace, TL002 donation-after-use, TL003 retrace
hazards — the three rules that guard the fused hot path's jit discipline.

Traced-region discovery is project-wide (:mod:`.project`): a host sync
two import hops away from the ``jax.jit`` call site is still reachable
from traced code and still re-serializes the step.  TL002/TL003 stay
scoped to one module's dataflow — donation locals and cache receivers
don't cross files.
"""
from __future__ import annotations

import ast
import re

from .callgraph import dotted, iter_own
from .core import Finding

__all__ = ["check_module"]

# zero-arg methods that force a device->host round trip
_HOST_SYNC_METHODS = {
    "item": "`.item()` pulls the scalar to host",
    "asnumpy": "`.asnumpy()` materializes the array on host",
    "tolist": "`.tolist()` materializes the array on host",
    "numpy": "`.numpy()` materializes the array on host",
    "wait_to_read": "`.wait_to_read()` blocks on device completion",
    "block_until_ready": "`.block_until_ready()` blocks on device "
                         "completion",
}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_NP_MATERIALIZERS = {"array", "asarray", "asanyarray", "ascontiguousarray",
                     "frombuffer", "copy"}
# containers that cannot be dict keys (or hash by identity)
_UNHASHABLE_DISPLAYS = {
    ast.List: "a list", ast.Dict: "a dict", ast.Set: "a set",
    ast.ListComp: "a list comprehension", ast.DictComp: "a dict "
    "comprehension", ast.SetComp: "a set comprehension",
    ast.GeneratorExp: "a generator", ast.Lambda: "a lambda",
}
_UNHASHABLE_CTORS = {"list", "dict", "set", "bytearray"}
# graph-walk memo dicts keyed by id(node) within one pass are legitimate,
# so only *cache*-named receivers (executable/trace caches) are audited
_CACHE_NAME_RE = re.compile(r"cache", re.IGNORECASE)
_CACHE_EXACT = {"_jitted"}
# attribute reads that are static under trace (no sync)
_STATIC_ATTRS = {"ndim", "shape", "size", "dtype"}
_TEST_SKIP_CALLS = {"isinstance", "len", "hasattr", "getattr", "callable",
                    "issubclass"}


def check_module(project, module):
    idx = project.index(module)
    findings = []
    findings.extend(_tl001(module, project.traced_in(module)))
    findings.extend(_tl002(module, idx))
    findings.extend(_tl003(module, idx))
    return findings


# --------------------------------------------------------------------- #
# TL001 — host sync inside traced code
# --------------------------------------------------------------------- #

def _benign_cast_arg(node):
    """Casts of trace-time python values (shapes, lens, literals) are
    fine; casts of anything array-flavored are a host sync."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        return d in ("len", "ord", "round", "abs")
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return isinstance(node.value, ast.Attribute) and \
            node.value.attr in _STATIC_ATTRS
    if isinstance(node, ast.BinOp):
        return _benign_cast_arg(node.left) and _benign_cast_arg(node.right)
    if isinstance(node, ast.UnaryOp):
        return _benign_cast_arg(node.operand)
    return False


def _host_sync_in_call(module, call):
    """Message when ``call`` is a host sync, else None."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in _HOST_CASTS:
        if len(call.args) == 1 and not _benign_cast_arg(call.args[0]):
            return (f"host cast `{func.id}(...)` forces a device sync "
                    "(and burns the value into the trace)")
        return None
    if isinstance(func, ast.Attribute):
        if func.attr in _HOST_SYNC_METHODS and not call.args:
            return _HOST_SYNC_METHODS[func.attr]
        d = dotted(func)
        if d:
            root, last = d.split(".")[0], d.split(".")[-1]
            if root in module.np_aliases and last in _NP_MATERIALIZERS:
                return (f"`{d}(...)` materializes a traced value as a "
                        "host numpy array")
            if last == "device_get" and (root in module.jax_aliases
                                         or root == "jax"):
                return f"`{d}(...)` is an explicit device->host readback"
    return None


def _arrayish_locals(module, fn_node):
    """Local names assigned from jnp/jax array producers (two passes so
    derived names like ``y = x + 1`` propagate)."""
    def produces_array(expr, known):
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d:
                    root = d.split(".")[0]
                    if root in module.jnp_aliases or \
                            d.startswith("jax.numpy.") or \
                            d.startswith("jax.lax."):
                        return True
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in known:
                return True
        return False

    known: set = set()
    for _ in range(2):
        for n in iter_own(fn_node):
            if isinstance(n, ast.Assign) and produces_array(n.value, known):
                for t in n.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            known.add(leaf.id)
            elif isinstance(n, ast.AugAssign) and isinstance(
                    n.target, ast.Name) and produces_array(n.value, known):
                known.add(n.target.id)
    return known


def _traced_branch_value(module, test, arrayish):
    """Name/expr when an if/while test depends on a traced array."""
    stack = [test]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            if d and d.split(".")[0] in _TEST_SKIP_CALLS:
                continue  # python-level predicates — no sync
            if d and (d.split(".")[0] in module.jnp_aliases
                      or d.startswith("jax.numpy.")):
                return f"{d}(...)"
            stack.extend(ast.iter_child_nodes(n))
        elif isinstance(n, ast.Attribute):
            if n.attr in _STATIC_ATTRS:
                continue  # x.ndim / x.shape are static under trace
            stack.extend(ast.iter_child_nodes(n))
        elif isinstance(n, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                continue  # identity tests resolve at trace time
            stack.extend(ast.iter_child_nodes(n))
        elif isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Load) and n.id in arrayish:
                return n.id
        else:
            stack.extend(ast.iter_child_nodes(n))
    return None


def _tl001(module, traced):
    out = []
    for info, reason in traced:
        arrayish = _arrayish_locals(module, info.node)
        for n in iter_own(info.node):
            if isinstance(n, ast.Call):
                msg = _host_sync_in_call(module, n)
                if msg:
                    out.append(Finding(
                        "TL001", module.path, n.lineno, n.col_offset,
                        f"{msg} — inside `{info.qualname}`, which is "
                        f"traced ({reason}); hoist it out of the traced "
                        "region or make the value an operand"))
            elif isinstance(n, (ast.If, ast.While)):
                val = _traced_branch_value(module, n.test, arrayish)
                if val:
                    kind = "while" if isinstance(n, ast.While) else "if"
                    out.append(Finding(
                        "TL001", module.path, n.lineno, n.col_offset,
                        f"`{kind} {val}:` branches on a traced array — "
                        f"inside `{info.qualname}`, which is traced "
                        f"({reason}); use jnp.where/lax.cond or lift the "
                        "decision to trace time"))
    return out


# --------------------------------------------------------------------- #
# TL002 — donated buffer read after dispatch
# --------------------------------------------------------------------- #

def _is_jit_call(call, module):
    d = dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    if parts[-1] not in ("jit", "pjit"):
        return False
    return len(parts) == 1 or parts[0] in module.jax_aliases or \
        parts[0] == "jax"


def _resolve_positions(expr, fn_node):
    """Static donated-position sets: literals, tuples of literals, names
    assigned such literals (IfExp unions both arms — conservative)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = set()
        for e in expr.elts:
            sub = _resolve_positions(e, fn_node)
            if sub is None:
                return None
            out |= sub
        return out
    if isinstance(expr, ast.IfExp):
        a = _resolve_positions(expr.body, fn_node)
        b = _resolve_positions(expr.orelse, fn_node)
        if a is None or b is None:
            return None
        return a | b
    if isinstance(expr, ast.Name) and fn_node is not None:
        out = set()
        for n in iter_own(fn_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets):
                sub = _resolve_positions(n.value, fn_node)
                if sub is None:
                    return None
                out |= sub
        return out or None
    return None


def _donation_index(module, idx):
    """(donating jit call-exprs, producer functions returning them)."""
    donating = {}  # id(call node) -> positions
    for call, scopes in idx.calls:
        if not _is_jit_call(call, module):
            continue
        kw = next((k for k in call.keywords
                   if k.arg == "donate_argnums"), None)
        if kw is None:
            continue
        fn_node = scopes[-1] if isinstance(
            scopes[-1], (ast.FunctionDef, ast.AsyncFunctionDef)) else None
        pos = _resolve_positions(kw.value, fn_node)
        if pos:
            donating[id(call)] = pos

    producers = {}  # id(fn node) -> positions (may be empty set)

    def _value_positions(value, info, scopes):
        """Positions known to be donated whenever ``value`` is the
        dispatched callable, or None when nothing is known.  Multiple
        reaching definitions / return paths INTERSECT: a position is
        only 'donated' if every resolvable path donates it (a phase-
        polymorphic compiler like FusedStep._compile returns different
        jits per phase — the union would flag live operands)."""
        if isinstance(value, ast.Call):
            if id(value) in donating:
                return set(donating[id(value)])
            sets = [producers[id(c.node)]
                    for c in idx.resolve_call(value, scopes)
                    if id(c.node) in producers]
            return set.intersection(*sets) if sets else None
        if isinstance(value, ast.Name):
            sets = []
            for n in iter_own(info.node):
                if isinstance(n, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == value.id
                        for t in n.targets):
                    s = _value_positions(n.value, info, scopes)
                    if s is not None:
                        sets.append(s)
            return set.intersection(*sets) if sets else None
        return None

    changed, rounds = True, 0
    while changed and rounds < 10:  # cap: recursive producer chains
        changed = False
        rounds += 1
        for info in idx.functions:
            scopes = info.scopes + (info.node,)
            sets = []
            for n in iter_own(info.node):
                if isinstance(n, ast.Return) and n.value is not None:
                    s = _value_positions(n.value, info, scopes)
                    if s is not None:
                        sets.append(s)
            if not sets:
                continue
            pos = set.intersection(*sets)
            if producers.get(id(info.node)) != pos:
                producers[id(info.node)] = pos
                changed = True
    return donating, producers


def _stores_and_loads(fn_node, key):
    """Line numbers of stores/loads of a Name or dotted self-attr."""
    stores, loads = [], []
    for n in iter_own(fn_node):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if dotted(leaf) == key and isinstance(
                            leaf, (ast.Name, ast.Attribute)) and \
                            isinstance(leaf.ctx, ast.Store):
                        stores.append(leaf.lineno)
        if isinstance(n, (ast.Name, ast.Attribute)) and \
                isinstance(getattr(n, "ctx", None), ast.Load) and \
                dotted(n) == key:
            loads.append(n.lineno)
    return stores, loads


def _tl002(module, idx):
    donating, producers = _donation_index(module, idx)
    if not donating and not producers:
        return []
    out = []
    for info in idx.functions:
        scopes = info.scopes + (info.node,)
        local_sets = {}  # local name -> [position sets, one per assign]
        for n in iter_own(info.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Call):
                if id(n.value) in donating:
                    pos = set(donating[id(n.value)])
                else:
                    sets = [producers[id(c.node)]
                            for c in idx.resolve_call(n.value, scopes)
                            if id(c.node) in producers]
                    pos = set.intersection(*sets) if sets else None
                if pos is not None:
                    local_sets.setdefault(n.targets[0].id, []).append(pos)
        # a name rebound from several sources donates only what EVERY
        # source donates (see _donation_index on phase polymorphism)
        donating_locals = {name: set.intersection(*sets)
                           for name, sets in local_sets.items()
                           if set.intersection(*sets)}
        if not donating_locals:
            continue
        for n in iter_own(info.node):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in donating_locals):
                continue
            call_end = getattr(n, "end_lineno", n.lineno) or n.lineno
            for p in sorted(donating_locals[n.func.id]):
                if p >= len(n.args):
                    continue
                key = dotted(n.args[p])
                if key is None:
                    continue  # complex expr — no binding to track
                stores, loads = _stores_and_loads(info.node, key)
                if any(n.lineno <= s <= call_end for s in stores):
                    continue  # rebound by the dispatch statement itself
                later = [s for s in stores if s > call_end]
                kill = min(later) if later else float("inf")
                bad = [ln for ln in loads if call_end < ln <= kill]
                if bad:
                    out.append(Finding(
                        "TL002", module.path, min(bad), 0,
                        f"`{key}` is donated to `{n.func.id}(...)` "
                        f"(arg {p}, dispatch at line {n.lineno}) and its "
                        "buffer is dead after the call — rebind it from "
                        "the result or stop reading it"))
    return out


# --------------------------------------------------------------------- #
# TL003 — retrace hazards
# --------------------------------------------------------------------- #

def _is_cache_receiver(expr):
    d = dotted(expr)
    if d is None:
        return False
    last = d.split(".")[-1]
    return bool(_CACHE_NAME_RE.search(last)) or last in _CACHE_EXACT


def _unhashable_reason(elem, fn_node):
    for typ, label in _UNHASHABLE_DISPLAYS.items():
        if isinstance(elem, typ):
            return label
    if isinstance(elem, ast.Call):
        d = dotted(elem.func)
        if d in _UNHASHABLE_CTORS:
            return f"a {d}()"
        if d == "id":
            return ("id(...) — an identity key retraces (and leaks an "
                    "entry) whenever the object is recreated")
    if isinstance(elem, ast.Name) and fn_node is not None:
        for n in iter_own(fn_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == elem.id
                    for t in n.targets):
                reason = _unhashable_reason(n.value, None)
                if reason:
                    return f"`{elem.id}`, bound to {reason}"
    return None


def _tl003(module, idx):
    out = []
    # -- cache-key hygiene ------------------------------------------------ #
    for info in idx.functions:
        for n in iter_own(info.node):
            key = None
            if isinstance(n, ast.Subscript) and _is_cache_receiver(n.value):
                key = n.slice
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("get", "setdefault") and \
                    _is_cache_receiver(n.func.value) and n.args:
                key = n.args[0]
            if key is None:
                continue
            if isinstance(key, ast.Name):
                # `key = (...)` then `cache.get(key)` — inspect the
                # tuple the name is bound to
                for n2 in iter_own(info.node):
                    if isinstance(n2, ast.Assign) and isinstance(
                            n2.value, ast.Tuple) and any(
                            isinstance(t, ast.Name) and t.id == key.id
                            for t in n2.targets):
                        key = n2.value
                        break
            elems = key.elts if isinstance(key, ast.Tuple) else [key]
            for elem in elems:
                reason = _unhashable_reason(elem, info.node)
                if reason:
                    recv = dotted(n.value if isinstance(n, ast.Subscript)
                                  else n.func.value)
                    out.append(Finding(
                        "TL003", module.path, elem.lineno, elem.col_offset,
                        f"executable-cache key for `{recv}` contains "
                        f"{reason} — unhashable/unstable keys mean a "
                        "retrace (or TypeError) per step; key on "
                        "shape/dtype/hashable hyperparameters instead"))
    # -- jit constructed inside a loop ------------------------------------ #
    for call, scopes in idx.calls:
        if not _is_jit_call(call, module):
            continue
        owner = scopes[-1]
        if _inside_loop(owner, call):
            out.append(Finding(
                "TL003", module.path, call.lineno, call.col_offset,
                "jitted executable constructed inside a loop — every "
                "iteration compiles a fresh executable; hoist the jit "
                "and cache it by signature"))
    return out


def _inside_loop(scope_node, target):
    """True when ``target`` sits under a For/While within its scope."""
    hit = [False]

    def walk(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if child is target and in_loop:
                hit[0] = True
                return
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            walk(child, in_loop or isinstance(
                child, (ast.For, ast.While, ast.AsyncFor)))

    walk(scope_node, False)
    return hit[0]
