"""TL005 — every ``MXNET_*`` escape hatch and docs/ENV_VARS.md agree.

Reads are collected via ast (``os.environ.get`` / ``os.environ[...]`` /
``os.getenv`` / the repo's ``get_env`` / ``env_truthy`` /
``register_env``).  Undocumented-read findings are scoped to the
scanned files; the stale-row direction is judged against reads in the
ENTIRE repo that owns the docs file (library, benchmark and tooling
layers alike — regex fallback if a file does not parse), so linting a
subset of the tree never reports hatches read elsewhere as stale.  The
docs side takes only variables named in the FIRST cell of a table row —
prose references to other systems' vars don't count as documentation.
"""
from __future__ import annotations

import ast
import os
import re

from .callgraph import dotted
from .core import Finding

__all__ = ["check"]

_VAR_RE = re.compile(r"MXNET_[A-Z0-9_]+")
_DOC_ROW_RE = re.compile(r"^\s*\|([^|]*)\|")
_READ_FNS = {"get_env", "env_truthy", "register_env", "getenv"}
_AUX_READ_RE = re.compile(
    r"(?:environ\.get|environ\[|getenv|get_env|env_truthy|register_env)"
    r"\(?\s*[\"'](MXNET_[A-Z0-9_]+)[\"']")


def _reads_in_tree(tree):
    """(var, line) pairs for every MXNET_* env read in one parsed file."""
    out = []
    for node in ast.walk(tree):
        var = None
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            last = d.split(".")[-1] if d else None
            if (last in _READ_FNS or (d and d.endswith("environ.get"))) \
                    and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                var = node.args[0].value
        elif isinstance(node, ast.Subscript):
            d = dotted(node.value)
            if d and d.endswith("environ") and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                var = node.slice.value
        if var and _VAR_RE.fullmatch(var):
            out.append((var, node.lineno))
    return out


def _documented_vars(docs_path):
    """var -> first doc line, from the first cell of each table row."""
    out = {}
    with open(docs_path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _DOC_ROW_RE.match(line)
            if not m:
                continue
            for var in _VAR_RE.findall(m.group(1)):
                out.setdefault(var, i)
    return out


def _aux_reads(docs_path, parsed=None):
    """MXNET_* reads across the WHOLE repo that owns the docs file.

    The stale-row direction ('documented but never read') must be
    judged against the full tree, not just the paths being linted —
    otherwise linting a single edited file reports every hatch read
    elsewhere as stale.  The undocumented-read direction stays scoped
    to the scanned files (those findings carry file/line anchors).
    ``parsed`` maps absolute paths to already-parsed trees so files in
    the scanned set are not parsed twice."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(docs_path)))
    parsed = parsed or {}
    vars_seen = set()
    candidates = []
    for r, dirs, names in os.walk(root):
        dirs[:] = [x for x in dirs
                   if x not in ("__pycache__", "node_modules")
                   and not x.startswith(".")]
        candidates.extend(os.path.join(r, n) for n in names
                          if n.endswith(".py"))
    for path in candidates:
        tree = parsed.get(path)
        if tree is not None:
            vars_seen.update(v for v, _ in _reads_in_tree(tree))
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        try:
            vars_seen.update(v for v, _ in _reads_in_tree(ast.parse(src)))
        except SyntaxError:
            vars_seen.update(_AUX_READ_RE.findall(src))
    return vars_seen


def check(modules, docs_path):
    if docs_path is None or not modules:
        return []  # nothing to reconcile against (fixture runs)
    findings = []
    read_lines = {}  # var -> (path, line) of first read
    for m in modules:
        for var, line in _reads_in_tree(m.tree):
            read_lines.setdefault(var, (m.path, line))
    documented = _documented_vars(docs_path)
    for var, (path, line) in sorted(read_lines.items()):
        if var not in documented:
            findings.append(Finding(
                "TL005", path, line, 0,
                f"`{var}` is read here but has no row in "
                f"{os.path.relpath(docs_path)} — document the hatch "
                "(default + effect) or remove the read"))
    all_reads = set(read_lines) | _aux_reads(
        docs_path, {os.path.abspath(m.path): m.tree for m in modules})
    for var, line in sorted(documented.items()):
        if var not in all_reads:
            findings.append(Finding(
                "TL005", docs_path, line, 0,
                f"`{var}` is documented but never read anywhere in the "
                "library or tooling — stale row; delete it or wire the "
                "hatch up (register_env keeps accepted-and-ignored vars "
                "honest)", snippet=f"doc row for {var}"))
    return findings
