"""TL005 — every ``MXNET_*`` escape hatch and docs/ENV_VARS.md agree.

Reads are collected via ast (``os.environ.get`` / ``os.environ[...]`` /
``os.getenv`` / the repo's ``get_env`` / ``env_truthy`` /
``register_env``).  Undocumented-read findings are scoped to the
scanned files; the stale-row direction is judged against reads in the
ENTIRE repo that owns the docs file (library, benchmark and tooling
layers alike — regex fallback if a file does not parse), so linting a
subset of the tree never reports hatches read elsewhere as stale.  The
docs side takes only variables named in the FIRST cell of a table row —
prose references to other systems' vars don't count as documentation.
"""
from __future__ import annotations

import ast
import os
import re

from .callgraph import dotted
from .core import Finding

__all__ = ["check", "repo_scan", "RepoScan"]

_VAR_RE = re.compile(r"MXNET_[A-Z0-9_]+")
_DOC_ROW_RE = re.compile(r"^\s*\|([^|]*)\|")
_READ_FNS = {"get_env", "env_truthy", "register_env", "getenv"}
_AUX_READ_RE = re.compile(
    r"(?:environ\.get|environ\[|getenv|get_env|env_truthy|register_env)"
    r"\(?\s*[\"'](MXNET_[A-Z0-9_]+)[\"']")


def _reads_in_tree(tree):
    """(var, line) pairs for every MXNET_* env read in one parsed file."""
    out = []
    for node in ast.walk(tree):
        var = None
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            last = d.split(".")[-1] if d else None
            if (last in _READ_FNS or (d and d.endswith("environ.get"))) \
                    and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                var = node.args[0].value
        elif isinstance(node, ast.Subscript):
            d = dotted(node.value)
            if d and d.endswith("environ") and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                var = node.slice.value
        if var and _VAR_RE.fullmatch(var):
            out.append((var, node.lineno))
    return out


def _documented_vars(docs_path):
    """var -> first doc line, from the first cell of each table row."""
    out = {}
    with open(docs_path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _DOC_ROW_RE.match(line)
            if not m:
                continue
            for var in _VAR_RE.findall(m.group(1)):
                out.setdefault(var, i)
    return out


# directories the TL015 contract scan skips: tests emit fixture kinds
# ("t.site") that must never count as the library's contract surface,
# and examples are demo code, not producers
_NON_CONTRACT_DIRS = {"tests", "test", "example", "examples", "fixtures"}


class RepoScan:
    """One walk over the repo that owns the docs files, shared by the
    repo-wide reconciliation directions of TL005 (env vars) and TL015
    (event kinds / metric names / fault sites).

    The stale-row direction ('documented but never used') must be
    judged against the full tree, not just the paths being linted —
    otherwise linting a single edited file reports every contract
    satisfied elsewhere as stale.  The undocumented-use direction stays
    scoped to the scanned files (those findings carry file/line
    anchors).  Env-var reads are collected everywhere (a hatch read
    only by a test is still real); telemetry uses skip test/example
    trees (a fixture kind is not a contract)."""

    __slots__ = ("env_vars", "emit_kinds", "metric_lits", "metric_pats",
                 "fault_sites")

    def __init__(self):
        self.env_vars = set()
        self.emit_kinds = set()
        self.metric_lits = set()
        self.metric_pats = set()
        self.fault_sites = set()


def repo_scan(root, parsed=None):
    """Walk ``root`` once, parsing each .py file at most once (reusing
    already-parsed trees via ``parsed``: abs path -> ast)."""
    from .rules_runtime import telemetry_uses

    parsed = parsed or {}
    scan = RepoScan()
    candidates = []
    for r, dirs, names in os.walk(root):
        dirs[:] = [x for x in dirs
                   if x not in ("__pycache__", "node_modules")
                   and not x.startswith(".")]
        candidates.extend(os.path.join(r, n) for n in names
                          if n.endswith(".py"))
    for path in candidates:
        tree = parsed.get(path)
        if tree is None:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except OSError:
                continue
            try:
                tree = ast.parse(src)
            except SyntaxError:
                scan.env_vars.update(_AUX_READ_RE.findall(src))
                continue
        scan.env_vars.update(v for v, _ in _reads_in_tree(tree))
        rel_parts = set(os.path.relpath(path, root).split(os.sep))
        if rel_parts & _NON_CONTRACT_DIRS:
            continue
        uses = telemetry_uses(tree)
        scan.emit_kinds.update(k for k, _ in uses.emits)
        scan.metric_lits.update(n for n, _ in uses.metric_lits)
        scan.metric_pats.update(p for p, _ in uses.metric_pats)
        scan.fault_sites.update(s for s, _ in uses.sites)
    return scan


def check(modules, docs_path, aux=None):
    if docs_path is None or not modules:
        return []  # nothing to reconcile against (fixture runs)
    findings = []
    read_lines = {}  # var -> (path, line) of first read
    for m in modules:
        for var, line in _reads_in_tree(m.tree):
            read_lines.setdefault(var, (m.path, line))
    documented = _documented_vars(docs_path)
    for var, (path, line) in sorted(read_lines.items()):
        if var not in documented:
            findings.append(Finding(
                "TL005", path, line, 0,
                f"`{var}` is read here but has no row in "
                f"{os.path.relpath(docs_path)} — document the hatch "
                "(default + effect) or remove the read"))
    if aux is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(docs_path)))
        aux = repo_scan(root, {os.path.abspath(m.path): m.tree
                               for m in modules})
    all_reads = set(read_lines) | aux.env_vars
    for var, line in sorted(documented.items()):
        if var not in all_reads:
            findings.append(Finding(
                "TL005", docs_path, line, 0,
                f"`{var}` is documented but never read anywhere in the "
                "library or tooling — stale row; delete it or wire the "
                "hatch up (register_env keeps accepted-and-ignored vars "
                "honest)", snippet=f"doc row for {var}"))
    return findings
