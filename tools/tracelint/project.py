"""Repo-wide call graph: import resolution + project traced discovery.

PR 4's tracelint walked a *module-local* call graph, which made it blind
to exactly the seams where sharding discipline breaks — the fused step
(`gluon/fused_step.py`) jits closures built in `autograd`, the serve
engine traces samplers defined in `models/decoding.py`, and the
collectives (`parallel/collectives.py`) wrap helpers from `mesh.py`.
This module upgrades discovery to the whole lint target:

* every scanned file gets a dotted module name (walk up while
  ``__init__.py`` exists, so ``mxnet_tpu/parallel/mesh.py`` is
  ``mxnet_tpu.parallel.mesh`` and a bare fixture ``a.py`` is ``a``);
* per-module import tables resolve ``import a.b as c``,
  ``from x import y as z`` (function or submodule, any alias), and
  relative imports at any level;
* calls resolve across modules: bare names through ``from x import y``
  (chasing ``__init__`` re-exports), dotted names through module
  aliases (longest-prefix match), ``self.method`` through the class's
  *project-wide* family (bases imported from other modules and their
  cross-module subclasses);
* traced seeds (jit call sites, decorators, trace_scope — plus
  function-valued args inside ``functools.partial``) propagate through
  those cross-module edges.

Unresolvable imports (jax, numpy, stdlib, files outside the lint
target) simply contribute no edges, so per-module behavior degrades to
exactly the old module-local walk — linting a single file still works.
"""
from __future__ import annotations

import ast
import os

from .callgraph import (Index, dotted, is_tracing_entry, iter_own,
                        _is_jit_decorator, _opens_trace_scope)

__all__ = ["Project", "module_name"]

_MAX_REEXPORT_HOPS = 8


def module_name(path):
    """Dotted module name for ``path``, anchored at the outermost
    directory that still has an ``__init__.py``."""
    path = os.path.abspath(path)
    base = os.path.splitext(os.path.basename(path))[0]
    parts = [] if base == "__init__" else [base]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        nxt = os.path.dirname(d)
        if nxt == d:
            break
        d = nxt
    return ".".join(reversed(parts)) if parts else None


class Imports:
    """One module's import bindings.

    ``mod_aliases``  local name -> dotted module name (``import a.b as c``,
                     ``from pkg import submod``)
    ``from_imports`` local name -> (dotted module, remote name) for
                     ``from x import y [as z]`` — recorded even when ``x``
                     is outside the project (rules use the target names,
                     e.g. ``from jax.lax import psum``)
    ``stars``        modules star-imported (``from x import *``)
    """

    def __init__(self, module, my_name, is_pkg):
        self.mod_aliases = {}
        self.from_imports = {}
        self.stars = []
        # the package context for relative imports: a package's
        # __init__ is its own base; a plain module's base is its parent
        pkg_parts = (my_name.split(".") if my_name else [])
        if not is_pkg and pkg_parts:
            pkg_parts = pkg_parts[:-1]
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.mod_aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.mod_aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                base_parts = list(pkg_parts)
                if node.level:
                    drop = node.level - 1
                    if drop > len(base_parts):
                        continue  # relative import past the root
                    base_parts = base_parts[:len(base_parts) - drop] \
                        if drop else base_parts
                else:
                    base_parts = []
                if node.module:
                    base_parts = base_parts + node.module.split(".")
                base = ".".join(base_parts)
                if not base:
                    continue
                for a in node.names:
                    if a.name == "*":
                        self.stars.append(base)
                        continue
                    local = a.asname or a.name
                    self.from_imports[local] = (base, a.name)


class Project:
    """All scanned modules + the cross-module resolution every rule
    shares.  Build once per run; per-module rule passes read it."""

    def __init__(self, modules):
        self.modules = modules
        self.by_path = {m.path: m for m in modules}
        self.by_name = {}
        self.names = {}          # id(module) -> dotted name
        self.indexes = {}        # id(module) -> Index
        self.imports = {}        # id(module) -> Imports
        for m in modules:
            name = module_name(m.path)
            self.names[id(m)] = name
            if name:
                self.by_name[name] = m
            self.indexes[id(m)] = Index(m)
        for m in modules:
            is_pkg = os.path.basename(m.path) == "__init__.py"
            self.imports[id(m)] = Imports(m, self.names[id(m)], is_pkg)
        self._build_class_registry()
        self.traced = {}  # id(fn node) -> (module, FuncInfo, reason)
        self._discover_traced()

    def index(self, module):
        return self.indexes[id(module)]

    # -- module-scope function lookup (with re-export chasing) ---------- #
    def _module_func(self, mod, name, hops=0):
        """FuncInfo for ``name`` at the top level of module ``mod``,
        following ``from x import name`` re-exports (the package
        ``__init__`` pattern) up to a small hop budget."""
        if mod is None or hops > _MAX_REEXPORT_HOPS:
            return None
        idx = self.indexes[id(mod)]
        info = idx.scope_funcs.get(id(mod.tree), {}).get(name)
        if info is not None:
            return mod, info
        imp = self.imports[id(mod)]
        if name in imp.from_imports:
            tgt, remote = imp.from_imports[name]
            return self._module_func(self.by_name.get(tgt), remote,
                                     hops + 1)
        if name in imp.mod_aliases:
            return None  # a submodule, not a function
        for star in imp.stars:
            hit = self._module_func(self.by_name.get(star), name,
                                    hops + 1)
            if hit is not None:
                return hit
        return None

    def _resolve_module_prefix(self, module, parts):
        """Longest prefix of ``parts`` naming a project module (through
        this module's aliases), plus the remainder.

        The head must be an IMPORT BINDING of this module — a dotted
        name whose head is a plain local variable (``bench.run(x)``
        where ``bench = Bench()``) stays unresolved even when a lint
        module happens to share the name; resolving it would fabricate
        traced edges into unrelated files."""
        imp = self.imports[id(module)]
        head = parts[0]
        expansions = []
        if head in imp.mod_aliases:
            expansions.append(imp.mod_aliases[head].split(".")
                              + parts[1:])
        if head in imp.from_imports:
            tgt, remote = imp.from_imports[head]
            expansions.append(tgt.split(".") + [remote] + parts[1:])
        for full in expansions:
            for cut in range(len(full) - 1, 0, -1):
                mod = self.by_name.get(".".join(full[:cut]))
                if mod is not None:
                    return mod, full[cut:]
        return None, parts

    # -- cross-module class families ------------------------------------ #
    def _build_class_registry(self):
        self._class_key = {}    # (modname, clsname) -> (module, ClassDef)
        for m in self.modules:
            name = self.names[id(m)] or m.path
            for cname, cnode in self.indexes[id(m)].classes.items():
                self._class_key[(name, cname)] = (m, cnode)
        self._bases = {}        # class key -> [base class keys]
        self._subs = {}         # class key -> [subclass keys]
        for m in self.modules:
            name = self.names[id(m)] or m.path
            for cname, cnode in self.indexes[id(m)].classes.items():
                key = (name, cname)
                for base in cnode.bases:
                    bkey = self._resolve_class_ref(m, base)
                    if bkey is not None:
                        self._bases.setdefault(key, []).append(bkey)
                        self._subs.setdefault(bkey, []).append(key)

    def _resolve_class_ref(self, module, expr):
        """(modname, clsname) key for a base-class expression, through
        this module's imports; None when outside the project."""
        d = dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        myname = self.names[id(module)] or module.path
        if len(parts) == 1:
            if parts[0] in self.indexes[id(module)].classes:
                return (myname, parts[0])
            imp = self.imports[id(module)]
            if parts[0] in imp.from_imports:
                tgt, remote = imp.from_imports[parts[0]]
                tm = self.by_name.get(tgt)
                if tm is not None and \
                        remote in self.indexes[id(tm)].classes:
                    return (self.names[id(tm)], remote)
            return None
        mod, rest = self._resolve_module_prefix(module, parts)
        if mod is not None and len(rest) == 1 and \
                rest[0] in self.indexes[id(mod)].classes:
            return (self.names[id(mod)] or mod.path, rest[0])
        return None

    def _class_family(self, module, cls):
        """The class plus its ancestors and descendants, project-wide."""
        myname = self.names[id(module)] or module.path
        start = (myname, cls.name)
        family, work = {start}, [start]
        while work:
            key = work.pop()
            for nxt in self._bases.get(key, []) + self._subs.get(key, []):
                if nxt not in family:
                    family.add(nxt)
                    work.append(nxt)
        return [self._class_key[k] for k in sorted(family)
                if k in self._class_key]

    def resolve_self_method(self, module, attr, scopes):
        """``self.attr(...)`` → matching method defs across the class's
        project-wide family."""
        cls = None
        for scope in reversed(scopes):
            if isinstance(scope, ast.ClassDef):
                cls = scope
                break
        if cls is None:
            return []
        out = []
        for fam_mod, fam_cls in self._class_family(module, cls):
            info = self.indexes[id(fam_mod)].class_methods.get(
                id(fam_cls), {}).get(attr)
            if info is not None:
                out.append((fam_mod, info))
        return out

    # -- call resolution ------------------------------------------------- #
    def resolve_call(self, module, call, scopes):
        """(module, FuncInfo) pairs a call statically resolves to.
        Module-local resolution first; cross-module through the import
        tables when that comes up empty (the fallback contract)."""
        idx = self.indexes[id(module)]
        func = call.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id == "self":
            # the project-wide class family is a superset of the
            # module-local one (bases/subclasses in other modules), so
            # self.method resolves through it directly
            hits = self.resolve_self_method(module, func.attr, scopes)
            if hits:
                return hits
        local = idx.resolve_call(call, scopes)
        if local:
            return [(module, info) for info in local]
        if isinstance(func, ast.Name):
            imp = self.imports[id(module)]
            if func.id in imp.from_imports:
                tgt, remote = imp.from_imports[func.id]
                hit = self._module_func(self.by_name.get(tgt), remote)
                if hit is not None:
                    return [hit]
            for star in imp.stars:
                hit = self._module_func(self.by_name.get(star), func.id)
                if hit is not None:
                    return [hit]
            return []
        if isinstance(func, ast.Attribute):
            d = dotted(func)
            if d is None:
                return []
            mod, rest = self._resolve_module_prefix(module, d.split("."))
            if mod is None:
                return []
            if len(rest) == 1:
                hit = self._module_func(mod, rest[0])
                return [hit] if hit is not None else []
            if len(rest) == 2:  # mod.Class.method
                cnode = self.indexes[id(mod)].classes.get(rest[0])
                if cnode is not None:
                    info = self.indexes[id(mod)].class_methods.get(
                        id(cnode), {}).get(rest[1])
                    if info is not None:
                        return [(mod, info)]
        return []

    # -- traced discovery ------------------------------------------------ #
    def _seed_targets(self, module, call, scopes):
        """Function-valued args of one tracing entry point — bare names,
        dotted module paths, and the same through functools.partial."""
        out = []
        args = list(call.args)
        for a in call.args:
            if isinstance(a, ast.Call):
                d = dotted(a.func)
                if d and d.split(".")[-1] == "partial" and a.args:
                    args.extend(a.args)
        for arg in args:
            if isinstance(arg, ast.Name):
                idx = self.indexes[id(module)]
                info = idx.resolve_name(arg.id, scopes)
                if info is not None:
                    out.append((module, info))
                    continue
                imp = self.imports[id(module)]
                if arg.id in imp.from_imports:
                    tgt, remote = imp.from_imports[arg.id]
                    hit = self._module_func(self.by_name.get(tgt), remote)
                    if hit is not None:
                        out.append(hit)
            elif isinstance(arg, ast.Attribute):
                d = dotted(arg)
                if d is None:
                    continue
                mod, rest = self._resolve_module_prefix(
                    module, d.split("."))
                if mod is not None and len(rest) == 1:
                    hit = self._module_func(mod, rest[0])
                    if hit is not None:
                        out.append(hit)
        return out

    def _mark(self, module, info, reason, work):
        if info is None or id(info.node) in self.traced:
            return
        self.traced[id(info.node)] = (module, info, reason)
        work.append((module, info))

    def _discover_traced(self):
        work = []
        for m in self.modules:
            idx = self.indexes[id(m)]
            for call, scopes in idx.calls:
                if not is_tracing_entry(call, m):
                    continue
                entry = dotted(call.func)
                for tmod, tinfo in self._seed_targets(m, call, scopes):
                    self._mark(tmod, tinfo,
                               f"passed to {entry} at "
                               f"{os.path.basename(m.path)}:{call.lineno}",
                               work)
            for info in idx.functions:
                for dec in info.node.decorator_list:
                    if _is_jit_decorator(dec, m):
                        self._mark(m, info, "decorated with jit", work)
                if _opens_trace_scope(info.node):
                    self._mark(m, info, "opens trace_scope", work)
        while work:
            mod, info = work.pop()
            reason = self.traced[id(info.node)][2]
            scopes = info.scopes + (info.node,)
            for n in iter_own(info.node):
                if isinstance(n, ast.Call):
                    for cmod, callee in self.resolve_call(mod, n, scopes):
                        self._mark(
                            cmod, callee,
                            f"called from traced `{info.qualname}` "
                            f"({reason})", work)

    def traced_in(self, module):
        """(FuncInfo, reason) pairs for traced functions defined in
        ``module`` — same shape CallGraph.traced_funcs had."""
        return [(info, reason) for mod, info, reason in
                self.traced.values() if mod is module]
