"""TL004 — lock discipline for the iterator rings and other shared state.

Two checks, both scoped to state that is *already* lock-protected
somewhere (a field nobody locks is presumed single-threaded; the rule
enforces consistency, not adoption):

* a field mutated under ``with self._lock`` in one method must be
  mutated under it everywhere (``__init__`` excepted — the object is
  not shared yet);
* two locks acquired nested in one order somewhere and the opposite
  order elsewhere is a deadlock waiting for a scheduler (the
  ``DevicePrefetchIter`` producer vs ``close()`` shape).

Works at class level (``self.X = threading.Lock()``) and module level
(``_lock = threading.Lock()`` guarding module globals).
"""
from __future__ import annotations

import ast

from .callgraph import dotted, iter_own
from .core import Finding

__all__ = ["check_module"]

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "appendleft", "pop", "popleft", "clear", "extend",
             "extendleft", "remove", "insert", "add", "discard", "update",
             "setdefault", "popitem", "sort", "reverse"}


def _is_lock_ctor(expr):
    if not isinstance(expr, ast.Call):
        return False
    d = dotted(expr.func)
    return bool(d) and d.split(".")[-1] in _LOCK_CTORS


class _Mutation:
    __slots__ = ("field", "line", "col", "held", "method")

    def __init__(self, field, line, col, held, method):
        self.field = field
        self.line = line
        self.col = col
        self.held = held       # tuple of lock keys held at this point
        self.method = method


def _walk_mutations(fn_node, lock_of_expr, field_of_node, method_name,
                    acquisitions):
    """Collect mutations + lock-acquisition order pairs in one method.

    ``lock_of_expr(expr)`` -> lock key for a with-item, or None.
    ``field_of_node(node)`` -> iterable of mutated field keys.
    """
    muts = []

    def walk(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            new_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    lock = lock_of_expr(item.context_expr)
                    if lock is not None:
                        if new_held:
                            acquisitions.append(
                                (new_held[-1], lock, child.lineno))
                        new_held = new_held + (lock,)
            for field in field_of_node(child):
                muts.append(_Mutation(field, child.lineno,
                                      getattr(child, "col_offset", 0),
                                      new_held, method_name))
            walk(child, new_held)

    walk(fn_node, ())
    return muts


def _self_attr(expr):
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _class_field_of_node(lock_attrs):
    def fields(node):
        out = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr and attr not in lock_attrs:
                    out.append(attr)
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        out.append(attr)
                if isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        attr = _self_attr(e)
                        if attr and attr not in lock_attrs:
                            out.append(attr)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr:
                out.append(attr)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    out.append(attr)
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        out.append(attr)
        return out
    return fields


def _class_methods(cls):
    """Every function belonging to ``cls`` — methods and their nested
    closures, but NOT anything inside a nested ClassDef (the inner
    class owns its own lock discipline and is checked separately)."""
    out, stack = [], list(ast.iter_child_nodes(cls))
    while stack:
        n = stack.pop()
        if isinstance(n, ast.ClassDef):
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _check_class(module, cls, acquisitions):
    methods = _class_methods(cls)
    lock_attrs = set()
    for m in methods:
        for n in iter_own(m):
            if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr:
                        lock_attrs.add(attr)
    if not lock_attrs:
        return []

    def lock_of(expr):
        attr = _self_attr(expr)
        if attr in lock_attrs:
            return f"{cls.name}.{attr}"
        # with self._lock.acquire_timeout(...) style — attribute chains
        d = dotted(expr.func) if isinstance(expr, ast.Call) else None
        if d and d.startswith("self."):
            parts = d.split(".")
            if len(parts) >= 2 and parts[1] in lock_attrs:
                return f"{cls.name}.{parts[1]}"
        return None

    muts = []
    for m in methods:
        muts.extend(_walk_mutations(m, lock_of,
                                    _class_field_of_node(lock_attrs),
                                    m.name, acquisitions))
    protected = {mu.field for mu in muts if mu.held}
    out = []
    for mu in muts:
        if mu.field in protected and not mu.held and \
                mu.method != "__init__":
            out.append(Finding(
                "TL004", module.path, mu.line, mu.col,
                f"`self.{mu.field}` is mutated under the lock elsewhere "
                f"in `{cls.name}` but `{mu.method}` mutates it without "
                "holding it — take the lock or document why this "
                "mutation cannot race"))
    return out


def _check_module_level(module, acquisitions):
    tree = module.tree
    mod_locks = set()
    mod_names = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            names = [t.id for t in stmt.targets
                     if isinstance(t, ast.Name)]
            mod_names.update(names)
            if _is_lock_ctor(stmt.value):
                mod_locks.update(names)
    if not mod_locks:
        return []

    def lock_of(expr):
        d = dotted(expr)
        if d in mod_locks:
            return f"{module.path}:{d}"
        return None

    def fields(node):
        out = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in mod_names:
                    out.append(t.value.id)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in mod_names:
            out.append(node.func.value.id)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in mod_names:
                    out.append(t.value.id)
        return out

    muts = []
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            muts.extend(_walk_mutations(fn, lock_of, fields, fn.name,
                                        acquisitions))
    protected = {mu.field for mu in muts if mu.held}
    out = []
    for mu in muts:
        if mu.field in protected and not mu.held:
            out.append(Finding(
                "TL004", module.path, mu.line, mu.col,
                f"module global `{mu.field}` is mutated under the lock "
                f"elsewhere but `{mu.method}` mutates it without holding "
                "it"))
    return out


def check_module(module):
    findings = []
    acquisitions = []  # (outer lock, inner lock, line)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(module, node, acquisitions))
    findings.extend(_check_module_level(module, acquisitions))
    # -- lock-order inversions ------------------------------------------- #
    pairs = {}
    for outer, inner, line in acquisitions:
        pairs.setdefault((outer, inner), []).append(line)
    for (a, b), lines in sorted(pairs.items()):
        if (b, a) in pairs and a < b:  # report one direction once
            findings.append(Finding(
                "TL004", module.path, min(lines), 0,
                f"lock-order inversion: `{a}` -> `{b}` here but "
                f"`{b}` -> `{a}` at line {min(pairs[(b, a)])} — pick one "
                "global order or merge the critical sections"))
    return findings
