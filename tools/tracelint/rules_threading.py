"""TL004 — lock discipline for the iterator rings and other shared state.

Two checks, both scoped to state that is *already* lock-protected
somewhere (a field nobody locks is presumed single-threaded; the rule
enforces consistency, not adoption):

* a field mutated under ``with self._lock`` in one method must be
  mutated under it everywhere (``__init__`` excepted — the object is
  not shared yet);
* two locks acquired nested in one order somewhere and the opposite
  order elsewhere is a deadlock waiting for a scheduler (the
  ``DevicePrefetchIter`` producer vs ``close()`` shape).

Works at class level (``self.X = threading.Lock()``) and module level
(``_lock = threading.Lock()`` guarding module globals).

Since tracelint v3 the held-lock region walk itself lives in
:mod:`.locks` — computed once per module in ``build_state`` and shared
with TL012 (finalizer lock safety) and TL013 (callback-under-lock), so
the three rules pay for one analysis.
"""
from __future__ import annotations

from .core import Finding

__all__ = ["check_module"]


def check_module(shared, module):
    la = shared.locks[id(module)]
    findings = []
    # -- class-level: unlocked mutations of protected self-fields -------- #
    for cls, muts in la.class_muts.values():
        protected = {mu.field for mu in muts if mu.held}
        for mu in muts:
            if mu.field in protected and not mu.held and \
                    mu.method != "__init__":
                findings.append(Finding(
                    "TL004", module.path, mu.line, mu.col,
                    f"`self.{mu.field}` is mutated under the lock "
                    f"elsewhere in `{cls.name}` but `{mu.method}` "
                    "mutates it without holding it — take the lock or "
                    "document why this mutation cannot race"))
    # -- module-level globals --------------------------------------------- #
    protected = {mu.field for mu in la.module_muts if mu.held}
    for mu in la.module_muts:
        if mu.field in protected and not mu.held:
            findings.append(Finding(
                "TL004", module.path, mu.line, mu.col,
                f"module global `{mu.field}` is mutated under the lock "
                f"elsewhere but `{mu.method}` mutates it without holding "
                "it"))
    # -- lock-order inversions ------------------------------------------- #
    pairs = {}
    for outer, inner, line in la.acquisitions:
        pairs.setdefault((outer, inner), []).append(line)
    for (a, b), lines in sorted(pairs.items()):
        if (b, a) in pairs and a < b:  # report one direction once
            findings.append(Finding(
                "TL004", module.path, min(lines), 0,
                f"lock-order inversion: `{a}` -> `{b}` here but "
                f"`{b}` -> `{a}` at line {min(pairs[(b, a)])} — pick one "
                "global order or merge the critical sections"))
    return findings
