"""TL006–TL009 — the sharding / multi-host discipline family.

These are the bug classes the multi-process pod runtime (`kvstore='tpu'`,
GSPMD across hosts) hits at 64-chip scale, where every one of them is a
hang or a silent replication instead of a stack trace:

* **TL006** — a collective / ``PartitionSpec`` axis name must be bound
  by a mesh (or axis-binding ``pmap``/``vmap``) definition somewhere in
  the lint target.  An unknown axis fails to compile at best; in a
  ``PartitionSpec`` it silently replicates the dim.  Axis names that
  exist only as default-``axis`` parameters are *conditionally* bound
  (a caller-supplied mesh has to provide them) — warn, not error.
* **TL007** — cross-host trace divergence: reads of
  ``jax.process_index()`` / ``process_count()``, ``os.environ``,
  wall-clock time, or host RNG inside trace-reachable code compile a
  *different program on different hosts*; the first collective then
  waits forever for peers that compiled something else.  Same family:
  ``donate_argnums`` / sharding arguments derived from set iteration or
  ``id()`` ordering (per-process hash seeds make the order differ).
* **TL008** — a collective issued under a data- or host-dependent
  Python branch inside a traced region: the canonical SPMD hang (some
  shards/hosts issue the collective, the rest never arrive).
* **TL009** — accountant discipline: every ``ACCOUNTANT.set(subsystem,
  ...)`` ledger registration needs a ``drop``/``drop_deferred`` for the
  same subsystem somewhere in the lint target, pinning the PR-10
  ledger-leak class as a lint instead of a review habit.

All four consume the project-wide call graph (:mod:`.project`): the
seeds live in one module (``gluon/fused_step.py``, ``serve/engine.py``)
and the flagged code in another (``parallel/collectives.py``,
``models/decoding.py``) — exactly the seams the module-local engine
could not see.
"""
from __future__ import annotations

import ast

from .callgraph import _JAXISH_ROOTS, dotted, iter_own
from .core import Finding
from .rules_trace import _arrayish_locals, _traced_branch_value

__all__ = ["build_state", "check_module"]

# collective name -> positional index of the axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "psum_scatter": 1, "ppermute": 1, "all_to_all": 1, "pshuffle": 1,
    "pbroadcast": 1, "axis_index": 0,
}
# entry points whose axis_name= kwarg BINDS an axis (vs the collectives,
# where axis_name= is a use)
_AXIS_BINDERS = {"pmap", "soft_pmap", "xmap", "vmap"}
_AXISH_PARAM = ("axis", "axis_name", "batch_axis")
_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time",
             "time_ns", "perf_counter_ns", "monotonic_ns"}
_PYRANDOM_FNS = {"random", "randint", "randrange", "choice", "choices",
                 "shuffle", "sample", "uniform", "getrandbits",
                 "randbytes", "gauss", "normalvariate"}
_SHARDING_KWARGS = {"donate_argnums", "in_shardings", "out_shardings",
                    "in_specs", "out_specs", "static_argnums"}


class SharedState:
    """Project-wide facts computed once and shared by every per-module
    pass (and, under ``--jobs``, inherited by every worker)."""

    __slots__ = ("mesh_axes", "vocab", "acct_drops", "module_lock_defs",
                 "locks", "instances")

    def __init__(self):
        self.mesh_axes = {}   # axis -> "path:line" of a binding mesh def
        self.vocab = {}       # axis -> site (mesh defs + param defaults)
        self.acct_drops = set()   # subsystems with a release path
        # lock analysis shared by TL004/TL012/TL013 (see locks.py):
        self.module_lock_defs = {}   # (modname, varname) -> ctor name
        self.locks = {}              # id(module) -> LockAnalysis
        # module-level singleton bindings (`ACCOUNTANT =
        # MemoryAccountant()`), so TL012 can resolve `ACCOUNTANT.drop`
        # through the instance to the class's method
        self.instances = {}          # (modname, varname) -> (mod, ClassDef)


# --------------------------------------------------------------------- #
# shared detection helpers
# --------------------------------------------------------------------- #

def _jaxish_root(root, module):
    return (root in _JAXISH_ROOTS or root in module.jax_aliases
            or root in module.jnp_aliases)


def _collective_name(call, module, imports):
    """The collective's name when ``call`` is one, else None."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    if last not in _COLLECTIVES:
        return None
    if len(parts) == 1:
        tgt = imports.from_imports.get(last)
        return last if tgt and tgt[0].split(".")[0] == "jax" else None
    return last if _jaxish_root(parts[0], module) else None


def _is_spec_ctor(call, imports):
    d = dotted(call.func)
    if d is None:
        return False
    last = d.split(".")[-1]
    if last == "PartitionSpec":
        return True
    if last == "P":
        tgt = imports.from_imports.get("P")
        return bool(tgt) and tgt[1] in ("P", "PartitionSpec")
    return False


def _str_elts(expr):
    """All string constants in a constant/tuple/list expression, or
    None when anything non-constant appears."""
    if isinstance(expr, ast.Constant):
        return [expr.value] if isinstance(expr.value, str) else []
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            sub = _str_elts(e)
            if sub is None:
                return None
            out.extend(sub)
        return out
    return None


def _resolve_axis_expr(expr, scopes):
    """(values, how) for an axis argument: ``how`` is 'literal' (string
    at the call site), 'param' (resolved through an enclosing function
    parameter's default), or 'dynamic' (caller-supplied, not checkable).
    """
    vals = _str_elts(expr)
    if vals is not None:
        return vals, "literal"
    if isinstance(expr, ast.Name):
        for scope in reversed(scopes):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            a = scope.args
            pos = a.posonlyargs + a.args
            defaults = [None] * (len(pos) - len(a.defaults)) \
                + list(a.defaults)
            for arg, dflt in list(zip(pos, defaults)) + \
                    list(zip(a.kwonlyargs, a.kw_defaults)):
                if arg.arg != expr.id:
                    continue
                if isinstance(dflt, ast.Constant) and \
                        isinstance(dflt.value, str):
                    return [dflt.value], "param"
                return [], "dynamic"
    return [], "dynamic"


# --------------------------------------------------------------------- #
# project-wide state: axis definitions + accountant release paths
# --------------------------------------------------------------------- #

def build_state(project):
    from .locks import build_locks, is_lock_ctor

    st = SharedState()
    # module-level lock globals + singleton instance bindings, needed
    # before the per-module lock analyses can resolve imported locks
    for m in project.modules:
        modname = project.names[id(m)] or m.path
        for stmt in m.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            names = [t.id for t in stmt.targets
                     if isinstance(t, ast.Name)]
            if not names:
                continue
            ctor = is_lock_ctor(stmt.value)
            if ctor:
                for n in names:
                    st.module_lock_defs[(modname, n)] = ctor
            elif isinstance(stmt.value, ast.Call):
                ckey = project._resolve_class_ref(m, stmt.value.func)
                if ckey is not None:
                    hit = project._class_key.get(ckey)
                    if hit is not None:
                        for n in names:
                            st.instances[(modname, n)] = hit
    for m in project.modules:
        st.locks[id(m)] = build_locks(m, project.imports[id(m)],
                                      st.module_lock_defs)
    for m in project.modules:
        idx = project.index(m)
        for call, _scopes in idx.calls:
            d = dotted(call.func)
            last = d.split(".")[-1] if d else None
            site = f"{m.path}:{call.lineno}"
            if last == "Mesh" and len(call.args) >= 2:
                for ax in _str_elts(call.args[1]) or []:
                    st.mesh_axes.setdefault(ax, site)
            if last == "make_mesh":
                axes = call.args[0] if call.args else next(
                    (k.value for k in call.keywords if k.arg == "axes"),
                    None)
                if isinstance(axes, ast.Dict):
                    for k in axes.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            st.mesh_axes.setdefault(k.value, site)
                elif isinstance(axes, (ast.List, ast.Tuple)):
                    for e in axes.elts:
                        if isinstance(e, (ast.Tuple, ast.List)) and \
                                e.elts and \
                                isinstance(e.elts[0], ast.Constant) and \
                                isinstance(e.elts[0].value, str):
                            st.mesh_axes.setdefault(
                                e.elts[0].value, site)
                # jax.make_mesh(axis_shapes, axis_names) style
                if len(call.args) >= 2:
                    for ax in _str_elts(call.args[1]) or []:
                        st.mesh_axes.setdefault(ax, site)
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    for ax in _str_elts(kw.value) or []:
                        st.mesh_axes.setdefault(ax, site)
                elif kw.arg == "axis_name" and last in _AXIS_BINDERS:
                    for ax in _str_elts(kw.value) or []:
                        st.mesh_axes.setdefault(ax, site)
        for info in idx.functions:
            a = info.node.args
            pos = a.posonlyargs + a.args
            defaults = [None] * (len(pos) - len(a.defaults)) \
                + list(a.defaults)
            for arg, dflt in list(zip(pos, defaults)) + \
                    list(zip(a.kwonlyargs, a.kw_defaults)):
                if (arg.arg in _AXISH_PARAM
                        or arg.arg.endswith("_axis")) and \
                        isinstance(dflt, ast.Constant) and \
                        isinstance(dflt.value, str):
                    st.vocab.setdefault(
                        dflt.value, f"{m.path}:{info.node.lineno}")
        # accountant release paths (project-wide: the drop may live in
        # another module than the set — Trainer vs FusedStep)
        for n in ast.walk(m.tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("drop", "drop_deferred", "release"):
                recv = dotted(n.func.value)
                if recv and recv.split(".")[-1] == "ACCOUNTANT" and \
                        n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    st.acct_drops.add(n.args[0].value)
    st.vocab.update(st.mesh_axes)
    return st


# --------------------------------------------------------------------- #
# per-module checks
# --------------------------------------------------------------------- #

def check_module(project, state, module):
    imports = project.imports[id(module)]
    findings = []
    findings.extend(_tl006(project, state, module, imports))
    findings.extend(_tl007(project, module, imports))
    findings.extend(_tl008(project, module, imports))
    findings.extend(_tl009(state, module))
    return findings


# -- TL006: axis/mesh discipline --------------------------------------- #

def _known_axes(state):
    return ", ".join(sorted(state.vocab)) or "none defined"


def _judge_axis(state, module, node, val, how, what):
    if val in state.mesh_axes:
        return None
    if how == "param":
        # a caller-supplied value rides the parameter; its default is
        # only checked against the project's axis vocabulary
        if val in state.vocab:
            return None
        sev, tail = "warn", (
            "it is a parameter default no mesh in the lint target "
            "defines, so only a caller-supplied mesh can bind it")
    elif val in state.vocab:
        sev, tail = "warn", (
            "no mesh in the lint target defines it (it appears only as "
            "a default axis parameter), so only a caller-supplied mesh "
            "can bind it — conditionally bound")
    else:
        sev, tail = "error", (
            f"known axes: {_known_axes(state)}; an unbound collective "
            "axis fails to compile, and an unbound PartitionSpec axis "
            "silently replicates the dim")
    return Finding(
        "TL006", module.path, node.lineno, node.col_offset,
        f"{what} axis {val!r} is not bound by any mesh or shard_map "
        f"axis definition reachable in the lint target — {tail}",
        severity=sev)


def _tl006(project, state, module, imports):
    out = []
    idx = project.index(module)
    for call, scopes in idx.calls:
        name = _collective_name(call, module, imports)
        axis_exprs = []
        if name is not None:
            # only axis_name= carries the mesh axis; the gather family's
            # axis= kwarg is the INTEGER array dimension, so it must not
            # shadow the positional axis-name argument
            kw = next((k.value for k in call.keywords
                       if k.arg == "axis_name"), None)
            if kw is not None:
                axis_exprs.append(kw)
            else:
                p = _COLLECTIVES[name]
                if p < len(call.args):
                    axis_exprs.append(call.args[p])
            what = f"collective `{name}`"
        else:
            d = dotted(call.func)
            if d and d.split(".")[-1] == "partial" and call.args:
                inner = dotted(call.args[0])
                if inner and inner.split(".")[-1] in _COLLECTIVES and \
                        _jaxish_root(inner.split(".")[0], module):
                    what = f"collective `{inner.split('.')[-1]}`"
                    axis_exprs.extend(
                        k.value for k in call.keywords
                        if k.arg == "axis_name")
                else:
                    continue
            elif _is_spec_ctor(call, imports):
                what = "PartitionSpec"
                axis_exprs.extend(call.args)
                axis_exprs.extend(k.value for k in call.keywords
                                  if k.arg is not None)
            else:
                continue
        for expr in axis_exprs:
            vals, how = _resolve_axis_expr(expr, scopes)
            for v in vals:
                f = _judge_axis(state, module, expr, v, how, what)
                if f is not None:
                    out.append(f)
    return out


# -- TL007: cross-host trace divergence -------------------------------- #

# modules whose from-imports we expand when classifying host reads —
# restricting to this set keeps a project module that merely shares a
# local name from being mistaken for the stdlib
_HOST_STATE_ROOTS = {"os", "time", "random", "numpy", "jax", "secrets",
                     "uuid"}


def _host_divergent_call(call, module, imports):
    """Message when ``call`` reads host-local state that differs across
    pod processes, else None.  Resolves both module aliases
    (``import os`` → ``os.getenv``) and from-imports
    (``from os import getenv`` → ``getenv``)."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    # expand a from-imported head to its source module so `getenv(...)`
    # and `perf_counter(...)` classify the same as the dotted forms; a
    # head bound to anything ELSE (e.g. the repo's `from .. import
    # random`) is known-not-stdlib and never classified
    head = imports.from_imports.get(parts[0])
    if head is not None:
        if head[0].split(".")[0] not in _HOST_STATE_ROOTS:
            return None
        parts = head[0].split(".") + [head[1]] + parts[1:]
    else:
        # `import os` / `import time as _time` style: normalize the
        # alias back to the real module name
        tgt = imports.mod_aliases.get(parts[0])
        if tgt is not None:
            if tgt.split(".")[0] not in _HOST_STATE_ROOTS:
                return None
            parts = tgt.split(".") + parts[1:]
    root, last = parts[0], parts[-1]
    if last in ("process_index", "process_count") and \
            (root == "jax" or parts[0] in module.jax_aliases):
        return (f"`{d}()` pins the host id into the trace — each host "
                "compiles a different program and every collective in "
                "it can deadlock the pod; hoist it to trace time (cache "
                "key / operand) or use lax.axis_index over a mesh axis")
    if ("environ" in parts[:-1] and last in ("get", "__getitem__")) or \
            (root == "os" and last in ("getenv", "environ")):
        return ("`os.environ` read inside traced code — per-host "
                "environment differences compile different programs on "
                "different hosts; read the hatch at trace time and "
                "close over the value")
    if root == "os" and last == "urandom":
        return ("`os.urandom` inside traced code — host entropy burned "
                "into the trace diverges across hosts")
    if root == "time" and last in _TIME_FNS:
        return (f"`{d}()` inside traced code — hosts trace at different "
                "wall-clock times, so anything derived from it (shapes, "
                "seeds, donation choices) diverges per host")
    if last in _PYRANDOM_FNS and (
            root == "random"
            or root in ("secrets",)
            or (root in module.np_aliases and "random" in parts)
            or (root == "numpy" and "random" in parts)):
        return (f"`{d}()` is host RNG inside traced code — per-host "
                "draws compile divergent programs; use jax.random with "
                "a key operand shared by all hosts")
    return None


def _environ_subscript(node):
    if isinstance(node, ast.Subscript):
        d = dotted(node.value)
        return bool(d) and d.endswith("environ")
    return False


def _order_hazard(expr):
    """Reason when ``expr`` derives ordering from a set or ``id()`` —
    per-process hash seeds make both differ across hosts."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            if d == "sorted" and not any(
                    k.arg == "key" and dotted(k.value) == "id"
                    for k in n.keywords):
                continue  # sorted(...) re-establishes a host-stable order
            if d == "sorted":
                return "`sorted(..., key=id)` (identity order)"
            if d == "set" or d == "frozenset":
                return f"`{d}(...)` iteration order"
            if d == "id":
                return "`id(...)`-derived ordering"
        if isinstance(n, (ast.Set, ast.SetComp)):
            return "set iteration order"
        if isinstance(n, (ast.ListComp, ast.GeneratorExp)):
            for gen in n.generators:
                it = gen.iter
                if isinstance(it, (ast.Set, ast.SetComp)) or (
                        isinstance(it, ast.Call)
                        and dotted(it.func) in ("set", "frozenset")):
                    return "set iteration order"
        stack.extend(ast.iter_child_nodes(n))
    return None


def _divergent_sources(module, imports, fn_node):
    """Divergent-read nodes in one function, plus the local names their
    values taint (fixed point over assignment chains)."""
    sources = {}   # id(node) -> (node, msg)
    for n in iter_own(fn_node):
        msg = None
        if isinstance(n, ast.Call):
            msg = _host_divergent_call(n, module, imports)
        elif _environ_subscript(n):
            msg = ("`os.environ[...]` read inside traced code — "
                   "per-host environment differences compile different "
                   "programs on different hosts")
        if msg:
            sources[id(n)] = (n, msg)
    tainted = {}   # local name -> (source node, msg)

    def origin(expr):
        for sub in ast.walk(expr):
            if id(sub) in sources:
                return sources[id(sub)]
            if isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Load) and sub.id in tainted:
                return tainted[sub.id]
        return None

    for _ in range(2):
        for n in iter_own(fn_node):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                hit = origin(n.value)
                if hit is None:
                    continue
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            tainted.setdefault(leaf.id, hit)
    return origin


def _identity_only_test(test):
    """`x is None`-style tests resolve host-uniformly at trace time."""
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


def _tl007(project, module, imports):
    out = []
    # host-divergent reads whose value FEEDS the trace: reaches a
    # return, a jax/jnp call argument, or a python branch test.  Reads
    # that stay host-side (profiler clocks, logging) are not divergence.
    for info, reason in project.traced_in(module):
        origin = _divergent_sources(module, imports, info.node)
        hits = {}

        def sink(expr, via):
            found = origin(expr)
            if found is not None:
                node, msg = found
                hits.setdefault(id(node), (node, msg, via))

        for n in iter_own(info.node):
            if isinstance(n, ast.Return) and n.value is not None:
                sink(n.value, "returned from the traced function")
            elif isinstance(n, (ast.If, ast.While)):
                if not _identity_only_test(n.test):
                    sink(n.test, "branches the python trace")
            elif isinstance(n, ast.IfExp):
                sink(n.test, "branches the python trace")
            elif isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and _jaxish_root(d.split(".")[0], module):
                    for a in list(n.args) + [k.value for k in n.keywords]:
                        sink(a, f"feeds `{d}(...)`")
        for node, msg, via in sorted(hits.values(),
                                     key=lambda h: h[0].lineno):
            out.append(Finding(
                "TL007", module.path, node.lineno, node.col_offset,
                f"{msg} — inside `{info.qualname}`, which is traced "
                f"({reason}); the value {via}, so each host can "
                "compile a different program"))
    # nondeterministic ordering feeding shardings / donation
    idx = project.index(module)
    for call, _scopes in idx.calls:
        d = dotted(call.func)
        last = d.split(".")[-1] if d else None
        if last in ("jit", "pjit", "shard_map"):
            for kw in call.keywords:
                if kw.arg in _SHARDING_KWARGS:
                    why = _order_hazard(kw.value)
                    if why:
                        out.append(Finding(
                            "TL007", module.path, kw.value.lineno,
                            kw.value.col_offset,
                            f"`{kw.arg}=` derived from {why} — set/id "
                            "order depends on the per-process hash "
                            "seed, so hosts disagree on which operands "
                            "are donated/sharded and compile different "
                            "programs; sort by a stable key instead"))
        elif _is_spec_ctor(call, imports):
            for arg in list(call.args) + [k.value for k in call.keywords]:
                why = _order_hazard(arg)
                if why:
                    out.append(Finding(
                        "TL007", module.path, arg.lineno, arg.col_offset,
                        f"PartitionSpec axes derived from {why} — hosts "
                        "disagree on the axis order and shard the same "
                        "array differently; use a stable sequence"))
    return out


# -- TL008: conditional collectives ------------------------------------ #

def _branch_reason(module, test, arrayish, imports):
    """Why a branch test is unsafe to gate a collective on, or None."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            msg = _host_divergent_call(n, module, imports)
            if msg:
                return f"host-dependent (`{dotted(n.func)}`)"
        elif _environ_subscript(n):
            return "host-dependent (`os.environ[...]`)"
    val = _traced_branch_value(module, test, arrayish)
    if val:
        return f"data-dependent (`{val}`)"
    return None


def _tl008(project, module, imports):
    out = []
    for info, reason in project.traced_in(module):
        arrayish = _arrayish_locals(module, info.node)

        def walk(node, why):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, (ast.If, ast.While)):
                    sub = _branch_reason(module, child.test, arrayish,
                                         imports) or why
                    walk(child.test, why)
                    for b in child.body + child.orelse:
                        walk(b, sub)
                    continue
                if isinstance(child, ast.IfExp):
                    sub = _branch_reason(module, child.test, arrayish,
                                         imports) or why
                    walk(child.test, why)
                    walk(child.body, sub)
                    walk(child.orelse, sub)
                    continue
                if why and isinstance(child, ast.Call):
                    name = _collective_name(child, module, imports)
                    if name is not None:
                        out.append(Finding(
                            "TL008", module.path, child.lineno,
                            child.col_offset,
                            f"collective `{name}` issued under a {why} "
                            f"branch inside traced `{info.qualname}` "
                            f"({reason}) — shards/hosts that skip the "
                            "branch never join the collective and the "
                            "rest wait forever (the canonical SPMD "
                            "hang); issue it unconditionally and mask, "
                            "or use lax.cond with a replicated "
                            "predicate"))
                walk(child, why)

        walk(info.node, None)
    return out


# -- TL009: accountant discipline -------------------------------------- #

def _tl009(state, module):
    out = []
    for n in ast.walk(module.tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "set"):
            continue
        recv = dotted(n.func.value)
        if not recv or recv.split(".")[-1] != "ACCOUNTANT":
            continue
        if not (n.args and isinstance(n.args[0], ast.Constant)
                and isinstance(n.args[0].value, str)):
            continue  # dynamic subsystem: not statically checkable
        cat = n.args[0].value
        if cat not in state.acct_drops:
            out.append(Finding(
                "TL009", module.path, n.lineno, n.col_offset,
                f"`ACCOUNTANT.set({cat!r}, ...)` has no "
                f"`ACCOUNTANT.drop`/`drop_deferred` for {cat!r} "
                "anywhere in the lint target — an unreleased ledger "
                "entry reads as a reconcile() delta<0 leak forever "
                "(the PR-10 ledger-leak class); add the release path "
                "(see FusedStep.release_accounting) or suppress with "
                "a justification"))
    return out
