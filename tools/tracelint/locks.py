"""Shared held-lock region analysis — computed ONCE per module and
consumed by three rule families:

* TL004 (``rules_threading``) — unlocked mutations of lock-protected
  state and lock-order inversions;
* TL012 (``rules_runtime``) — lock acquisitions reachable from GC
  finalizers;
* TL013 (``rules_runtime``) — user callbacks invoked while a lock is
  held.

One AST walk per function records, with the held-lock stack threaded
through it: every shared-state mutation, every call site, every lock
acquisition (``with`` items and bare ``.acquire()``), and every nested
acquisition pair.  Lock *keys* carry their scope kind so each rule sees
exactly the locks it reasons about:

* ``class``  — ``self._lock``-family attributes assigned a
  ``threading.Lock/RLock/Condition/...`` inside the class's methods;
* ``module`` — module-level ``_lock = threading.Lock()`` globals;
* ``ext``    — module-level locks *imported from another project
  module* (``from ..parameter import _TRACE_LOCK``).  TL013 treats
  them as held; TL004 deliberately ignores them so its findings stay
  scoped to the module that owns the lock (the pre-v3 semantics).

Nested-acquisition pairs are recorded per scope kind (the innermost
held lock *of the same kind*), which reproduces TL004's historical
two-pass behavior exactly.
"""
from __future__ import annotations

import ast

from .callgraph import dotted, iter_own

__all__ = ["LockAnalysis", "Mutation", "build_locks", "LOCK_CTORS"]

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}
_MUTATORS = {"append", "appendleft", "pop", "popleft", "clear", "extend",
             "extendleft", "remove", "insert", "add", "discard", "update",
             "setdefault", "popitem", "sort", "reverse"}


def is_lock_ctor(expr):
    if not isinstance(expr, ast.Call):
        return None
    d = dotted(expr.func)
    if d and d.split(".")[-1] in LOCK_CTORS:
        return d.split(".")[-1]
    return None


def _self_attr(expr):
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


class Mutation:
    __slots__ = ("field", "line", "col", "held", "method", "scope")

    def __init__(self, field, line, col, held, method, scope):
        self.field = field
        self.line = line
        self.col = col
        self.held = held       # lock keys of this mutation's own scope
        self.method = method
        self.scope = scope     # id(ClassDef) or "module"


class LockAnalysis:
    """Per-module result; see the module docstring for the shape."""

    __slots__ = ("module", "class_locks", "module_locks", "class_muts",
                 "module_muts", "acquisitions", "fn_calls", "fn_acquires",
                 "lock_ctor")

    def __init__(self, module):
        self.module = module
        self.class_locks = {}    # id(ClassDef) -> {attr: ctor}
        self.module_locks = {}   # name -> ctor
        self.class_muts = {}     # id(ClassDef) -> (ClassDef, [Mutation])
        self.module_muts = []    # [Mutation]
        self.acquisitions = []   # (outer key, inner key, line) same-kind
        self.fn_calls = {}       # id(fn) -> [(Call, held full-key tuple)]
        self.fn_acquires = {}    # id(fn) -> [(kind, key, ctor, node)]
        self.lock_ctor = {}      # full key -> ctor name


def _class_methods(cls):
    """Methods + their nested closures, excluding nested ClassDefs
    (an inner class owns its own lock discipline)."""
    out, stack = [], list(ast.iter_child_nodes(cls))
    while stack:
        n = stack.pop()
        if isinstance(n, ast.ClassDef):
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _class_fields(node, lock_attrs):
    """Mutated self-field names in one statement (TL004's class scope)."""
    out = []
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            attr = _self_attr(t)
            if attr and attr not in lock_attrs:
                out.append(attr)
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr:
                    out.append(attr)
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    attr = _self_attr(e)
                    if attr and attr not in lock_attrs:
                        out.append(attr)
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATORS:
        attr = _self_attr(node.func.value)
        if attr:
            out.append(attr)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            attr = _self_attr(t)
            if attr:
                out.append(attr)
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr:
                    out.append(attr)
    return out


def _module_fields(node, mod_names):
    """Mutated module-global names in one statement (TL004's module
    scope: subscript stores, container mutators, dels)."""
    out = []
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id in mod_names:
                out.append(t.value.id)
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATORS and \
            isinstance(node.func.value, ast.Name) and \
            node.func.value.id in mod_names:
        out.append(node.func.value.id)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id in mod_names:
                out.append(t.value.id)
    return out


def build_locks(module, imports, module_lock_defs):
    """One-pass lock analysis of ``module``.

    ``imports`` is the module's :class:`project.Imports`;
    ``module_lock_defs`` maps ``(modname, varname) -> ctor`` for every
    module-level lock in the project (for the ``ext`` scope kind).
    """
    la = LockAnalysis(module)
    tree = module.tree

    # -- lock definitions ------------------------------------------------- #
    owner = {}           # id(fn node) -> ClassDef
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            attrs = {}
            for m in _class_methods(node):
                owner.setdefault(id(m), node)
                for n in iter_own(m):
                    if isinstance(n, ast.Assign):
                        ctor = is_lock_ctor(n.value)
                        if ctor:
                            for t in n.targets:
                                attr = _self_attr(t)
                                if attr:
                                    attrs[attr] = ctor
            if attrs:
                la.class_locks[id(node)] = attrs
                la.class_muts[id(node)] = (node, [])

    mod_names = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            names = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            mod_names.update(names)
            ctor = is_lock_ctor(stmt.value)
            if ctor:
                for n in names:
                    la.module_locks[n] = ctor

    def ext_lock(name):
        """(key, ctor) for a name bound to another project module's
        lock global, else None."""
        if name in imports.from_imports:
            tgt, remote = imports.from_imports[name]
            ctor = module_lock_defs.get((tgt, remote))
            if ctor:
                return f"{tgt}:{remote}", ctor
        return None

    def classify(expr, cls):
        """(kind, key, ctor) when ``expr`` names a known lock."""
        attr = _self_attr(expr)
        lock_attrs = la.class_locks.get(id(cls), {}) if cls else {}
        if attr and attr in lock_attrs:
            return "class", f"{cls.name}.{attr}", lock_attrs[attr]
        d = dotted(expr.func) if isinstance(expr, ast.Call) else None
        if d and d.startswith("self.") and cls is not None:
            parts = d.split(".")
            if len(parts) >= 2 and parts[1] in lock_attrs:
                return ("class", f"{cls.name}.{parts[1]}",
                        lock_attrs[parts[1]])
        d = dotted(expr)
        if d in la.module_locks:
            return "module", f"{module.path}:{d}", la.module_locks[d]
        if d is not None and "." not in d:
            hit = ext_lock(d)
            if hit:
                return "ext", hit[0], hit[1]
        elif d is not None:
            parts = d.split(".")
            tgt = imports.mod_aliases.get(parts[0])
            if tgt is not None and len(parts) == 2:
                ctor = module_lock_defs.get((tgt, parts[1]))
                if ctor:
                    return "ext", f"{tgt}:{parts[1]}", ctor
        return None

    # -- the one walk per function ---------------------------------------- #
    def walk_fn(fn):
        cls = owner.get(id(fn))
        lock_attrs = la.class_locks.get(id(cls), {}) if cls else {}
        calls, acquires = [], []
        cmuts = la.class_muts.get(id(cls), (None, []))[1] \
            if cls is not None and id(cls) in la.class_locks else None
        want_mod = bool(la.module_locks)

        def walk(node, held):
            # held: tuple of (kind, key)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        hit = classify(item.context_expr, cls)
                        if hit is not None:
                            kind, key, ctor = hit
                            la.lock_ctor.setdefault(key, ctor)
                            same = [k for kd, k in new_held if kd == kind]
                            if same and kind != "ext":
                                la.acquisitions.append(
                                    (same[-1], key, child.lineno))
                            acquires.append((kind, key, ctor, child))
                            new_held = new_held + ((kind, key),)
                if isinstance(child, ast.Call):
                    calls.append((child, new_held))
                    if isinstance(child.func, ast.Attribute) and \
                            child.func.attr == "acquire":
                        hit = classify(child.func.value, cls)
                        if hit is not None:
                            kind, key, ctor = hit
                            la.lock_ctor.setdefault(key, ctor)
                            acquires.append((kind, key, ctor, child))
                if cmuts is not None:
                    for field in _class_fields(child, lock_attrs):
                        cmuts.append(Mutation(
                            field, child.lineno,
                            getattr(child, "col_offset", 0),
                            tuple(k for kd, k in new_held
                                  if kd == "class"),
                            fn.name, id(cls)))
                if want_mod:
                    for field in _module_fields(child, mod_names):
                        la.module_muts.append(Mutation(
                            field, child.lineno,
                            getattr(child, "col_offset", 0),
                            tuple(k for kd, k in new_held
                                  if kd == "module"),
                            fn.name, "module"))
                walk(child, new_held)

        walk(fn, ())
        if calls:
            la.fn_calls[id(fn)] = calls
        if acquires:
            la.fn_acquires[id(fn)] = acquires

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_fn(node)
    return la
