"""Per-module scope index + traced-seed recognition.

"Traced" code is anything jax re-executes abstractly: bodies passed to
``jax.jit`` / ``value_and_grad`` / ``vjp`` / ``pallas_call`` / control-flow
combinators, functions decorated with jit, and functions that open a
``trace_scope`` (the repo's CachedOp trace discipline — their body runs
under an active jax trace by construction).  Bare-name calls resolve
lexically through nested scopes; ``self.method`` calls resolve within
the enclosing class, its module-local ancestors and descendants (the
optimizer registry pattern: ``Optimizer._apply_one`` calls
``self._update_rule``, overridden by every registered subclass).

Seed propagation across modules — import resolution, re-export chasing,
project-wide class families — lives in :mod:`.project`; this module
stays the single-file building block it composes (and the fallback when
an import cannot be resolved).
"""
from __future__ import annotations

import ast

# jax entry points whose function-valued arguments are (re)traced
TRACING_FNS = {
    "jit", "pjit", "value_and_grad", "grad", "vjp", "jvp", "linearize",
    "checkpoint", "remat", "eval_shape", "make_jaxpr", "vmap", "pmap",
    "pallas_call", "shard_map", "scan", "while_loop", "cond", "fori_loop",
    "switch", "associative_scan", "custom_vjp", "custom_jvp",
}
# bare (un-dotted) names we accept as tracing entries without an alias
_BARE_OK = {"jit", "pjit", "pallas_call", "shard_map", "checkpoint",
            "value_and_grad"}
_JAXISH_ROOTS = {"jax", "jnp", "lax", "pl", "pltpu", "plgpu"}


def dotted(expr):
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def iter_own(node):
    """Walk a function body without descending into nested function /
    class definitions (lambdas and comprehensions DO run as part of the
    enclosing trace, so they are included)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class FuncInfo:
    __slots__ = ("node", "name", "qualname", "scopes", "cls")

    def __init__(self, node, qualname, scopes, cls):
        self.node = node
        self.name = node.name
        self.qualname = qualname
        self.scopes = scopes      # enclosing scope nodes, outermost first
        self.cls = cls            # innermost enclosing ClassDef or None

    def __repr__(self):
        return f"<FuncInfo {self.qualname}>"


class Index:
    """Scope-aware function/class/call index of one module."""

    def __init__(self, module):
        self.module = module
        self.functions = []               # all FuncInfo
        self.by_node = {}                 # id(fn node) -> FuncInfo
        self.scope_funcs = {}             # id(scope node) -> {name: FuncInfo}
        self.classes = {}                 # class name -> ClassDef
        self.class_methods = {}           # id(ClassDef) -> {name: FuncInfo}
        self.calls = []                   # (Call node, scope stack tuple)
        self._subclasses = None
        self._build(module.tree, (module.tree,), None, "")

    def _build(self, scope_node, scopes, cls, prefix):
        """Walk one scope: register defs (even when nested inside
        if/try statements — they still belong to this scope), index
        calls, recurse into each def/class with an extended stack."""
        stack = list(ast.iter_child_nodes(scope_node))
        while stack:
            child = stack.pop()
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + child.name
                info = FuncInfo(child, qual, scopes, cls)
                self.functions.append(info)
                self.by_node[id(child)] = info
                self.scope_funcs.setdefault(id(scopes[-1]), {})[
                    child.name] = info
                if cls is not None and scopes[-1] is cls:
                    self.class_methods.setdefault(id(cls), {})[
                        child.name] = info
                self._build(child, scopes + (child,), cls, qual + ".")
            elif isinstance(child, ast.ClassDef):
                self.classes[child.name] = child
                self._build(child, scopes + (child,), child,
                            prefix + child.name + ".")
            else:
                if isinstance(child, ast.Call):
                    self.calls.append((child, scopes))
                stack.extend(ast.iter_child_nodes(child))

    # -- resolution ------------------------------------------------------ #
    def resolve_name(self, name, scopes):
        """Lexical lookup of a bare function name: innermost enclosing
        function scope outward to module (class bodies are not lexical
        scopes in python and are skipped)."""
        for scope in reversed(scopes):
            if isinstance(scope, ast.ClassDef):
                continue
            info = self.scope_funcs.get(id(scope), {}).get(name)
            if info is not None:
                return info
        return None

    def _class_family(self, cls):
        """The class plus its module-local ancestors and descendants."""
        if self._subclasses is None:
            self._subclasses = {}
            for name, node in self.classes.items():
                for base in node.bases:
                    b = dotted(base)
                    if b and b.split(".")[-1] in self.classes:
                        self._subclasses.setdefault(
                            b.split(".")[-1], []).append(name)
        family, work = {cls.name}, [cls.name]
        while work:  # descendants
            for sub in self._subclasses.get(work.pop(), []):
                if sub not in family:
                    family.add(sub)
                    work.append(sub)
        work = [cls.name]
        while work:  # ancestors
            node = self.classes.get(work.pop())
            if node is None:
                continue
            for base in node.bases:
                b = dotted(base)
                if b:
                    b = b.split(".")[-1]
                    if b in self.classes and b not in family:
                        family.add(b)
                        work.append(b)
        return [self.classes[n] for n in family]

    def resolve_self_method(self, attr, scopes):
        """``self.attr(...)`` — every matching method def in the
        enclosing class's module-local family."""
        cls = None
        for scope in reversed(scopes):
            if isinstance(scope, ast.ClassDef):
                cls = scope
                break
        if cls is None:
            return []
        out = []
        for c in self._class_family(cls):
            info = self.class_methods.get(id(c), {}).get(attr)
            if info is not None:
                out.append(info)
        return out

    def resolve_call(self, call, scopes):
        """FuncInfos a call statically resolves to (possibly empty)."""
        func = call.func
        if isinstance(func, ast.Name):
            info = self.resolve_name(func.id, scopes)
            return [info] if info else []
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            return self.resolve_self_method(func.attr, scopes)
        return []


def is_tracing_entry(call, module):
    """True when ``call`` is a jax entry point that traces its
    function-valued arguments."""
    d = dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    last = parts[-1]
    if last not in TRACING_FNS:
        return False
    if len(parts) == 1:
        return last in _BARE_OK
    root = parts[0]
    return (root in _JAXISH_ROOTS or root in module.jax_aliases
            or root in module.jnp_aliases)


def _is_jit_decorator(dec, module):
    d = dotted(dec)
    if d and d.split(".")[-1] in ("jit", "pjit"):
        return True
    if isinstance(dec, ast.Call):
        dd = dotted(dec.func)
        if dd and dd.split(".")[-1] in ("jit", "pjit"):
            return True
        if dd and dd.split(".")[-1] == "partial" and dec.args:
            inner = dotted(dec.args[0])
            if inner and inner.split(".")[-1] in ("jit", "pjit"):
                return True
    return False


def _opens_trace_scope(fn_node):
    for n in iter_own(fn_node):
        if isinstance(n, ast.With):
            for item in n.items:
                if isinstance(item.context_expr, ast.Call):
                    d = dotted(item.context_expr.func)
                    if d and d.split(".")[-1] == "trace_scope":
                        return True
    return False


