"""TL011–TL015 — the concurrency & runtime-contract family.

These are the bug classes the fault-tolerant runtime (supervised
launch, serve deadlines/watchdog, finalizer-driven ledger drops) was
hand-reviewed for across PRs 7/10/13 — each one now a lint instead of
a review round:

* **TL011 clock discipline** — a ``time.time()`` value that flows into
  deadline/timeout arithmetic (compared against a ``*deadline*`` /
  ``*timeout*`` name, added to one, passed as a ``timeout=`` argument,
  or stored into a deadline-named field) is an NTP hazard: a wall-clock
  step turns the budget into an instant or an infinite timeout.  Use
  ``time.monotonic()``.  Pure elapsed *logging* (``t0 = time.time();
  ...; log(time.time() - t0)`` — the ``event_handler.py`` /
  ``callback.py`` / telemetry-timestamp pattern) stays clean: the rule
  fires on the deadline-shaped *use*, not on the read.
* **TL012 finalizer lock safety** — a ``threading.Lock``/``RLock``
  acquisition reachable (project call graph, including module-level
  singletons like ``ACCOUNTANT``) from a ``__del__`` or
  ``weakref.finalize`` callback: a GC pass can run the finalizer
  inside a thread that already holds the lock and self-deadlock (the
  PR-10 accountant bug).  Route finalizer-side cleanup through a
  lock-free deferral (the ``drop_deferred`` pattern) — or suppress
  with the reentrancy argument where the lock is an ``RLock`` held
  only by short non-blocking sections.
* **TL013 callback-under-lock** — a user-supplied callable (``on_*`` /
  ``*callback*`` / ``*hook*`` attributes, names, or parameters that
  don't resolve to a project-internal function) invoked while a
  ``self._lock``-family lock is held: the callback can re-enter the
  owner (``submit()`` from ``on_token``) and deadlock, or block every
  other client of the lock (the ``_push``-outside-``_lock`` discipline
  PR 7 established).
* **TL014 thread lifecycle** — a ``threading.Thread`` started by a
  class must be ``daemon=True`` or joined on some close/stop/teardown
  path of the class family; and a class that owns a producer thread
  and a blocking ``queue.get()`` must have a poison-pill wakeup (a
  ``put(None)`` / sentinel put) outside the thread's own target, so a
  parked consumer wakes when the producer dies (the ``_END`` pill
  pattern).
* **TL015 telemetry schema drift** — ``emit(kind)`` literals and
  registry counter/gauge/histogram names must appear in
  ``docs/TELEMETRY.md``'s schema tables and vice versa, and
  ``fault_point("site")`` literals must match the documented
  ``MXNET_FAULT_INJECT`` site list in ``docs/ENV_VARS.md`` (the TL005
  pattern applied to the two newer contract surfaces).

TL011/TL013/TL014 are per-module passes; TL012 and TL015 run once over
the whole lint target (their facts cross modules).  TL012/TL013 consume
the shared held-lock analysis from :mod:`.locks` (computed once,
shared with TL004).
"""
from __future__ import annotations

import ast
import os
import re

from .callgraph import dotted, iter_own
from .core import Finding
from .locks import _self_attr

__all__ = ["check_module", "check_project", "check_contract"]

_DEADLINE_RE = re.compile(r"deadline|timeout|expir|time_limit",
                          re.IGNORECASE)
_CALLBACK_RE = re.compile(r"(^|_)on_[a-z0-9_]+$|callback|hook",
                          re.IGNORECASE)
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "JoinableQueue"}


def check_module(project, shared, module):
    findings = []
    findings.extend(_tl011(project, module))
    findings.extend(_tl013(project, shared, module))
    findings.extend(_tl014(project, module))
    return findings


# --------------------------------------------------------------------- #
# TL011 — clock discipline
# --------------------------------------------------------------------- #

def _is_wall_call(call, imports):
    """True when ``call`` reads the wall clock (``time.time()``,
    ``datetime.now()``/``utcnow()``), resolving module aliases and
    from-imports so ``from time import time`` classifies the same."""
    d = dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    head = imports.from_imports.get(parts[0])
    if head is not None:
        parts = head[0].split(".") + [head[1]] + parts[1:]
    else:
        tgt = imports.mod_aliases.get(parts[0])
        if tgt is not None:
            parts = tgt.split(".") + parts[1:]
    if parts[0] == "time" and parts[-1] == "time" and len(parts) == 2:
        return True
    if parts[0] == "datetime" and parts[-1] in ("now", "utcnow"):
        return True
    return False


def _deadline_name(expr):
    """An identifier matching the deadline/timeout vocabulary inside
    ``expr``, or None."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and _DEADLINE_RE.search(sub.id):
            return sub.id
        if isinstance(sub, ast.Attribute) and \
                _DEADLINE_RE.search(sub.attr):
            return sub.attr
    return None


def _wall_attrs_by_class(module, imports):
    """Per-class set of self-attributes assigned from a wall-clock read
    in ANY method (``self.tic = time.time()``), so cross-method elapsed
    math still sees the taint."""
    out = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        attrs = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Assign):
                if any(isinstance(sub, ast.Call)
                       and _is_wall_call(sub, imports)
                       for sub in ast.walk(n.value)):
                    for t in n.targets:
                        attr = _self_attr(t)
                        if attr:
                            attrs.add(attr)
        if attrs:
            out[id(node)] = attrs
    return out


def _tl011(project, module):
    imports = project.imports[id(module)]
    if "time" not in module.source:
        return []   # fast path: no clock reads at all
    idx = project.index(module)
    wall_attrs = _wall_attrs_by_class(module, imports)
    out = []
    for info in idx.functions:
        cls_attrs = wall_attrs.get(id(info.cls), set()) \
            if info.cls is not None else set()
        sources = {id(n) for n in iter_own(info.node)
                   if isinstance(n, ast.Call)
                   and _is_wall_call(n, imports)}
        if not sources and not cls_attrs:
            continue
        tainted = set()

        def is_tainted(expr):
            for sub in ast.walk(expr):
                if id(sub) in sources:
                    return True
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in tainted:
                    return True
                attr = _self_attr(sub)
                if attr in cls_attrs and \
                        isinstance(getattr(sub, "ctx", None), ast.Load):
                    return True
            return False

        for _ in range(2):
            for n in iter_own(info.node):
                if isinstance(n, (ast.Assign, ast.AugAssign)) and \
                        is_tainted(n.value):
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)

        hits = {}

        def flag(node, via, assign_value=None):
            hits.setdefault(id(node), (node, via, assign_value))

        for n in iter_own(info.node):
            if isinstance(n, ast.Compare):
                sides = [n.left] + list(n.comparators)
                for i, s in enumerate(sides):
                    if not is_tainted(s):
                        continue
                    for j, other in enumerate(sides):
                        if j == i:
                            continue
                        name = _deadline_name(other)
                        if name:
                            flag(n, f"compared against `{name}`")
            elif isinstance(n, ast.BinOp) and \
                    isinstance(n.op, (ast.Add, ast.Sub)):
                for a, b in ((n.left, n.right), (n.right, n.left)):
                    if is_tainted(a):
                        name = _deadline_name(b)
                        if name:
                            flag(n, f"combined with `{name}`")
            elif isinstance(n, ast.Call):
                for kw in n.keywords:
                    if kw.arg in ("timeout", "deadline") and \
                            is_tainted(kw.value):
                        flag(kw.value, f"passed as `{kw.arg}=`")
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("wait", "join") and n.args and \
                        is_tainted(n.args[0]):
                    flag(n.args[0], f"passed to `.{n.func.attr}(...)` "
                                    "as its timeout")
            elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                if n.value is not None and is_tainted(n.value):
                    for t in targets:
                        name = None
                        if isinstance(t, ast.Name):
                            name = t.id
                        elif isinstance(t, ast.Attribute):
                            name = t.attr
                        if name and _DEADLINE_RE.search(name):
                            flag(n, f"stored into `{name}`",
                                 assign_value=n.value)
        # one finding per defect: an Assign whose VALUE expression was
        # already flagged (`deadline = time.time() + timeout` hits both
        # the BinOp and the store) reports only once
        for hid, (node, _via, value) in list(hits.items()):
            if value is not None and any(
                    id(sub) in hits and id(sub) != hid
                    for sub in ast.walk(value)):
                del hits[hid]
        for node, via, _value in sorted(hits.values(),
                                        key=lambda h: (h[0].lineno,
                                                       h[0].col_offset)):
            out.append(Finding(
                "TL011", module.path, node.lineno, node.col_offset,
                f"wall-clock `time.time()` value {via} inside "
                f"`{info.qualname}` — deadline/timeout arithmetic on "
                "the wall clock breaks under an NTP step (instant or "
                "infinite budget); use time.monotonic() (elapsed-only "
                "logging is exempt and not flagged)"))
    return out


# --------------------------------------------------------------------- #
# TL012 — finalizer lock safety (project-wide; run once in the parent)
# --------------------------------------------------------------------- #

def _resolve_instance_method(project, shared, module, call):
    """``NAME.meth(...)`` where NAME is bound (locally or via import)
    to a module-level singleton (``ACCOUNTANT = MemoryAccountant()``):
    resolve to the class's method so the finalizer walk sees through
    the instance."""
    func = call.func
    if not (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)):
        return []
    head, meth = func.value.id, func.attr
    imp = project.imports[id(module)]
    keys = []
    if head in imp.from_imports:
        keys.append(imp.from_imports[head])
    keys.append((project.names[id(module)] or module.path, head))
    for key in keys:
        hit = shared.instances.get(key)
        if hit is None:
            continue
        imod, icls = hit
        info = project.indexes[id(imod)].class_methods.get(
            id(icls), {}).get(meth)
        if info is not None:
            return [(imod, info)]
    return []


def _finalizer_roots(project):
    """(module, FuncInfo, label) for every ``__del__`` and every
    resolvable ``weakref.finalize(obj, cb, ...)`` callback."""
    roots = []
    for m in project.modules:
        idx = project.index(m)
        for info in idx.functions:
            if info.name == "__del__" and info.cls is not None:
                roots.append((m, info, f"{info.cls.name}.__del__"))
        imp = project.imports[id(m)]
        for call, scopes in idx.calls:
            d = dotted(call.func)
            if not d or d.split(".")[-1] != "finalize":
                continue
            head = d.split(".")[0]
            if d == "finalize":
                # bare name: only counts when from-imported from weakref
                # (a project helper that happens to be named finalize
                # must not seed the walk)
                if imp.from_imports.get("finalize", ("",))[0] != \
                        "weakref":
                    continue
            elif head != "weakref" and \
                    imp.mod_aliases.get(head) != "weakref":
                continue
            if len(call.args) < 2:
                continue
            cb = call.args[1]
            hit = None
            if isinstance(cb, ast.Name):
                local = idx.resolve_name(cb.id, scopes)
                if local is not None:
                    hit = (m, local)
                else:
                    imp = project.imports[id(m)]
                    if cb.id in imp.from_imports:
                        tgt, remote = imp.from_imports[cb.id]
                        hit = project._module_func(
                            project.by_name.get(tgt), remote)
            elif isinstance(cb, ast.Attribute):
                dd = dotted(cb)
                if dd:
                    mod, rest = project._resolve_module_prefix(
                        m, dd.split("."))
                    if mod is not None and len(rest) == 1:
                        hit = project._module_func(mod, rest[0])
            if hit is not None:
                roots.append((hit[0], hit[1],
                              f"weakref.finalize callback "
                              f"`{hit[1].qualname}`"))
    return roots


def check_project(project, shared):
    """TL012 over the whole lint target."""
    out, seen_sites = [], set()
    for rmod, rinfo, label in _finalizer_roots(project):
        seen_fns = {id(rinfo.node)}
        work = [(rmod, rinfo, label)]
        while work:
            mod, info, chain = work.pop(0)
            la = shared.locks.get(id(mod))
            for kind, key, ctor, node in (
                    la.fn_acquires.get(id(info.node), ())
                    if la is not None else ()):
                site = (mod.path, node.lineno, key)
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                out.append(Finding(
                    "TL012", mod.path, node.lineno,
                    getattr(node, "col_offset", 0),
                    f"{ctor} `{key}` is acquired here, reachable from "
                    f"GC finalizer {chain} — a finalizer can run via "
                    "GC inside a thread that already holds the lock "
                    "and deadlock (the ACCOUNTANT finalizer bug); "
                    "route finalizer-side cleanup through a lock-free "
                    "deferral (the drop_deferred pattern), or suppress "
                    "with the reentrancy argument"))
            scopes = info.scopes + (info.node,)
            for n in iter_own(info.node):
                if not isinstance(n, ast.Call):
                    continue
                targets = project.resolve_call(mod, n, scopes)
                if not targets:
                    targets = _resolve_instance_method(
                        project, shared, mod, n)
                for cmod, callee in targets:
                    if id(callee.node) in seen_fns:
                        continue
                    seen_fns.add(id(callee.node))
                    work.append((cmod, callee,
                                 f"{chain} -> {callee.qualname}"))
    out.sort(key=lambda f: (f.path, f.line))
    return out


# --------------------------------------------------------------------- #
# TL013 — callback invoked under a held lock
# --------------------------------------------------------------------- #

def _tl013(project, shared, module):
    la = shared.locks.get(id(module))
    if la is None or not la.fn_calls:
        return []
    idx = project.index(module)
    out = []
    for info in idx.functions:
        calls = la.fn_calls.get(id(info.node))
        if not calls:
            continue
        scopes = info.scopes + (info.node,)
        for call, held in calls:
            if not held:
                continue
            func = call.func
            if isinstance(func, ast.Name):
                name = func.id
            elif isinstance(func, ast.Attribute):
                name = func.attr
            else:
                continue
            if not _CALLBACK_RE.search(name):
                continue
            if project.resolve_call(module, call, scopes):
                continue   # resolves to a project function: internal,
                # not a user-supplied callable
            _kind, key = held[-1]
            out.append(Finding(
                "TL013", module.path, call.lineno, call.col_offset,
                f"user callback `{dotted(func) or name}(...)` invoked "
                f"while `{key}` is held (in `{info.qualname}`) — a "
                "callback that re-enters the owner (submit/close from "
                "on_token) deadlocks, and a slow one blocks every "
                "other client of the lock; move the invocation outside "
                "the critical section (the _push-outside-_lock "
                "discipline)"))
    return out


# --------------------------------------------------------------------- #
# TL014 — thread lifecycle
# --------------------------------------------------------------------- #

def _is_thread_ctor(call, imports):
    d = dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    if parts[-1] != "Thread":
        return False
    if len(parts) == 1:
        tgt = imports.from_imports.get("Thread")
        return bool(tgt) and tgt[0] == "threading"
    return parts[0] == "threading" or \
        imports.mod_aliases.get(parts[0]) == "threading"


def _is_queue_ctor(call):
    d = dotted(call.func)
    return bool(d) and d.split(".")[-1] in _QUEUE_CTORS


def _daemon_kwarg(call):
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and \
                kw.value.value is True
    return False


def _module_sentinels(module):
    """Module-level names usable as poison pills: ALL-CAPS constants
    and names bound to ``object()`` or ``None``."""
    out = set()
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign):
            names = [t.id for t in stmt.targets
                     if isinstance(t, ast.Name)]
            sentinel = (isinstance(stmt.value, ast.Call)
                        and dotted(stmt.value.func) == "object") or \
                (isinstance(stmt.value, ast.Constant)
                 and stmt.value.value is None)
            for n in names:
                if sentinel or n.isupper() or \
                        (n.startswith("_") and n[1:].isupper()):
                    out.add(n)
    return out


def _family_methods(project, module, cls):
    """(owner_module, method fn node) across the project-wide family."""
    from .locks import _class_methods

    out = []
    for fmod, fcls in project._class_family(module, cls):
        for m in _class_methods(fcls):
            out.append((fmod, m))
    return out


def _blocking_get(call):
    """True when ``call`` is an unbounded blocking ``.get()``."""
    if call.args:
        a0 = call.args[0]
        if not (isinstance(a0, ast.Constant) and a0.value is True):
            return False
        if len(call.args) >= 2:
            # positional timeout: get(True, 1.0) wakes on its own —
            # only an explicit None timeout stays unbounded
            a1 = call.args[1]
            if not (isinstance(a1, ast.Constant) and a1.value is None):
                return False
    for kw in call.keywords:
        if kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None):
            return False
        if kw.arg == "block" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is True):
            return False
    return True


def _tl014(project, module):
    imports = project.imports[id(module)]
    threads_present = "Thread" in module.source
    idx = project.index(module)
    out = []

    # -- per-class: threads bound to self attributes + queue pills ------- #
    for cls in idx.classes.values():
        fam = _family_methods(project, module, cls) if threads_present \
            or "Queue" in module.source else []
        thread_attrs = {}     # attr -> (ctor call, daemon)
        queue_attrs = set()
        for fmod, m in fam:
            for n in iter_own(m):
                if isinstance(n, ast.Assign):
                    attr = _self_attr(n.targets[0]) \
                        if len(n.targets) == 1 else None
                    if attr and isinstance(n.value, ast.Call):
                        fimp = project.imports[id(fmod)]
                        if _is_thread_ctor(n.value, fimp):
                            thread_attrs.setdefault(
                                attr, (fmod, n.value,
                                       _daemon_kwarg(n.value)))
                        elif _is_queue_ctor(n.value):
                            queue_attrs.add(attr)
        if not thread_attrs and not queue_attrs:
            continue
        joined, daemoned, pills, gets = set(), set(), set(), []
        sentinels = set()
        for fmod in {id(fm): fm for fm, _m in fam}.values():
            sentinels |= _module_sentinels(fmod)
        for fmod, m in fam:
            for n in iter_own(m):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute):
                    recv = _self_attr(n.func.value)
                    if recv and n.func.attr == "join":
                        joined.add(recv)
                    elif recv and n.func.attr == "setDaemon":
                        daemoned.add(recv)
                    elif recv in queue_attrs and \
                            n.func.attr in ("put", "put_nowait") and \
                            n.args:
                        a0 = n.args[0]
                        if (isinstance(a0, ast.Constant)
                                and a0.value is None) or \
                                (isinstance(a0, ast.Name)
                                 and a0.id in sentinels):
                            pills.add(recv)
                    elif recv in queue_attrs and n.func.attr == "get" \
                            and _blocking_get(n) and fmod is module:
                        gets.append((recv, n, m.name))
                elif isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr == "daemon":
                            recv = _self_attr(t.value)
                            if recv and isinstance(n.value, ast.Constant) \
                                    and n.value.value is True:
                                daemoned.add(recv)
        for attr, (fmod, call, daemon) in sorted(thread_attrs.items()):
            if daemon or attr in daemoned or attr in joined:
                continue
            if fmod is not module:
                continue   # reported where the ctor lives
            out.append(Finding(
                "TL014", module.path, call.lineno, call.col_offset,
                f"`self.{attr}` thread started by `{cls.name}` is not "
                "daemon=True and is never joined on any close/stop/"
                "teardown path of the class family — an abandoned "
                "instance strands the thread (and a non-daemon thread "
                "blocks interpreter exit); mark it daemon or join it "
                "in close()"))
        if thread_attrs:
            for recv, n, meth in gets:
                if recv in pills:
                    continue
                out.append(Finding(
                    "TL014", module.path, n.lineno, n.col_offset,
                    f"unbounded `self.{recv}.get()` in "
                    f"`{cls.name}.{meth}` with no poison-pill wakeup "
                    "reachable: the class owns a producer thread, and "
                    "when it dies (or close() runs) a consumer parked "
                    "here blocks forever — put a sentinel (the _END "
                    "pill pattern) on every close path, or use "
                    "get(timeout=...)"))

    # -- local threads inside plain functions ----------------------------- #
    if threads_present:
        for info in idx.functions:
            local_threads = {}   # name -> ctor call
            started, joined, daemoned = set(), set(), set()
            returned = set()     # ownership handed to the caller
            for n in iter_own(info.node):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) and \
                        isinstance(n.value, ast.Call) and \
                        _is_thread_ctor(n.value, imports):
                    if not _daemon_kwarg(n.value):
                        local_threads[n.targets[0].id] = n.value
                elif isinstance(n, ast.Call):
                    if isinstance(n.func, ast.Attribute) and \
                            isinstance(n.func.value, ast.Name):
                        if n.func.attr == "start":
                            started.add(n.func.value.id)
                            continue
                        if n.func.attr == "join":
                            joined.add(n.func.value.id)
                            continue
                    # a handle passed to any other call escapes —
                    # self._workers.append(t), registry.add(t): the
                    # callee owns the join-on-teardown story now
                    for a in list(n.args) + [k.value for k in n.keywords]:
                        if isinstance(a, ast.Name):
                            returned.add(a.id)
                elif isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr == "daemon" and \
                                isinstance(t.value, ast.Name) and \
                                isinstance(n.value, ast.Constant) and \
                                n.value.value is True:
                            daemoned.add(t.value.id)
                        elif isinstance(t, (ast.Attribute,
                                            ast.Subscript)):
                            # stored into an attribute/container:
                            # ownership transferred to that structure
                            for leaf in ast.walk(n.value):
                                if isinstance(leaf, ast.Name):
                                    returned.add(leaf.id)
                elif isinstance(n, ast.Return) and n.value is not None:
                    for leaf in ast.walk(n.value):
                        if isinstance(leaf, ast.Name):
                            returned.add(leaf.id)
            for name, call in sorted(local_threads.items()):
                if name in started and name not in joined and \
                        name not in daemoned and name not in returned:
                    out.append(Finding(
                        "TL014", module.path, call.lineno,
                        call.col_offset,
                        f"thread `{name}` started in "
                        f"`{info.qualname}` is neither daemon=True "
                        "nor joined before the function returns — it "
                        "outlives its owner with no teardown path; "
                        "mark it daemon or join it"))
    return out


# --------------------------------------------------------------------- #
# TL015 — telemetry / fault-site contract (run once in the parent)
# --------------------------------------------------------------------- #

_DOC_TOKEN_RE = re.compile(r"`([A-Za-z_][\w.]*)")
_SITE_RE = re.compile(r"^[a-z_]+(\.[a-z_]+)+$")
_EMIT_RECEIVERS = {"telemetry", "events", "_events"}
_METRIC_RECEIVERS = {"telemetry", "REGISTRY"}
_METRIC_FNS = {"counter", "gauge", "histogram"}


def _emit_forwarders(tree):
    """Module functions that forward their FIRST parameter as an event
    kind (``tools/launch.py``'s ``_emit(kind, **fields)`` wrapper) —
    calls to them with a literal count as emits of that kind."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args.posonlyargs + node.args.args
        if not args:
            continue
        first = args[0].arg
        for n in iter_own(node):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d.split(".")[-1] == "emit" and n.args and \
                        isinstance(n.args[0], ast.Name) and \
                        n.args[0].id == first:
                    names.add(node.name)
    return names


def _bare_imports(tree):
    """Locally-bound bare names for emit / metric fns, resolved from
    the tree's own import statements (no project machinery, so the aux
    repo walk can use this too)."""
    emit_names, metric_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            src = node.module
            telemetryish = "telemetry" in src or \
                src.split(".")[-1] in ("events", "registry")
            if not telemetryish:
                continue
            for a in node.names:
                if a.name == "emit":
                    emit_names.add(a.asname or a.name)
                elif a.name in _METRIC_FNS:
                    metric_names.add(a.asname or a.name)
    return emit_names, metric_names


class TelemetryUses:
    __slots__ = ("emits", "metric_lits", "metric_pats", "sites")

    def __init__(self):
        self.emits = []        # (kind, line)
        self.metric_lits = []  # (name, line)
        self.metric_pats = []  # (regex string, line)
        self.sites = []        # (site, line)


def telemetry_uses(tree):
    """All telemetry-contract uses in one parsed file."""
    uses = TelemetryUses()
    forwarders = _emit_forwarders(tree)
    emit_bare, metric_bare = _bare_imports(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        parts = d.split(".")
        last = parts[-1]
        arg0 = node.args[0] if node.args else None
        lit = arg0.value if isinstance(arg0, ast.Constant) and \
            isinstance(arg0.value, str) else None
        if last == "fault_point":
            if lit:
                uses.sites.append((lit, node.lineno))
        elif last == "emit" or (len(parts) == 1
                                and last in forwarders):
            ok = (len(parts) > 1 and parts[-2] in _EMIT_RECEIVERS) or \
                (len(parts) == 1 and (last in emit_bare
                                      or last in forwarders))
            if ok and lit:
                uses.emits.append((lit, node.lineno))
        elif last in _METRIC_FNS:
            ok = (len(parts) > 1 and parts[-2] in _METRIC_RECEIVERS) or \
                (len(parts) == 1 and last in metric_bare)
            if not ok:
                continue
            if lit:
                uses.metric_lits.append((lit, node.lineno))
            elif isinstance(arg0, ast.JoinedStr):
                pat, has_const = "", False
                for v in arg0.values:
                    if isinstance(v, ast.Constant):
                        pat += re.escape(str(v.value))
                        has_const = True
                    else:
                        pat += ".+"
                if has_const:
                    uses.metric_pats.append((pat, node.lineno))
    return uses


def _doc_schema(path):
    """(kinds, metrics) documented in TELEMETRY.md: backticked tokens
    in the FIRST cell of table rows, namespaced by the enclosing
    heading ('event' tables document kinds, 'metric' tables document
    instrument names)."""
    kinds, metrics = {}, {}
    heading = ""
    in_code = False
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            if line.lstrip().startswith("```"):
                in_code = not in_code
                continue
            if in_code:
                continue
            if line.startswith("#"):
                heading = line.lower()
                continue
            s = line.strip()
            if not s.startswith("|"):
                continue
            cells = s.split("|")
            if len(cells) < 2:
                continue
            first = cells[1]
            if set(first.strip()) <= set("-: "):
                continue   # the |---|---| separator row
            toks = [t.split("{")[0] for t in _DOC_TOKEN_RE.findall(first)]
            if "event" in heading:
                for t in toks:
                    kinds.setdefault(t, i)
            elif "metric" in heading:
                for t in toks:
                    metrics.setdefault(t, i)
    return kinds, metrics


def _doc_fault_sites(path):
    """Documented fault-injection sites: the backticked ``a.b`` tokens
    between 'Sites:' and 'Kinds:' in the ``MXNET_FAULT_INJECT`` doc
    row.  None when the row (or the Sites: marker) is absent — the
    contract is then unchecked rather than vacuously failed."""
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            cells = line.strip().split("|")
            if len(cells) < 2 or "MXNET_FAULT_INJECT" not in cells[1]:
                continue
            lo = line.find("Sites:")
            if lo < 0:
                return None
            hi = line.find("Kinds:", lo)
            seg = line[lo:hi if hi > lo else len(line)]
            sites = {t for t in _DOC_TOKEN_RE.findall(seg)
                     if _SITE_RE.match(t)}
            return sites, i
    return None


def check_contract(modules, telemetry_docs, env_docs, aux_tele,
                   aux_env=None):
    """TL015 over the scanned modules (code-side anchors) + the docs
    (stale-row anchors).  ``aux_tele``/``aux_env`` are repo scans
    (``rules_env.repo_scan``) rooted at the tree owning each docs file
    — the reverse directions are judged against the WHOLE owning repo
    (minus tests/examples) so partial-path lints don't report
    contracts satisfied elsewhere as stale."""
    findings = []
    if not modules:
        return findings
    if aux_env is None:
        aux_env = aux_tele
    uses = {id(m): telemetry_uses(m.tree) for m in modules}

    if telemetry_docs is not None:
        kinds_doc, metrics_doc = _doc_schema(telemetry_docs)
        rel = os.path.relpath(telemetry_docs)
        for m in modules:
            u = uses[id(m)]
            for kind, line in u.emits:
                if kind not in kinds_doc:
                    findings.append(Finding(
                        "TL015", m.path, line, 0,
                        f"event kind `{kind}` is emitted here but has "
                        f"no row in {rel}'s event-schema table — "
                        "document the event (producer + fields) or "
                        "rename the emit"))
            for name, line in u.metric_lits:
                if name not in metrics_doc:
                    findings.append(Finding(
                        "TL015", m.path, line, 0,
                        f"metric `{name}` is created here but has no "
                        f"row in {rel}'s metrics table — document the "
                        "instrument (kind + labels) or rename it"))
        aux_kinds = aux_tele.emit_kinds if aux_tele is not None else \
            {k for u in uses.values() for k, _ in u.emits}
        aux_lits = aux_tele.metric_lits if aux_tele is not None else \
            {k for u in uses.values() for k, _ in u.metric_lits}
        aux_pats = aux_tele.metric_pats if aux_tele is not None else \
            {p for u in uses.values() for p, _ in u.metric_pats}
        for kind, line in sorted(kinds_doc.items()):
            if kind not in aux_kinds:
                findings.append(Finding(
                    "TL015", telemetry_docs, line, 0,
                    f"event kind `{kind}` is documented but never "
                    "emitted anywhere in the library or tooling — "
                    "stale row; delete it or wire the emit up",
                    snippet=f"event-schema row for {kind}"))
        for name, line in sorted(metrics_doc.items()):
            if name in aux_lits:
                continue
            if any(re.fullmatch(p, name) for p in aux_pats):
                continue
            findings.append(Finding(
                "TL015", telemetry_docs, line, 0,
                f"metric `{name}` is documented but never created "
                "anywhere in the library or tooling — stale row; "
                "delete it or wire the instrument up",
                snippet=f"metrics row for {name}"))

    if env_docs is not None:
        doc_sites = _doc_fault_sites(env_docs)
        if doc_sites is not None:
            sites, row_line = doc_sites
            rel = os.path.relpath(env_docs)
            for m in modules:
                for site, line in uses[id(m)].sites:
                    if site not in sites:
                        findings.append(Finding(
                            "TL015", m.path, line, 0,
                            f"fault-injection site `{site}` is not in "
                            f"the MXNET_FAULT_INJECT site list in "
                            f"{rel} — document it (operators can only "
                            "arm sites they can discover) or rename "
                            "the fault_point"))
            aux_sites = aux_env.fault_sites if aux_env is not None else \
                {s for u in uses.values() for s, _ in u.sites}
            for site in sorted(sites):
                if site not in aux_sites:
                    findings.append(Finding(
                        "TL015", env_docs, row_line, 0,
                        f"fault-injection site `{site}` is documented "
                        "in the MXNET_FAULT_INJECT row but no "
                        "fault_point with that name exists — stale; "
                        "delete it or add the site",
                        snippet=f"MXNET_FAULT_INJECT site {site}"))
    return findings
