"""TL016–TL019 — the executable-contract family (tracelint v4).

The serve engine's compiled programs live by POSITIONAL facts the
compiler trusts blindly: ``donate_argnums`` indices, the slot-state
tuple layout, each dispatch call's operand order.  PR 18's recycled-page
bug rode exactly that — a hand-shifted donation pair plus a slot-state
column threaded through scatter sites by eye.  PR 20 moved those facts
into a declarative registry (``mxnet_tpu/serve/schema.py``:
``EXECUTABLES`` + ``SLOT_STATE``, pure literals), and these rules hold
every producer and consumer in the lint target to it — the registry is
read straight out of the AST (``ast.literal_eval``), no import, so the
linter checks the same declaration the runtime derives its
``donate_argnums`` from.

* **TL016** — donation-index drift.  A ``jax.jit(fn,
  donate_argnums=<literal>)`` whose wrapped function is a registry
  executable must donate exactly the registry's positions, and the
  parameters at those positions must be the declared donated operands
  (deriving via ``schema.jit_donate`` is the sanctioned pattern and
  passes).  Outside the registry the producer-side generalization of
  TL002 applies: a literal donation index past the wrapped function's
  positional arity donates a buffer that does not exist — XLA trusts
  the index, so the wrong operand dies silently.
* **TL017** — slot-state / meta layout drift.  Hard-coded ``meta``
  column subscripts inside an executable body, state tuples whose
  arity disagrees with the declared column count, and literal
  ``*SLOT_STATE*BYTES*`` constants all bypass the registry accessors —
  the PR-13 deadline and PR-17 spec-depth columns were each
  hand-threaded through four scatter sites this way.
* **TL018** — operand-arity drift.  A dispatch call-site reached
  through a registry executable's getter must pass exactly the
  declared operand count (a ``*state`` splat counts as the declared
  state arity) — the "``zpages`` lands in 2 of 3 admission paths"
  class.
* **TL019** — multi-process placement discipline.  Host-local values
  (``jax.process_index()``, ``jax.local_devices()``,
  ``jax.local_device_count()``, per-rank env reads) flowing into mesh
  or sharding CONSTRUCTION (``Mesh``/``make_mesh``/``NamedSharding``/
  ``PartitionSpec``) or into the sharding position of
  ``device_put``/``global_put``/``make_array_from_process_local_data``
  give each pod process a different placement for the "same" global
  array — the elastic-resume hazard PR 19 hand-reviewed for.  Route
  placement through the ``parallel.mesh`` helpers (whose definitions
  are the sanctioned boundary and are exempt) and pod-global facts
  (``jax.devices()``, ``jax.device_count()``).
"""
from __future__ import annotations

import ast

from .callgraph import dotted, iter_own
from .core import Finding
from .rules_trace import _is_jit_call, _resolve_positions

__all__ = ["check_module", "find_registry"]

# mirror of the registry's dtype pricing table (the registry file is
# read as data, not imported, so the linter prices slot-state bytes
# with its own copy)
_ITEMSIZE = {"bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
             "int32": 4, "uint32": 4, "float32": 4, "int64": 8,
             "uint64": 8, "float64": 8}

# host-local reads that differ per pod process (TL019 taint sources)
_LOCAL_READS = {"process_index", "local_devices", "local_device_count"}
# the sanctioned placement helpers (parallel/mesh.py): values produced
# BY them are clean, and the functions DEFINING them are exempt sinks
_MESH_HELPERS = {"make_mesh", "default_mesh", "current_mesh",
                 "named_sharding", "data_sharding",
                 "replicated_sharding", "local_mesh_axes", "global_put"}


class Registry:
    """The operand-schema declarations of one registry module, parsed
    from its AST (``EXECUTABLES`` / ``SLOT_STATE`` literal assigns)."""

    def __init__(self, module, execs, slots):
        self.module = module
        self.execs = execs
        self.slots = tuple(slots)
        self.state_arity = 2 + len(self.slots)
        self.slot_state_bytes = sum(
            _ITEMSIZE.get(dt, 0) * n for _, dt, n in self.slots)
        self.by_getter = {}
        for name, e in execs.items():
            getter = e.get("getter")
            if isinstance(getter, str):
                self.by_getter[getter] = name

    def operands(self, name):
        return tuple(self.execs[name]["operands"])

    def arity(self, name):
        return len(self.operands(name))

    def donated(self, name):
        return tuple(self.execs[name].get("donated", ()))

    def donate_argnums(self, name):
        donated = set(self.donated(name))
        return tuple(i for i, op in enumerate(self.operands(name))
                     if op in donated)

    def scope_match(self, mod_name, name):
        """Is ``mod_name`` the module the executable declares itself
        defined in (suffix-tolerant for bare fixture files)?"""
        decl = self.execs[name].get("module")
        if not isinstance(decl, str) or not mod_name:
            return False
        return (mod_name == decl or mod_name.endswith("." + decl)
                or decl.endswith("." + mod_name))

    def in_scope(self, mod_name):
        return any(self.scope_match(mod_name, n) for n in self.execs)


def _literal_assign(module, varname):
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == varname:
            return stmt.value
    return None


def _valid_execs(execs):
    if not isinstance(execs, dict) or not execs:
        return False
    for e in execs.values():
        if not isinstance(e, dict) or \
                not isinstance(e.get("operands"), (tuple, list)):
            return False
    return True


def find_registry(project):
    """The first scanned module declaring BOTH ``EXECUTABLES`` and
    ``SLOT_STATE`` as pure literals, or None.  Memoized per project
    (cheap: top-level assigns only)."""
    cached = getattr(project, "_contract_registry", False)
    if cached is not False:
        return cached
    reg = None
    for m in project.modules:
        ev = _literal_assign(m, "EXECUTABLES")
        sv = _literal_assign(m, "SLOT_STATE")
        if ev is None or sv is None:
            continue
        try:
            execs = ast.literal_eval(ev)
            slots = ast.literal_eval(sv)
        except (ValueError, SyntaxError):
            continue
        if _valid_execs(execs) and isinstance(slots, (tuple, list)):
            reg = Registry(m, execs, slots)
            break
    project._contract_registry = reg
    return reg


def check_module(project, shared, module):
    reg = find_registry(project)
    findings = []
    findings.extend(_tl016(project, reg, module))
    if reg is not None and module is not reg.module:
        findings.extend(_tl017(project, reg, module))
        findings.extend(_tl018(project, reg, module))
    findings.extend(_tl019(project, module))
    return findings


# --------------------------------------------------------------------- #
# TL016 — donation-index drift
# --------------------------------------------------------------------- #

def _positional_params(fn_node):
    a = fn_node.args
    return [p.arg for p in a.posonlyargs + a.args], a.vararg is not None


def _wrapped_fn(project, module, idx, call, scopes):
    """FuncInfo of ``jax.jit``'s wrapped function, when resolvable."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Name):
        info = idx.resolve_name(target.id, scopes)
        if info is not None:
            return info
        imp = project.imports[id(module)]
        if target.id in imp.from_imports:
            tgt, remote = imp.from_imports[target.id]
            hit = project._module_func(project.by_name.get(tgt), remote)
            if hit is not None:
                return hit[1]
    return None


def _tl016(project, reg, module):
    idx = project.index(module)
    mod_name = project.names[id(module)] or ""
    out = []
    for call, scopes in idx.calls:
        if not _is_jit_call(call, module):
            continue
        kw = next((k for k in call.keywords
                   if k.arg == "donate_argnums"), None)
        if kw is None:
            continue
        if isinstance(kw.value, ast.Call):
            d = dotted(kw.value.func)
            if d and d.split(".")[-1] == "jit_donate":
                continue  # registry-derived: the sanctioned pattern
        fn_node = scopes[-1] if isinstance(
            scopes[-1], (ast.FunctionDef, ast.AsyncFunctionDef)) else None
        pos = _resolve_positions(kw.value, fn_node)
        if not pos:
            continue
        winfo = _wrapped_fn(project, module, idx, call, scopes)
        if winfo is None:
            continue
        params, has_var = _positional_params(winfo.node)
        if reg is not None and winfo.name in reg.execs and \
                reg.scope_match(mod_name, winfo.name):
            name = winfo.name
            expected = reg.donate_argnums(name)
            donated = set(reg.donated(name))
            if set(pos) != set(expected):
                out.append(Finding(
                    "TL016", module.path, kw.value.lineno,
                    kw.value.col_offset,
                    f"literal donate_argnums {tuple(sorted(pos))} on "
                    f"serve executable {name!r} disagree with the "
                    f"operand schema's donated positions {expected} "
                    f"(donated operands: {sorted(donated)}) — derive "
                    "them with schema.jit_donate() so an operand "
                    "insertion can never donate the wrong buffer"))
                continue
            bad = [p for p in sorted(pos)
                   if p >= len(params) or params[p] not in donated]
            if bad:
                at = ", ".join(
                    f"{p} (param "
                    f"{params[p]!r})" if p < len(params) else f"{p} "
                    "(past the arity)" for p in bad)
                out.append(Finding(
                    "TL016", module.path, kw.value.lineno,
                    kw.value.col_offset,
                    f"serve executable {name!r} donates position(s) "
                    f"{at}, but the operand schema donates "
                    f"{sorted(donated)} — the function's parameter "
                    "list drifted from the declaration (the PR-18 "
                    "recycled-page shape); update the schema and the "
                    "signature together and derive the indices with "
                    "schema.jit_donate()"))
        else:
            over = [p for p in sorted(pos) if p >= len(params)]
            if over and not has_var:
                out.append(Finding(
                    "TL016", module.path, kw.value.lineno,
                    kw.value.col_offset,
                    f"donate_argnums {tuple(sorted(pos))} exceed "
                    f"`{winfo.name}`'s positional arity {len(params)} "
                    f"({', '.join(params) or 'no parameters'}) — XLA "
                    "trusts donation indices blindly, so a stale index "
                    "silently donates the wrong operand; re-count "
                    "against the signature"))
    return out


# --------------------------------------------------------------------- #
# TL017 — slot-state / meta layout drift
# --------------------------------------------------------------------- #

def _int_subscript_consts(node):
    """Constant-int index nodes inside one Subscript slice."""
    sl = node.slice
    elems = sl.elts if isinstance(sl, ast.Tuple) else [sl]
    return [e for e in elems
            if isinstance(e, ast.Constant) and isinstance(e.value, int)]


def _calls_getters(idx, reg):
    for call, _scopes in idx.calls:
        d = dotted(call.func)
        if d and d.split(".")[-1] in reg.by_getter:
            return True
    return False


def _tl017(project, reg, module):
    idx = project.index(module)
    mod_name = project.names[id(module)] or ""
    exec_scope = reg.in_scope(mod_name)
    dispatch_scope = exec_scope or _calls_getters(idx, reg)
    out = []
    # (a) hard-coded meta column subscripts — in executable bodies and
    # in dispatch modules building the rows the bodies unpack
    if dispatch_scope:
        meta_fns = []
        for info in idx.functions:
            params, _ = _positional_params(info.node)
            if "meta" in params or (exec_scope and info.name in reg.execs):
                meta_fns.append(info)
        if not exec_scope:
            meta_fns = idx.functions  # dispatch side: any builder
        for info in meta_fns:
            for n in iter_own(info.node):
                if isinstance(n, ast.Subscript) and \
                        dotted(n.value) == "meta":
                    for c in _int_subscript_consts(n):
                        out.append(Finding(
                            "TL017", module.path, c.lineno, c.col_offset,
                            f"hard-coded meta column index {c.value} — "
                            "the packed meta-row layout is declared in "
                            "the operand schema; index through "
                            "schema.meta_col()/meta_cols() (build rows "
                            "with schema.meta_row()) so a new column "
                            "renumbers every site at once"))
    # (b) state tuples whose arity disagrees with the declared columns
    if exec_scope:
        for n in ast.walk(module.tree):
            if not isinstance(n, ast.Tuple) or len(n.elts) < 3:
                continue
            e0, e1 = n.elts[0], n.elts[1]
            if isinstance(e0, ast.Name) and isinstance(e1, ast.Name) \
                    and e0.id == "kp" and e1.id == "vp" and \
                    len(n.elts) != reg.state_arity:
                out.append(Finding(
                    "TL017", module.path, n.lineno, n.col_offset,
                    f"pool state tuple has {len(n.elts)} elements where "
                    f"the operand schema declares {reg.state_arity} "
                    "(kp, vp + SLOT_STATE columns) — a column threaded "
                    "through some scatter sites but not this one is "
                    "exactly the PR-13/PR-17 drift; update the schema "
                    "and every site together"))
    # (c) literal slot-state byte totals bypassing the registry
    for n in ast.walk(module.tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Constant) \
                and isinstance(n.value.value, int):
            for t in n.targets:
                if isinstance(t, ast.Name) and "SLOT_STATE" in t.id \
                        and "BYTE" in t.id:
                    out.append(Finding(
                        "TL017", module.path, n.lineno, n.col_offset,
                        f"`{t.id} = {n.value.value}` hard-codes the "
                        "per-slot state byte total — price it from the "
                        "declaration (schema.slot_state_bytes(), "
                        f"currently {reg.slot_state_bytes}) so the "
                        "ledger can never drift from the layout"))
    return out


# --------------------------------------------------------------------- #
# TL018 — operand-arity drift at dispatch call-sites
# --------------------------------------------------------------------- #

def _dispatch_exec(n, bound, getters):
    """Executable name when Call ``n`` dispatches one, else None."""
    if isinstance(n.func, ast.Name) and n.func.id in bound:
        return bound[n.func.id]
    if isinstance(n.func, ast.Call):
        d = dotted(n.func.func)
        if d and d.split(".")[-1] in getters:
            return getters[d.split(".")[-1]]
    return None


def _tl018(project, reg, module):
    idx = project.index(module)
    getters = reg.by_getter
    out = []
    for info in idx.functions:
        bound = {}   # local name -> executable it was fetched as
        for n in iter_own(info.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Call):
                d = dotted(n.value.func)
                if d and d.split(".")[-1] in getters:
                    bound[n.targets[0].id] = getters[d.split(".")[-1]]
        if not bound and not any(
                isinstance(n, ast.Call) and isinstance(n.func, ast.Call)
                for n in iter_own(info.node)):
            continue
        for n in iter_own(info.node):
            if not isinstance(n, ast.Call):
                continue
            name = _dispatch_exec(n, bound, getters)
            if name is None or n.keywords:
                continue
            count, countable = 0, True
            for a in n.args:
                if isinstance(a, ast.Starred):
                    d = dotted(a.value)
                    if d and "state" in d.split(".")[-1].lower():
                        count += reg.state_arity
                    else:
                        countable = False
                        break
                else:
                    count += 1
            if not countable:
                continue
            want = reg.arity(name)
            if count != want:
                out.append(Finding(
                    "TL018", module.path, n.lineno, n.col_offset,
                    f"dispatch of serve executable {name!r} passes "
                    f"{count} operand(s) (a *state splat counts as "
                    f"{reg.state_arity}) where the operand schema "
                    f"declares {want}: "
                    f"({', '.join(reg.operands(name))}) — an operand "
                    "missing from one dispatch path is the "
                    "'zpages lands in 2 of 3 admission paths' class"))
    return out


# --------------------------------------------------------------------- #
# TL019 — multi-process placement discipline
# --------------------------------------------------------------------- #

def _jaxish(root, module):
    return root == "jax" or root in module.jax_aliases


def _local_read(call, module, imports):
    """Label when ``call`` reads host-local pod state, else None."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    head = imports.from_imports.get(parts[0])
    if head is not None:
        parts = head[0].split(".") + [head[1]] + parts[1:]
    else:
        tgt = imports.mod_aliases.get(parts[0])
        if tgt is not None:
            parts = tgt.split(".") + parts[1:]
    root, last = parts[0], parts[-1]
    if last in _LOCAL_READS and _jaxish(root, module):
        return f"jax.{last}()"
    if root == "os" and (last == "getenv" or
                         ("environ" in parts[:-1] and last == "get")):
        return "a per-rank os.environ read"
    return None


def _environ_sub(node):
    if isinstance(node, ast.Subscript):
        d = dotted(node.value)
        return bool(d) and d.endswith("environ")
    return False


def _placement_taint(module, imports, fn_node):
    """origin(expr) -> (source node, label) for host-local values in one
    scope, following local assignment chains.  Values produced by the
    ``parallel.mesh`` helpers are clean — the helpers are the
    sanctioned boundary."""
    sources = {}
    for n in iter_own(fn_node):
        label = None
        if isinstance(n, ast.Call):
            label = _local_read(n, module, imports)
        elif _environ_sub(n):
            label = "a per-rank os.environ read"
        if label:
            sources[id(n)] = (n, label)
    tainted = {}

    def origin(expr):
        for sub in ast.walk(expr):
            if id(sub) in sources:
                return sources[id(sub)]
            if isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Load) and sub.id in tainted:
                return tainted[sub.id]
        return None

    # to a fixed point: iter_own's walk order is not source order, so a
    # k-link assignment chain can need k passes (capped — chains this
    # deep in one scope are already suspect)
    for _ in range(10):
        changed = False
        for n in iter_own(fn_node):
            if not isinstance(n, (ast.Assign, ast.AugAssign)):
                continue
            if isinstance(n.value, ast.Call):
                d = dotted(n.value.func)
                if d and d.split(".")[-1] in _MESH_HELPERS:
                    continue  # helper output is sanctioned-clean
            hit = origin(n.value)
            if hit is None:
                continue
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name) and \
                            leaf.id not in tainted:
                        tainted[leaf.id] = hit
                        changed = True
        if not changed:
            break
    return origin


def _spec_ctor(call, imports):
    d = dotted(call.func)
    if d is None:
        return False
    last = d.split(".")[-1]
    if last == "PartitionSpec":
        return True
    if last == "P":
        tgt = imports.from_imports.get("P")
        return bool(tgt) and tgt[1] in ("P", "PartitionSpec")
    return False


def _placement_sink_args(call, imports):
    """(what, arg nodes to taint-check) when ``call`` constructs or
    consumes cross-process placement, else None."""
    if _spec_ctor(call, imports):
        return ("PartitionSpec construction",
                list(call.args) + [k.value for k in call.keywords])
    d = dotted(call.func)
    last = d.split(".")[-1] if d else None
    if last in ("Mesh", "make_mesh", "NamedSharding"):
        return (f"`{last}(...)` mesh/sharding construction",
                list(call.args) + [k.value for k in call.keywords])
    if last in ("device_put", "global_put") and len(call.args) >= 2:
        return (f"the sharding argument of `{last}(...)`",
                [call.args[1]])
    if last == "make_array_from_process_local_data" and call.args:
        return ("the sharding argument of "
                "`make_array_from_process_local_data(...)`",
                [call.args[0]])
    return None


def _tl019(project, module):
    imports = project.imports[id(module)]
    idx = project.index(module)
    out = []
    scopes = [module.tree] + [info.node for info in idx.functions]
    for fn_node in scopes:
        # the parallel.mesh helper DEFINITIONS are the sanctioned
        # boundary — their internals legitimately branch on process
        # locality (global_put assembles from process-local data)
        if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and fn_node.name in (_MESH_HELPERS | {"init_distributed",
                                                      "barrier"}):
            continue
        origin = _placement_taint(module, imports, fn_node)
        for n in iter_own(fn_node):
            if not isinstance(n, ast.Call):
                continue
            sink = _placement_sink_args(n, imports)
            if sink is None:
                continue
            what, args = sink
            for a in args:
                hit = origin(a)
                if hit is None:
                    continue
                node, label = hit
                out.append(Finding(
                    "TL019", module.path, a.lineno, a.col_offset,
                    f"host-local {label} (line {node.lineno}) flows "
                    f"into {what} — each pod process computes a "
                    "different placement for the same global array "
                    "(the elastic-resume hazard); build placement "
                    "from pod-global facts (jax.devices(), "
                    "jax.device_count()) or route it through the "
                    "parallel.mesh helpers"))
                break  # one finding per sink call
    return out
