"""tracelint — trace-discipline static analyzer for the mxnet_tpu tree.

The fused hot path (gluon/fused_step.py, gluon/block.py ``_CachedOp``,
optimizer/optimizer.py ``multi_update``, gluon/data's device-prefetch
ring) is fast because of invariants the code cannot express in types:

* no host synchronization inside anything that traces under ``jax.jit``
  (one stray ``float(x)`` re-serializes the step);
* donated buffers are dead after the dispatch that donates them;
* executable-cache keys stay hashable and value-keyed, or every step
  silently retraces;
* the iterator rings mutate shared state only under their lock, and
  locks are always taken in one order;
* every ``MXNET_*`` escape hatch is documented in docs/ENV_VARS.md.

The multi-host pod runtime adds a harsher class — collective axes no
mesh binds, traces that diverge per host, collectives under
data-dependent branches, device-ledger entries nobody releases — each
a 64-chip hang or a silent leak instead of a stack trace.  And the
fault-tolerant runtime (supervised launch, serve deadlines, finalizer
ledger drops) adds the concurrency-contract class: wall-clock deadline
math, finalizers taking non-reentrant locks, callbacks fired under a
held lock, stranded threads, telemetry schemas drifting from the
stream (v3: TL011–TL015).

tracelint checks those invariants with ``ast`` only (no third-party
dependencies) so CI fails the moment a change reintroduces the
74.8 ms/step world.  Traced-region discovery walks a REPO-WIDE call
graph (imports, re-exports, cross-module class families — see
``project.py``), falling back to the module-local walk where an import
cannot be resolved.  Run it as::

    python -m tools.tracelint mxnet_tpu/ tools/ benchmark/ \
        [--format=json|sarif] [--jobs N] [--baseline f]

Rules (see docs/TRACELINT.md for the full catalog):

=======  ==========================================================
TL000    malformed/unjustified ``# tracelint: disable=`` comment
TL001    host sync reachable from traced code
TL002    donated buffer read after the dispatch that donates it
TL003    retrace hazard (unhashable / identity cache key, jit-in-loop)
TL004    lock-order inversion or unlocked shared-state mutation
TL005    ``MXNET_*`` env read and docs/ENV_VARS.md out of sync
TL006    collective/PartitionSpec axis not bound by any mesh
TL007    cross-host trace divergence (process id / env / time / RNG
         feeding the trace; set/id ordering feeding shardings)
TL008    collective under a data- or host-dependent branch
TL009    ``ACCOUNTANT.set`` without a reachable drop/release path
TL010    stale suppression (opt-in via ``--select TL010``)
TL011    ``time.time()`` in deadline/timeout arithmetic (NTP hazard)
TL012    lock acquisition reachable from a GC finalizer
TL013    user callback invoked while a lock is held
TL014    thread without daemon/join lifecycle; blocking ``queue.get``
         with no poison-pill wakeup
TL015    telemetry event/metric/fault-site out of sync with
         docs/TELEMETRY.md / docs/ENV_VARS.md
TL016    ``donate_argnums`` drift against the serve operand schema
         (or past the wrapped function's arity, producer-side TL002)
TL017    slot-state / meta layout hard-coded past the operand schema
TL018    serve executable call-site arity disagrees with its
         declaration
TL019    host-local value (process_index / local_devices / per-rank
         env) flows into cross-process placement construction
=======  ==========================================================

Suppress a deliberate violation with a justified comment on the same
line (or on a comment line directly above)::

    x = float(loss)  # tracelint: disable=TL001 -- epoch boundary, sync is the point
"""
from .core import RULES, Finding, run_paths  # noqa: F401

__all__ = ["RULES", "Finding", "run_paths"]
