"""tracelint — trace-discipline static analyzer for the mxnet_tpu tree.

The fused hot path (gluon/fused_step.py, gluon/block.py ``_CachedOp``,
optimizer/optimizer.py ``multi_update``, gluon/data's device-prefetch
ring) is fast because of invariants the code cannot express in types:

* no host synchronization inside anything that traces under ``jax.jit``
  (one stray ``float(x)`` re-serializes the step);
* donated buffers are dead after the dispatch that donates them;
* executable-cache keys stay hashable and value-keyed, or every step
  silently retraces;
* the iterator rings mutate shared state only under their lock, and
  locks are always taken in one order;
* every ``MXNET_*`` escape hatch is documented in docs/ENV_VARS.md.

tracelint checks those invariants with ``ast`` only (no third-party
dependencies) so CI fails the moment a change reintroduces the
74.8 ms/step world.  Run it as::

    python -m tools.tracelint mxnet_tpu/ [--format=json] [--baseline f]

Rules (see docs/TRACELINT.md for the full catalog):

=======  ==========================================================
TL000    malformed/unjustified ``# tracelint: disable=`` comment
TL001    host sync reachable from traced code
TL002    donated buffer read after the dispatch that donates it
TL003    retrace hazard (unhashable / identity cache key, jit-in-loop)
TL004    lock-order inversion or unlocked shared-state mutation
TL005    ``MXNET_*`` env read and docs/ENV_VARS.md out of sync
=======  ==========================================================

Suppress a deliberate violation with a justified comment on the same
line (or on a comment line directly above)::

    x = float(loss)  # tracelint: disable=TL001 -- epoch boundary, sync is the point
"""
from .core import RULES, Finding, run_paths  # noqa: F401

__all__ = ["RULES", "Finding", "run_paths"]
