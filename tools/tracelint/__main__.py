"""CLI: ``python -m tools.tracelint <paths> [options]``.

Exit codes: 0 = clean, 1 = findings, 2 = usage/internal error.
"""
from __future__ import annotations

import argparse
import json
import sys

from .core import (RULES, apply_baseline, load_baseline, render_sarif,
                   run_paths, write_baseline)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.tracelint",
        description="trace-discipline static analyzer (host-sync, "
                    "donation, retrace, lock-order, env-hatch checks)")
    ap.add_argument("paths", nargs="+",
                    help="python files or directories to analyze")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="sarif = SARIF 2.1.0 for CI annotations; all "
                         "formats are byte-identical across --jobs")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="ignore findings whose fingerprint is in FILE "
                         "(lets a new rule land warn-only)")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write current findings to FILE and exit 0")
    ap.add_argument("--env-docs", default=None, metavar="FILE",
                    help="override the docs/ENV_VARS.md location for "
                         "TL005/TL015 (auto-discovered by default)")
    ap.add_argument("--telemetry-docs", default=None, metavar="FILE",
                    help="override the docs/TELEMETRY.md location for "
                         "TL015 (auto-discovered by default)")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="distribute per-module rule passes over N "
                         "forked workers (identical output to serial)")
    args = ap.parse_args(argv)

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            print(f"tracelint: unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    try:
        findings = run_paths(args.paths, select=select,
                             env_docs=args.env_docs, jobs=args.jobs,
                             telemetry_docs=args.telemetry_docs)
    except FileNotFoundError as e:
        print(f"tracelint: no such path: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"tracelint: wrote {len(findings)} fingerprint(s) to "
              f"{args.write_baseline}")
        return 0
    if args.baseline:
        findings = apply_baseline(findings, load_baseline(args.baseline))

    errors = [f for f in findings if f.severity != "warn"]
    if args.format == "sarif":
        print(render_sarif(findings))
    elif args.format == "json":
        counts: dict = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "counts": counts}, indent=1))
    else:
        for f in findings:
            print(f.render())
            if f.snippet:
                print(f"    {f.snippet}")
        n, w = len(errors), len(findings) - len(errors)
        tail = f", {w} warning(s)" if w else ""
        print(f"tracelint: {n} finding(s){tail}" if findings
              else "tracelint: clean")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
