"""CLI: ``python -m tools.tracelint <paths> [options]``.

Exit codes: 0 = clean, 1 = findings, 2 = usage/internal error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import (RULES, apply_baseline, load_baseline, render_sarif,
                   run_paths, write_baseline)


def _git_changed_files():
    """Absolute paths of .py files changed vs HEAD (worktree + index)
    plus untracked ones — the ``--changed-only`` report scope.  Raises
    ``RuntimeError`` outside a git checkout."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, check=True).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        raise RuntimeError(
            f"--changed-only needs a git checkout: {e}") from e
    out = set()
    for line in (diff + untracked).splitlines():
        line = line.strip()
        if line.endswith(".py"):
            out.add(os.path.realpath(os.path.join(top, line)))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.tracelint",
        description="trace-discipline static analyzer (host-sync, "
                    "donation, retrace, lock-order, env-hatch checks)")
    ap.add_argument("paths", nargs="+",
                    help="python files or directories to analyze")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="sarif = SARIF 2.1.0 for CI annotations; all "
                         "formats are byte-identical across --jobs")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="ignore findings whose fingerprint is in FILE "
                         "(lets a new rule land warn-only)")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write current findings to FILE and exit 0")
    ap.add_argument("--env-docs", default=None, metavar="FILE",
                    help="override the docs/ENV_VARS.md location for "
                         "TL005/TL015 (auto-discovered by default)")
    ap.add_argument("--telemetry-docs", default=None, metavar="FILE",
                    help="override the docs/TELEMETRY.md location for "
                         "TL015 (auto-discovered by default)")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="distribute per-module rule passes over N "
                         "forked workers (identical output to serial)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only files changed vs git HEAD "
                         "(worktree, index, untracked) — the project "
                         "graph still spans all paths, so findings "
                         "are byte-identical to a full run filtered "
                         "to those files; the pre-commit fast path")
    args = ap.parse_args(argv)

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            print(f"tracelint: unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    only_paths = None
    if args.changed_only:
        try:
            only_paths = _git_changed_files()
        except RuntimeError as e:
            print(f"tracelint: {e}", file=sys.stderr)
            return 2

    try:
        findings = run_paths(args.paths, select=select,
                             env_docs=args.env_docs, jobs=args.jobs,
                             telemetry_docs=args.telemetry_docs,
                             only_paths=only_paths)
    except FileNotFoundError as e:
        print(f"tracelint: no such path: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"tracelint: wrote {len(findings)} fingerprint(s) to "
              f"{args.write_baseline}")
        return 0
    if args.baseline:
        findings = apply_baseline(findings, load_baseline(args.baseline))

    errors = [f for f in findings if f.severity != "warn"]
    if args.format == "sarif":
        print(render_sarif(findings))
    elif args.format == "json":
        counts: dict = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "counts": counts}, indent=1))
    else:
        for f in findings:
            print(f.render())
            if f.snippet:
                print(f"    {f.snippet}")
        n, w = len(errors), len(findings) - len(errors)
        tail = f", {w} warning(s)" if w else ""
        print(f"tracelint: {n} finding(s){tail}" if findings
              else "tracelint: clean")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
