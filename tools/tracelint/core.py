"""tracelint core: file loading, suppressions, rule driver, baselines.

Everything here is plain ``ast`` + stdlib so the linter can run in any
environment the repo runs in (CI shells it from a tier-1 test).
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize

RULES = {
    "TL000": "malformed or unjustified tracelint suppression",
    "TL001": "host sync reachable from traced code",
    "TL002": "donated buffer read after dispatch",
    "TL003": "retrace hazard in executable cache key / jit construction",
    "TL004": "lock-order inversion or unlocked shared-state mutation",
    "TL005": "MXNET_* env var out of sync with docs/ENV_VARS.md",
    "TL006": "collective/PartitionSpec axis not bound by any mesh",
    "TL007": "cross-host trace divergence (process id/env/time/RNG/"
             "set-order in traced or sharding-feeding code)",
    "TL008": "collective under a data- or host-dependent branch",
    "TL009": "ACCOUNTANT.set without a reachable drop/release path",
    "TL010": "stale suppression: disabled rule no longer fires here",
    "TL011": "wall-clock time.time() in deadline/timeout arithmetic",
    "TL012": "lock acquisition reachable from a GC finalizer",
    "TL013": "user callback invoked while holding a lock",
    "TL014": "thread without daemon/join lifecycle, or blocking "
             "queue.get with no close wakeup",
    "TL015": "telemetry event/metric/fault-site out of sync with docs",
    "TL016": "donate_argnums drift against the executable operand schema",
    "TL017": "slot-state/meta layout hard-coded past the operand schema",
    "TL018": "executable call-site arity disagrees with its declaration",
    "TL019": "host-local value flows into cross-process placement",
}

# `# tracelint: disable=TL001[,TL004] -- justification`
_SUPPRESS_RE = re.compile(
    r"#\s*tracelint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(\S.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    # "error" fails the gate; "warn" is advisory (conditionally-bound
    # axes, stale suppressions) and leaves the exit code at 0
    severity: str = "error"

    def fingerprint(self) -> str:
        """Line-number-free identity used by ``--baseline`` so findings
        survive unrelated edits above them."""
        return f"{self.rule}:{os.path.normpath(self.path)}:{self.snippet}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sev = "warning: " if self.severity == "warn" else ""
        return (f"{self.path}:{self.line}:{self.col}: {sev}{self.rule} "
                f"{self.message}")


class Module:
    """One parsed python file plus the lookups every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # import alias maps (numpy vs jax.numpy matters for TL001)
        self.np_aliases: set = set()
        self.jnp_aliases: set = set()
        self.jax_aliases: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_aliases.add(a.asname or "numpy")
                    elif a.name == "jax.numpy":
                        self.jnp_aliases.add(a.asname or "jax")
                    elif a.name == "jax" or a.name.startswith("jax."):
                        self.jax_aliases.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp_aliases.add(a.asname or "numpy")
        self.suppressions = self._parse_suppressions()

    # -- suppressions ----------------------------------------------------- #
    def _parse_suppressions(self):
        """Map line number -> (rule-id set, justification or None).

        A suppression on a code line covers that line; a whole-line
        comment covers the next line (for statements too long to carry
        the comment inline).  Real COMMENT tokens only — the marker
        inside a string literal (an error message quoting the syntax, a
        docstring example) is not a suppression.
        """
        out: dict = {}
        if "tracelint" not in self.source:
            return out  # fast path: no marker, no tokenize pass
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            return out  # ast parsed but tokenize balked: no suppressions
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2)
            line = tok.start[0]
            whole_line = not self.lines[line - 1][:tok.start[1]].strip()
            out[line + 1 if whole_line else line] = (rules, reason, line)
        return out

    def suppressed(self, finding: Finding):
        """None if not suppressed, else the (rules, reason, line) entry."""
        entry = self.suppressions.get(finding.line)
        if entry and finding.rule in entry[0]:
            return entry
        return None

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def collect_py_files(paths):
    files = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return files


def load_modules(files):
    """Parse files; unparsable ones become findings rather than crashes
    (a syntax error in the audited tree must fail the gate loudly)."""
    modules, findings = [], []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            modules.append(Module(path, source))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding("TL000", path,
                                    getattr(e, "lineno", 0) or 0, 0,
                                    f"could not analyze file: {e}"))
    return modules, findings


def find_repo_docs(paths, explicit=None, name="ENV_VARS.md"):
    """Locate docs/<name> by walking up from the scanned paths."""
    if explicit:
        return explicit if os.path.isfile(explicit) else None
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p))
        while True:
            cand = os.path.join(d, "docs", name)
            if os.path.isfile(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def _validate_suppressions(module: Module):
    """TL000: every suppression needs known rule ids and a justification
    after ``--`` (an unexplained disable is itself a finding, and the
    suppression does not take effect — enforced by emitting TL000 here
    while rules keep reporting through reasonless entries)."""
    out = []
    for target, (rules, reason, line) in module.suppressions.items():
        bad = [r for r in rules if r not in RULES]
        if bad:
            out.append(Finding(
                "TL000", module.path, line, 0,
                f"unknown rule id(s) {','.join(sorted(bad))} in suppression",
                module.snippet(line)))
        if not reason:
            out.append(Finding(
                "TL000", module.path, line, 0,
                "suppression without justification: write "
                "'# tracelint: disable=TLxxx -- <why this is deliberate>'",
                module.snippet(line)))
    return out


def _module_findings(project, shared, module):
    """Every per-module rule pass over one module (the unit of work
    ``--jobs`` distributes)."""
    from . import (rules_contract, rules_runtime, rules_sharding,
                   rules_threading, rules_trace)

    out = list(_validate_suppressions(module))
    out.extend(rules_trace.check_module(project, module))
    out.extend(rules_threading.check_module(shared, module))
    out.extend(rules_sharding.check_module(project, shared, module))
    out.extend(rules_runtime.check_module(project, shared, module))
    out.extend(rules_contract.check_module(project, shared, module))
    return out


# worker context for --jobs: set in the parent immediately before the
# fork so children inherit the fully-built project (parse + call graph
# happen ONCE, in the parent; only rule execution is distributed)
_WORKER_CTX = None


def _lint_one(path):
    project, shared = _WORKER_CTX
    return _module_findings(project, shared, project.by_path[path])


def _run_modules(project, shared, modules, jobs):
    """Per-module findings, serial or via a fork pool.  The parallel
    path returns byte-identical results to the serial one: workers see
    the same pre-built project, ``map`` preserves submission order, and
    the caller sorts regardless."""
    if jobs and jobs > 1 and len(modules) > 1:
        import multiprocessing as mp
        try:
            ctx = mp.get_context("fork")
        except ValueError:  # pragma: no cover - non-posix fallback
            ctx = None
        if ctx is not None:
            global _WORKER_CTX
            _WORKER_CTX = (project, shared)
            try:
                with ctx.Pool(min(jobs, len(modules))) as pool:
                    chunks = pool.map(
                        _lint_one, [m.path for m in modules],
                        chunksize=max(1, len(modules) // (jobs * 4)))
            finally:
                _WORKER_CTX = None
            return [f for chunk in chunks for f in chunk]
    out = []
    for m in modules:
        out.extend(_module_findings(project, shared, m))
    return out


def _unused_suppressions(modules, findings):
    """TL010: a justified ``disable=TLxxx`` whose rule produced no
    finding on its line is stale — it documents a hazard that no longer
    exists and would silently mask the next real one.  Warn-level and
    ``--select TL010`` opt-in (run_paths drops it otherwise)."""
    hits = {(f.path, f.line, f.rule) for f in findings}
    out = []
    for m in modules:
        for target, (rules, reason, line) in sorted(
                m.suppressions.items()):
            if not reason:
                continue  # reasonless: already a TL000
            for r in sorted(rules):
                if r in RULES and r != "TL010" and \
                        (m.path, target, r) not in hits:
                    out.append(Finding(
                        "TL010", m.path, line, 0,
                        f"suppression for {r} matches no {r} finding on "
                        "its line — stale; delete it so a future "
                        "regression here is not silently masked",
                        snippet=m.snippet(line), severity="warn"))
    return out


def run_paths(paths, select=None, env_docs=None, jobs=None,
              telemetry_docs=None, only_paths=None):
    """Run every rule over ``paths``; returns the surviving findings.

    ``select`` restricts to an iterable of rule ids (and is the opt-in
    for TL010).  ``jobs`` > 1 distributes per-module rule execution
    over a fork pool — output is identical to the serial run.
    Suppressions with a justification remove matching findings;
    reasonless suppressions do not (and raise TL000 themselves).

    ``only_paths`` (an iterable of file paths, e.g. the git-changed
    set behind ``--changed-only``) restricts the REPORT to those
    files while the project graph — imports, traced discovery,
    mesh-axis vocabulary, the operand-schema registry — is still
    built over all of ``paths``, so the surviving findings are
    byte-identical to a full run filtered to the same files.  Only
    the per-module rule passes are skipped for unreported modules;
    project-level passes always run in full (they are the cheap
    part, and their findings cross files).
    """
    from . import rules_env, rules_runtime
    from .project import Project
    from .rules_contract import find_registry
    from .rules_sharding import build_state

    files = collect_py_files(paths)
    modules, findings = load_modules(files)
    mod_by_path = {m.path: m for m in modules}

    project = Project(modules)
    shared = build_state(project)
    find_registry(project)  # memoize pre-fork: workers inherit it
    if only_paths is None:
        active = modules
    else:
        keep_paths = {os.path.realpath(p) for p in only_paths}
        active = [m for m in modules
                  if os.path.realpath(m.path) in keep_paths]
    findings.extend(_run_modules(project, shared, active, jobs))
    docs = find_repo_docs(paths, env_docs)
    tele = find_repo_docs(paths, telemetry_docs, name="TELEMETRY.md")
    # one repo scan per distinct docs ROOT: the stale directions must
    # be judged against the tree that owns each docs file (an
    # --env-docs override pointing elsewhere must not blind the
    # TELEMETRY.md reconciliation to the real repo, or vice versa)
    parsed = {os.path.abspath(m.path): m.tree for m in modules}
    scans = {}

    def _aux_for(doc_path):
        if doc_path is None:
            return None
        root = os.path.dirname(os.path.dirname(os.path.abspath(doc_path)))
        if root not in scans:
            scans[root] = rules_env.repo_scan(root, parsed)
        return scans[root]

    findings.extend(rules_env.check(modules, docs, _aux_for(docs)))
    findings.extend(rules_runtime.check_contract(
        modules, tele, docs, _aux_for(tele), _aux_for(docs)))
    findings.extend(rules_runtime.check_project(project, shared))
    findings.extend(_unused_suppressions(active, findings))

    if only_paths is not None:
        keep_paths = {os.path.realpath(p) for p in only_paths}
        findings = [f for f in findings
                    if os.path.realpath(f.path) in keep_paths]
    if select:
        keep = set(select)
        findings = [f for f in findings if f.rule in keep]
    else:
        findings = [f for f in findings if f.rule != "TL010"]

    out = []
    for f in findings:
        if not f.snippet:
            m = mod_by_path.get(f.path)
            if m is not None:
                f.snippet = m.snippet(f.line)
        m = mod_by_path.get(f.path)
        if m is not None and f.rule != "TL000":
            entry = m.suppressed(f)
            if entry and entry[1]:  # justified suppression
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# -- SARIF output -------------------------------------------------------- #

def render_sarif(findings):
    """SARIF 2.1.0 for CI annotation surfaces (GitHub code scanning et
    al.).  Deterministic: findings arrive sorted from run_paths and the
    rule table is emitted in id order, so serial and ``--jobs`` runs
    produce byte-identical documents."""
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "warning" if f.severity == "warn" else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": os.path.normpath(f.path).replace(
                            os.sep, "/")},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col, 0) + 1},
                },
            }],
        })
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tracelint",
                "rules": [{"id": rid,
                           "shortDescription": {"text": desc}}
                          for rid, desc in sorted(RULES.items())],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True)


# -- baseline ----------------------------------------------------------- #

def load_baseline(path):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return set(data.get("fingerprints", []))
    except (OSError, ValueError, AttributeError):
        print(f"tracelint: could not read baseline {path}", file=sys.stderr)
        return set()


def write_baseline(path, findings):
    data = {"fingerprints": sorted({f.fingerprint() for f in findings})}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings, baseline):
    return [f for f in findings if f.fingerprint() not in baseline]
