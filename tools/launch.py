#!/usr/bin/env python3
"""Distributed job launcher (reference ``tools/launch.py`` +
``dmlc_tracker``; SURVEY.md §4.4, L10).

Reference protocol: start a scheduler, then ssh/local-exec N workers and S
servers with ``DMLC_*`` env vars pointing at it.

TPU-native protocol: there are no server/scheduler roles — one process per
host joins a ``jax.distributed`` group via a coordinator address.  This
launcher keeps the reference CLI shape::

    python tools/launch.py -n 4 --launcher local  python train.py ...
    python tools/launch.py -n 4 --launcher ssh -H hosts  python train.py ...

and sets, for each rank:

    MXNET_COORDINATOR       host:port of rank 0 (feeds
                            jax.distributed.initialize; read by
                            mxnet_tpu.parallel.init_distributed)
    MXNET_NUM_WORKERS       total ranks
    MXNET_WORKER_ID         this rank
    DMLC_ROLE=worker        reference compat (server/scheduler ranks can be
                            requested with -s but are deprecated no-ops)
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rank_env(args, coordinator, rank):
    env = dict(os.environ)
    env.update({
        "MXNET_COORDINATOR": coordinator,
        "MXNET_NUM_WORKERS": str(args.num_workers),
        "MXNET_WORKER_ID": str(rank),
        # reference-compatible names (SURVEY.md §4.4 env protocol)
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_PS_ROOT_URI": coordinator.split(":")[0],
        "DMLC_PS_ROOT_PORT": coordinator.split(":")[1],
    })
    return env


def launch_local(args, command):
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(args.num_workers):
        env = _rank_env(args, coordinator, rank)
        if args.dry_run:
            kv = " ".join(f"{k}={env[k]}" for k in sorted(env)
                          if k.startswith(("MXNET_", "DMLC")))
            print(f"[rank {rank}] {kv} {' '.join(command)}")
            continue
        procs.append(subprocess.Popen(command, env=env))
    if args.dry_run:
        return 0
    code = 0

    def _kill_all(*_a):
        for p in procs:
            p.terminate()

    signal.signal(signal.SIGINT, _kill_all)
    for p in procs:
        p.wait()
        code = code or p.returncode
    return code


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        print(f"hostfile has {len(hosts)} hosts < -n {args.num_workers}",
              file=sys.stderr)
        return 1
    coordinator = f"{hosts[0]}:{args.port or _free_port()}"
    procs = []
    for rank in range(args.num_workers):
        env = _rank_env(args, coordinator, rank)
        exports = " ".join(
            f"{k}={shlex.quote(env[k])}" for k in sorted(env)
            if k.startswith(("MXNET_", "DMLC")))
        remote_cmd = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
            " ".join(shlex.quote(c) for c in command)
        full = ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank],
                remote_cmd]
        if args.dry_run:
            print(f"[rank {rank}] {' '.join(full)}")
            continue
        procs.append(subprocess.Popen(full))
    if args.dry_run:
        return 0
    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    return code


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job "
                    "(reference tools/launch.py workalike)")
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes (one per host)")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="[deprecated] PS server count; servers are "
                             "no-ops on TPU (XLA collectives)")
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for --launcher ssh")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (ssh mode)")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the per-rank commands without running")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("missing training command")
    if args.num_servers:
        print("note: -s/--num-servers is a no-op on TPU (parameter-server "
              "roles are subsumed by XLA collectives)", file=sys.stderr)
    if args.launcher == "ssh":
        if not args.hostfile:
            parser.error("--launcher ssh requires -H/--hostfile")
        return launch_ssh(args, args.command)
    return launch_local(args, args.command)


if __name__ == "__main__":
    sys.exit(main())
